"""Quorum high-water-mark checkpoint file — HWM persistence across
remount (iotml.replication's durable half, kept store-side per R9).

Kafka persists each partition's high water mark in a
``replication-offset-checkpoint`` file so a restarted broker knows how
far the quorum had committed before the crash.  The rebuild's analog:
one small JSON document per store dir mapping ``"topic:partition"`` to
the quorum HWM, written through the store's own ``atomic_write`` (R9:
every byte under a store dir has one writer package).

Semantics on remount: crash recovery may resurrect records PAST the
persisted HWM (appended by the old leader, never quorum-acked).  They
are not truncated — the log keeps them — but the replication layer
re-anchors its fetch ceiling at the persisted mark, so consumers cannot
read the un-replicated tail until followers have actually mirrored it
and the quorum HWM advances past it again.  A torn/corrupt checkpoint
degrades to "no checkpoint" (the ceiling re-anchors at the log end,
the pre-replication behavior) rather than refusing to mount.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

from . import segment as seg

_FILENAME = "replication-hwm.json"


class HwmFile:
    """Atomic-rewrite checkpoint of per-partition quorum HWMs.

    Not thread-safe by itself: the one caller is the replication
    state's persist path, which already serializes stores (and never
    writes under its tracking lock — file I/O stays off the quorum
    wait path)."""

    def __init__(self, store_dir: str):
        self.path = os.path.join(store_dir, _FILENAME)

    def load(self) -> Dict[Tuple[str, int], int]:
        """{(topic, partition): hwm} from the checkpoint; empty when
        absent or torn (degrade to no-checkpoint, never refuse)."""
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {}
        out: Dict[Tuple[str, int], int] = {}
        for key, v in doc.get("hwm", {}).items():
            topic, _, part = key.rpartition(":")
            try:
                out[(topic, int(part))] = int(v)
            except ValueError:
                continue  # one malformed row never poisons the rest
        return out

    def store(self, hwms: Dict[Tuple[str, int], int]) -> None:
        """Persist the full map (tmp + rename + fsync — the same
        publication discipline as the topic manifest)."""
        doc = {"hwm": {f"{t}:{p}": int(v)
                       for (t, p), v in sorted(hwms.items())}}
        blob = json.dumps(doc, sort_keys=True).encode()
        seg.atomic_write(self.path, blob)


def hwm_file_for(store_dir: Optional[str]) -> Optional[HwmFile]:
    """The store-dir's HWM checkpoint handle (None for in-memory
    brokers — nothing survives the process anyway)."""
    return HwmFile(store_dir) if store_dir else None
