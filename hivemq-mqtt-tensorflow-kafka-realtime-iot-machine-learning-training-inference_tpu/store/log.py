"""Append-only segmented log with crash recovery and timestamp replay.

The durable partition: what `stream.broker._Partition` keeps in a Python
list, on disk — so "training straight from the commit log, no data lake"
(README §'no data lake', the paper's load-bearing claim) survives a
process death instead of dying with it.

Layout of one partition directory::

    <dir>/00000000000000000000.log        records (segment.py frame)
    <dir>/00000000000000000000.index      sparse offset index (sealed)
    <dir>/00000000000000000000.timeindex  timestamp index (sealed)
    <dir>/00000000000000000123.log        ... next segment, named by its
                                          base offset (Kafka's layout)

The highest-named segment is ACTIVE (appends go there); all others are
sealed.  Sealed segments carry size-stamped sidecar indexes, trusted at
mount only when the stamp matches the log file exactly (so restart cost
is O(tail), not O(total retained bytes)); the active segment's indexes
live in memory and its sidecars are written at roll.  A sidecar that is
missing or disagrees with its log is ignored and the index rebuilt from
the log — the log is the only ground truth (the index/log-mismatch
recovery test pins this).

Recovery (mount time): every segment is CRC-scanned; the first torn or
corrupt frame in the TAIL segment truncates the file there (the
expected artifact of dying mid-write) and the dropped bytes are counted
in ``iotml_store_recovery_truncated_bytes``.  A sealed segment with a
bad frame is truncated the same way — later segments' records are
still served (their frames are self-describing), which keeps recovery
monotone: nothing valid is ever dropped.

Retention is segment-granular (delete whole sealed segments), by total
bytes and by age against the newest record timestamp — the reference's
``retention.ms`` analog (its topics ran retention.ms=100000,
reference 01_installConfluentPlatform.sh:180-183).

Thread-safety: none here.  The broker serializes every call under its
own lock, exactly as it does for the in-memory list.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics
from . import segment as seg
from .segment import SegmentWriter

store_segment_bytes = obs_metrics.default_registry.gauge(
    "iotml_store_segment_bytes",
    "on-disk bytes per durable partition (all segments)")
store_recovery_truncated = obs_metrics.default_registry.counter(
    "iotml_store_recovery_truncated_bytes",
    "bytes of torn/corrupt tail dropped by crash recovery")
store_replay_records = obs_metrics.default_registry.counter(
    "iotml_store_replay_records_total",
    "records served by the replay API (read_from / read_since)")

_LOG_SUFFIX = ".log"
_IDX_SUFFIX = ".index"
_TIDX_SUFFIX = ".timeindex"


def _seg_name(base_offset: int) -> str:
    return f"{base_offset:020d}"


class StorePolicy:
    """Per-log knobs (the `store.*` config section, minus the dir)."""

    def __init__(self, fsync: str = "interval",
                 fsync_interval_s: float = 0.05,
                 segment_bytes: int = 16 * 1024 * 1024,
                 segment_age_s: float = 0.0,
                 retention_bytes: int = 0,
                 retention_ms: int = 0,
                 retention_messages: int = 0,
                 index_interval_bytes: int = 4096,
                 compact_min_dirty_ratio: float = 0.5,
                 compact_grace_ms: int = 60_000,
                 compact_interval_s: float = 5.0):
        if fsync not in ("never", "interval", "always"):
            raise ValueError(f"fsync policy must be never|interval|always, "
                             f"got {fsync!r}")
        self.fsync = fsync
        self.fsync_interval_s = float(fsync_interval_s)
        self.segment_bytes = int(segment_bytes)
        self.segment_age_s = float(segment_age_s)
        self.retention_bytes = int(retention_bytes)
        self.retention_ms = int(retention_ms)
        self.retention_messages = int(retention_messages)
        self.index_interval_bytes = int(index_interval_bytes)
        #: compaction trigger (min.cleanable.dirty.ratio) and tombstone
        #: retention (delete.retention.ms against the newest record ts)
        #: for cleanup.policy=compact topics
        self.compact_min_dirty_ratio = float(compact_min_dirty_ratio)
        self.compact_grace_ms = int(compact_grace_ms)
        #: background StoreCompactor cadence (the platform's thread)
        self.compact_interval_s = float(compact_interval_s)

    @classmethod
    def from_config(cls, store_cfg) -> "StorePolicy":
        """Build from the `store.*` config section (config.StoreConfig)."""
        return cls(fsync=store_cfg.fsync,
                   fsync_interval_s=store_cfg.fsync_interval_s,
                   segment_bytes=store_cfg.segment_bytes,
                   segment_age_s=store_cfg.segment_age_s,
                   retention_bytes=store_cfg.retention_bytes,
                   retention_ms=store_cfg.retention_ms,
                   retention_messages=getattr(store_cfg,
                                              "retention_messages", 0),
                   index_interval_bytes=store_cfg.index_interval_bytes,
                   compact_min_dirty_ratio=getattr(
                       store_cfg, "compact_min_dirty_ratio", 0.5),
                   compact_grace_ms=getattr(store_cfg,
                                            "compact_grace_ms", 60_000),
                   compact_interval_s=getattr(store_cfg,
                                              "compact_interval_s", 5.0))


class _Segment:
    """One sealed-or-active segment and its in-memory indexes."""

    __slots__ = ("base_offset", "path", "size", "next_offset",
                 "index", "timeindex", "max_ts")

    def __init__(self, base_offset: int, path: str):
        self.base_offset = base_offset
        self.path = path
        self.size = 0
        self.next_offset = base_offset
        #: sparse [(offset, file_pos)] — one entry per index_interval_bytes
        self.index: List[Tuple[int, int]] = []
        #: [(timestamp_ms, offset)] — appended when ts advances
        self.timeindex: List[Tuple[int, int]] = []
        self.max_ts = -1


class SegmentedLog:
    """One partition's durable log.  See the module docstring."""

    def __init__(self, dir: str, policy: Optional[StorePolicy] = None,
                 metric_labels: Optional[dict] = None):
        self.dir = dir
        self.policy = policy or StorePolicy()
        # mounted logs label by topic/partition (store/mount.py); a BARE
        # construction gets the unlabeled series — labeling by the raw
        # directory path was a cardinality leak (one series per tmp dir,
        # forever), exactly the class the closed-vocabulary test rejects
        self._labels = metric_labels or {}
        os.makedirs(dir, exist_ok=True)
        self._segments: List[_Segment] = []
        self._writer: Optional[SegmentWriter] = None
        self._last_fsync = time.monotonic()
        self._active_opened = time.monotonic()
        self.recovered_truncated_bytes = 0
        self._total_bytes = 0  # maintained incrementally (gauge hot path)
        #: offset frontier of the last compaction pass (compact.py):
        #: sealed segments wholly below it are "clean" for the dirty-
        #: ratio trigger.  Not persisted — a remount re-compacts at
        #: worst (idempotent), never under-compacts silently.
        self._clean_through = 0
        self._recover()

    # ---------------------------------------------------------- recovery
    def _recover(self) -> None:
        from .compact import sweep_cleaned

        # a compaction pass killed before its swap leaves a `.cleaned`
        # rewrite tmp beside the live segment; the live segment is still
        # the truth, the tmp is dead weight
        sweep_cleaned(self.dir)
        names = sorted(n for n in os.listdir(self.dir)
                       if n.endswith(_LOG_SUFFIX))
        for i, name in enumerate(names):
            base = int(name[:-len(_LOG_SUFFIX)])
            path = os.path.join(self.dir, name)
            s = None
            if i + 1 < len(names):
                # sealed segment: its size-stamped sidecars, when they
                # agree with the file, replace the full CRC scan — this
                # is what keeps mount time O(tail), not O(total retained
                # bytes).  Any disagreement falls back to the scan.
                nxt_base = int(names[i + 1][:-len(_LOG_SUFFIX)])
                s = self._load_sealed(base, path, nxt_base)
            if s is None:
                s = self._scan_segment(base, path)
            if s.next_offset == base and self._segments:
                # an empty tail segment (crashed right after a roll):
                # drop the file, the previous segment resumes as active
                os.remove(path)
                self._remove_sidecars(base)
                continue
            self._segments.append(s)
        if not self._segments:
            self._segments.append(
                _Segment(0, os.path.join(self.dir, _seg_name(0) + _LOG_SUFFIX)))
        self._total_bytes = sum(s.size for s in self._segments)
        self._open_writer()
        self._persist_sidecars()  # sealed segments re-publish clean indexes
        self._update_size_gauge()

    def _scan_segment(self, base: int, path: str) -> _Segment:
        """Full CRC scan of one segment: rebuild indexes from the log
        (the only ground truth) and truncate the first torn/corrupt
        frame.  A truncated SEALED segment's sidecars are removed so the
        stale ones can never shadow the truncation."""
        s = _Segment(base, path)
        data = seg.read_file(path)
        valid_end = 0
        for pos, end, off, _k, _v, ts, _h in seg.scan_records(data):
            if not s.index or pos - s.index[-1][1] >= \
                    self.policy.index_interval_bytes:
                s.index.append((off, pos))
            if ts > s.max_ts:
                s.timeindex.append((ts, off))
                s.max_ts = ts
            s.next_offset = off + 1
            valid_end = end
        if valid_end < len(data):
            torn = len(data) - valid_end
            self.recovered_truncated_bytes += torn
            store_recovery_truncated.inc(torn)
            w = SegmentWriter(path, fsync=self.policy.fsync)
            w.truncate_to(valid_end)
            w.close(sync=self.policy.fsync != "never")
            self._remove_sidecars(base)
        s.size = valid_end
        return s

    def _load_sealed(self, base: int, path: str,
                     next_base: int) -> Optional[_Segment]:
        """Build a sealed segment from its sidecars without scanning the
        log.  Returns None (→ full scan) unless BOTH sidecars exist,
        parse, and their stamped log size matches the file exactly."""
        import struct

        try:
            size = os.path.getsize(path)
            s = _Segment(base, path)
            s.size = size
            s.next_offset = next_base  # the roll invariant for sealed
            for suffix, target in ((_IDX_SUFFIX, s.index),
                                   (_TIDX_SUFFIX, s.timeindex)):
                p = os.path.join(self.dir, _seg_name(base) + suffix)
                blob = seg.read_file(p)
                (stamped,) = struct.unpack_from(">q", blob, 0)
                if stamped != size or (len(blob) - 8) % 16:
                    return None
                for off in range(8, len(blob), 16):
                    target.append(struct.unpack_from(">qq", blob, off))
            s.max_ts = s.timeindex[-1][0] if s.timeindex else -1
            return s
        except (OSError, struct.error):
            return None

    def _open_writer(self) -> None:
        active = self._segments[-1]
        self._writer = SegmentWriter(active.path, fsync=self.policy.fsync)
        self._active_opened = time.monotonic()

    def _remove_sidecars(self, base: int) -> None:
        for suffix in (_IDX_SUFFIX, _TIDX_SUFFIX):
            p = os.path.join(self.dir, _seg_name(base) + suffix)
            if os.path.exists(p):
                os.remove(p)

    def _persist_sidecars(self) -> None:
        """Write index sidecars for every SEALED segment that lacks
        them.  Format: ``>q`` stamped log size, then ``>qq`` entries —
        the stamp is the mount-time trust check (`_load_sealed`): a
        sidecar that disagrees with its log's size is ignored and the
        log rescanned, so sidecars can accelerate recovery but never
        override the log."""
        import struct

        for s in self._segments[:-1]:
            head = struct.pack(">q", s.size)
            p = os.path.join(self.dir, _seg_name(s.base_offset) + _IDX_SUFFIX)
            if not os.path.exists(p):
                blob = head + b"".join(struct.pack(">qq", o, pos)
                                       for o, pos in s.index)
                seg.atomic_write(p, blob, fsync=self.policy.fsync == "always")
            p = os.path.join(self.dir, _seg_name(s.base_offset) + _TIDX_SUFFIX)
            if not os.path.exists(p):
                blob = head + b"".join(struct.pack(">qq", ts, o)
                                       for ts, o in s.timeindex)
                seg.atomic_write(p, blob, fsync=self.policy.fsync == "always")

    # ------------------------------------------------------------- state
    @property
    def base_offset(self) -> int:
        return self._segments[0].base_offset

    @property
    def end_offset(self) -> int:
        return self._segments[-1].next_offset

    def __len__(self) -> int:
        return self.end_offset - self.base_offset

    def total_bytes(self) -> int:
        return self._total_bytes

    def _update_size_gauge(self) -> None:
        store_segment_bytes.set(self.total_bytes(), **self._labels)

    @property
    def active_path(self) -> str:
        return self._segments[-1].path

    # ------------------------------------------------------------ append
    def append(self, key: Optional[bytes], value: bytes, timestamp_ms: int,
               headers: Optional[tuple] = None, sync: bool = True) -> int:
        """Append one record; under ``fsync=always`` the record is
        durable when this returns.  ``sync=False`` defers the fsync to a
        caller-owned ``sync_batch()`` — how a bulk produce acks once per
        batch instead of once per record (the ack still happens after
        the sync, so acked⇒durable is intact)."""
        self._maybe_roll()
        active = self._segments[-1]
        off = active.next_offset
        frame = seg.encode_record(off, key, value, timestamp_ms, headers)
        pos = self._writer.append(frame)
        if not active.index or pos - active.index[-1][1] >= \
                self.policy.index_interval_bytes:
            active.index.append((off, pos))
        if timestamp_ms > active.max_ts:
            active.timeindex.append((timestamp_ms, off))
            active.max_ts = timestamp_ms
        active.next_offset = off + 1
        active.size += len(frame)
        self._total_bytes += len(frame)
        if self.policy.fsync == "always":
            if sync:
                self._writer.sync()
            self._last_fsync = time.monotonic()
        elif self.policy.fsync == "interval":
            now = time.monotonic()
            if now - self._last_fsync >= self.policy.fsync_interval_s:
                self._writer.sync()
                self._last_fsync = now
        self._update_size_gauge()
        return off

    def append_at(self, offset: int, key: Optional[bytes], value,
                  timestamp_ms: int, headers: Optional[tuple] = None,
                  sync: bool = True) -> int:
        """Append one record AT an explicit offset at/after the log end —
        the replica's mirror path for COMPACTED topics, whose fetches
        carry offset holes (compaction punched out shadowed records).
        Appending them contiguously would renumber the survivors and
        silently break the offsets-identical failover contract; jumping
        the active segment's next_offset forward reproduces the hole."""
        offset = int(offset)
        end = self.end_offset
        if offset < end:
            raise ValueError(f"append_at({offset}) behind log end {end}: "
                             f"offsets only move forward")
        if offset > end:
            self._segments[-1].next_offset = offset
        return self.append(key, value, timestamp_ms, headers, sync=sync)

    def append_raw(self, blob: bytes, count: int, first_offset: int,
                   last_offset: int, max_ts: int,
                   sync: bool = True) -> int:
        """Append a VALIDATED raw frame batch verbatim — the zero-copy
        write path (RAW_PRODUCE landing, replica mirror leg, fused
        produce_many framing): the batch's bytes become the segment's
        bytes in one write, no per-record re-serialisation.  The caller
        (Broker) has already CRC-validated the whole batch and stamped
        the offsets; ``first_offset`` past the log end reproduces an
        offset hole (the compacted-mirror case), exactly like
        ``append_at``.  Indexing is batch-granular: one sparse-index
        candidate at the batch head and one timeindex entry at the
        batch's max timestamp — both are conservative lower bounds, so
        reads only ever start earlier, never skip records."""
        if count <= 0:
            return first_offset
        self._maybe_roll()
        active = self._segments[-1]
        if first_offset < active.next_offset:
            raise ValueError(
                f"append_raw({first_offset}) behind log end "
                f"{active.next_offset}: offsets only move forward")
        pos = self._writer.append(blob)
        if not active.index or pos - active.index[-1][1] >= \
                self.policy.index_interval_bytes:
            active.index.append((first_offset, pos))
        if max_ts > active.max_ts:
            active.timeindex.append((max_ts, first_offset))
            active.max_ts = max_ts
        active.next_offset = last_offset + 1
        active.size += len(blob)
        self._total_bytes += len(blob)
        if self.policy.fsync == "always":
            if sync:
                self._writer.sync()
            self._last_fsync = time.monotonic()
        elif self.policy.fsync == "interval":
            now = time.monotonic()
            if now - self._last_fsync >= self.policy.fsync_interval_s:
                self._writer.sync()
                self._last_fsync = now
        self._update_size_gauge()
        return first_offset

    def sync_batch(self) -> None:
        """The deferred half of ``append(sync=False)`` under
        ``fsync=always``; cheap no-op otherwise."""
        if self.policy.fsync == "always":
            self._writer.sync()

    def _maybe_roll(self) -> None:
        active = self._segments[-1]
        if active.size == 0:
            return
        age = time.monotonic() - self._active_opened
        if active.size >= self.policy.segment_bytes or (
                self.policy.segment_age_s
                and age >= self.policy.segment_age_s):
            self.roll()

    def roll(self) -> None:
        """Seal the active segment and start a new one at end_offset."""
        active = self._segments[-1]
        if active.size == 0:
            return
        self._writer.close(sync=self.policy.fsync != "never")
        base = active.next_offset
        s = _Segment(base, os.path.join(self.dir,
                                        _seg_name(base) + _LOG_SUFFIX))
        self._segments.append(s)
        self._open_writer()
        self._persist_sidecars()

    def flush(self, sync: bool = True) -> None:
        w = self._writer  # readers flush lock-free; a roll may swap it
        if w is not None:
            try:
                if sync and self.policy.fsync != "never":
                    w.sync()
                else:
                    w.flush()
            except ValueError:
                pass  # closed mid-roll by the appender — the roll's own
                # close() flushed everything this reader needed

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close(sync=self.policy.fsync != "never")
            self._writer = None

    # -------------------------------------------------------------- read
    @staticmethod
    def _segment_for(segments: List[_Segment],
                     offset: int) -> Optional[_Segment]:
        lo, hi = 0, len(segments) - 1
        ans = None
        while lo <= hi:
            mid = (lo + hi) // 2
            if segments[mid].base_offset <= offset:
                ans = segments[mid]
                lo = mid + 1
            else:
                hi = mid - 1
        return ans

    def read_from(self, offset: int, max_records: int = 1024,
                  _count_replay: bool = False) -> List[tuple]:
        """Records from `offset` (inclusive), at most `max_records`:
        [(offset, key, value, timestamp_ms, headers)].  Raises
        LookupError when `offset` is below the retained base — the
        caller (broker fetch) maps it to its own out-of-range signal.

        Safe to call WITHOUT the broker lock: the segment list is
        snapshotted, appends only grow files (a torn in-flight frame
        parks the scan exactly like a crash artifact would), and a
        segment deleted by concurrent retention reads as trimmed
        history (skipped), never an error."""
        if offset < self.base_offset:
            raise LookupError(
                f"offset {offset} below retained base {self.base_offset}")
        out: List[tuple] = []
        self.flush(sync=False)  # reads see every append, fsync'd or not
        segments = list(self._segments)  # snapshot vs concurrent roll/trim
        end = segments[-1].next_offset
        while len(out) < max_records and offset < end:
            s = self._segment_for(segments, offset)
            if s is None:
                break
            if offset >= s.next_offset:
                # a recovery-truncated SEALED segment leaves an offset
                # hole before its successor's base; jump it — the
                # monotone-recovery promise is that every intact later
                # record still serves, never that a reader stalls at
                # the hole forever.  But only at the START of a batch:
                # a returned batch must never hide a gap mid-list (the
                # replica's realignment check reads msgs[0].offset only,
                # so an internal gap would be mirrored contiguously and
                # shift every later offset in the follower's log)
                if out:
                    break
                nxt = [x for x in segments if x.base_offset > offset]
                if not nxt:
                    break
                offset = nxt[0].base_offset
                continue
            start_pos = 0
            for o, pos in reversed(s.index):
                if o <= offset:
                    start_pos = pos
                    break
            # bounded, streaming I/O: seek to the sparse-index position
            # and decode in chunks, stopping at max_records — neither a
            # 16MB active segment per poll nor read-to-EOF per
            # sequential-replay round
            filled = False
            scanned_to = start_pos
            try:
                for _pos, _end, off, key, value, ts, hdrs in \
                        seg.iter_frames(s.path, start_pos):
                    scanned_to = _end
                    if off < offset:
                        continue
                    out.append((off, key, value, ts, hdrs))
                    offset = off + 1
                    if len(out) >= max_records:
                        filled = True
                        break
            except FileNotFoundError:
                # retention deleted it mid-read: trimmed history.  Stop
                # if records were already collected — same no-mid-batch-
                # gap rule as the hole jump above
                if out:
                    break
            if not filled:
                if scanned_to < s.size and out:
                    # the scan stopped at a CORRUPT frame mid-segment
                    # (sidecar-trusted mount discovers corruption at
                    # read time): end the batch here so the skipped
                    # region starts the next batch, never hides inside
                    # this one
                    break
                offset = s.next_offset  # exhausted this segment; next one
        if _count_replay and out:
            store_replay_records.inc(len(out))
        return out

    def read_raw(self, offset: int, max_bytes: int = 1 << 20
                 ) -> Optional[Tuple[bytes, int]]:
        """RAW frame bytes from `offset` — the zero-copy read: one
        bounded pread of the owning segment, NO per-record parsing (the
        caller's columnar decoder walks the frames).  Returns
        ``(frame_bytes, aligned_start_offset)`` or None at/after the log
        end; raises LookupError below the retained base (broker fetch
        maps it to its out-of-range signal).

        The returned range starts at the sparse-index position at/before
        `offset` (leading frames are skipped by the decoder via their
        self-describing offsets) and may end mid-frame (the decoder
        treats the torn tail exactly like crash recovery: batch ends
        there, the next poll resumes).  Safe without the broker lock for
        the same reasons as ``read_from``: the segment list is
        snapshotted, appends only grow files, and a concurrent trim
        surfaces as FileNotFoundError → trimmed history."""
        if offset < self.base_offset:
            raise LookupError(
                f"offset {offset} below retained base {self.base_offset}")
        self.flush(sync=False)  # raw reads see every append too
        segments = list(self._segments)
        end = segments[-1].next_offset
        if offset >= end:
            return None
        s = self._segment_for(segments, offset)
        start_pos = 0
        want = 0
        for _ in range(len(segments) + 1):
            if s is None or offset >= s.next_offset:
                # recovery-truncated hole before the next segment: serve
                # the successor from its base (same monotone-recovery
                # promise as read_from's hole jump)
                nxt = [x for x in segments if x.base_offset > offset]
                if not nxt:
                    return None
                s = nxt[0]
                offset = s.base_offset
            start_pos = 0
            for o, pos in reversed(s.index):
                if o <= offset:
                    start_pos = pos
                    break
            want = min(max(int(max_bytes), seg.MIN_BODY + 8),
                       s.size - start_pos)
            if want > 0:
                break
            # a compaction-emptied segment (zero bytes, base/next_offset
            # preserved to keep the log head stable): jump past it like
            # the hole case — returning None here would read as log end
            # and park every raw reader forever
            offset = s.next_offset
            s = self._segment_for(segments, offset)
        if want <= 0:
            return None
        try:
            with open(s.path, "rb") as fh:
                fh.seek(start_pos)
                data = fh.read(want)
        except FileNotFoundError:
            return None  # retention deleted it mid-read: trimmed history
        return data, offset

    def offset_for_timestamp(self, timestamp_ms: int) -> int:
        """Earliest offset whose record timestamp is >= `timestamp_ms`
        (end_offset when no such record) — the `retention.ms`-era replay
        cursor: 'give me everything since T'."""
        self.flush(sync=False)
        segments = list(self._segments)  # snapshot, like read_from
        for s in segments:
            if s.max_ts < timestamp_ms:
                continue
            # first timeindex entry at/after the target bounds the scan;
            # stream frames and stop at the first match — never decode
            # (or materialize) the rest of the segment
            start = s.base_offset
            for ts, off in s.timeindex:
                if ts >= timestamp_ms:
                    break
                start = off
            start_pos = 0
            for o, pos in reversed(s.index):
                if o <= start:
                    start_pos = pos
                    break
            try:
                for _pos, _end, off, _key, _value, ts, _hdrs in \
                        seg.iter_frames(s.path, start_pos):
                    if off >= start and ts >= timestamp_ms:
                        return off
            except FileNotFoundError:
                continue  # retention deleted it mid-scan: trimmed
        return segments[-1].next_offset

    def read_since(self, timestamp_ms: int,
                   max_records: int = 1024) -> List[tuple]:
        """Replay every record with timestamp >= `timestamp_ms`."""
        return self.read_from(self.offset_for_timestamp(timestamp_ms),
                              max_records=max_records, _count_replay=True)

    # -------------------------------------------------------- compaction
    def compact(self, grace_ms: Optional[int] = None, lock=None):
        """Key-based compaction over the sealed segments (compact.py):
        keeps the latest record per key, drops tombstones past the grace
        window, preserves offsets.  ``lock`` (the broker lock) is taken
        only around each swap + segment-list update — the scan/rewrite
        I/O runs outside it so compaction never stalls produce/fetch."""
        from . import compact as _compact

        return _compact.compact_log(self, grace_ms=grace_ms, lock=lock)

    def dirty_ratio(self) -> float:
        """Sealed bytes appended since the last compaction over total
        sealed bytes — the ``min.cleanable.dirty.ratio`` trigger input."""
        from . import compact as _compact

        return _compact.dirty_ratio(self)

    # --------------------------------------------------------- retention
    def enforce_retention(self) -> int:
        """Delete whole sealed segments past the byte/count/age budget;
        returns records dropped.  The active segment is never deleted —
        the head of the log trims, the tail keeps appending.  Count
        retention is segment-granular like the others: the head segment
        goes once the REMAINING segments alone satisfy the cap (Kafka's
        own delete-whole-segments semantics, a slight over-retention
        rather than record-exact trimming)."""
        dropped = 0
        pol = self.policy
        newest_ts = max((s.max_ts for s in self._segments), default=-1)
        while len(self._segments) > 1:
            head = self._segments[0]
            over_bytes = pol.retention_bytes and \
                self.total_bytes() > pol.retention_bytes
            over_count = pol.retention_messages and \
                (self.end_offset - self._segments[1].base_offset
                 >= pol.retention_messages)
            over_age = pol.retention_ms and newest_ts >= 0 and \
                0 <= head.max_ts < newest_ts - pol.retention_ms
            if not (over_bytes or over_count or over_age):
                break
            dropped += head.next_offset - head.base_offset
            self._total_bytes -= head.size
            os.remove(head.path)
            self._remove_sidecars(head.base_offset)
            self._segments.pop(0)
        if dropped:
            self._update_size_gauge()
        return dropped

    # ------------------------------------------------- replica/test hooks
    def align_base(self, offset: int) -> None:
        """Seed an EMPTY log's base offset (replica bootstrap parity
        with the in-memory partition)."""
        if len(self):
            raise ValueError("log not empty; base is immutable")
        base = int(offset)
        old = self._segments[-1]
        if old.base_offset == base:
            return
        self.close()
        os.remove(old.path)
        self._remove_sidecars(old.base_offset)
        s = _Segment(base, os.path.join(self.dir,
                                        _seg_name(base) + _LOG_SUFFIX))
        self._segments = [s]
        self._open_writer()

    def reset(self, base_offset: int) -> None:
        """Drop everything and restart at `base_offset` (replica
        realignment after the leader's retention outran replication)."""
        self.close()
        for s in self._segments:
            if os.path.exists(s.path):
                os.remove(s.path)
            self._remove_sidecars(s.base_offset)
        s = _Segment(int(base_offset),
                     os.path.join(self.dir,
                                  _seg_name(int(base_offset)) + _LOG_SUFFIX))
        self._segments = [s]
        self._total_bytes = 0
        self._open_writer()
        self._update_size_gauge()

    def simulate_torn_write(self, blob: Optional[bytes] = None) -> int:
        """Append a deliberately torn frame to the active segment — the
        on-disk artifact of a process killed mid-write.  Chaos/test-only
        (production appends can't emit an invalid frame); lives here so
        even crash simulation goes through SegmentWriter (lint R9).
        Returns the byte count recovery must truncate."""
        if blob is None:
            # a length prefix promising far more bytes than follow
            blob = seg._LEN.pack(1 << 20) + b"\xde\xad\xbe\xef" * 4
        self._writer.write_blob(blob)
        self._writer.flush()
        return len(blob)

    def index_entries(self) -> Dict[int, int]:
        """{offset: file_pos} of the active segment's sparse index —
        test introspection for index density assertions."""
        return dict(self._segments[-1].index)
