"""Store mount — one durable directory serving one broker.

Directory layout::

    <store_dir>/topics.json                      topic manifest
    <store_dir>/offsets                          consumer-group offsets
    <store_dir>/segments/<topic>/<partition>/    one SegmentedLog each

The manifest records every topic's partition count and retention so a
restarted broker re-creates the same TopicSpecs before serving (a
consumer must never observe a mounted broker with fewer partitions than
it committed against).  Topic names are sanitized into directory names
conservatively; the manifest keeps the real name, so lookups never
depend on the sanitized form being reversible.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional

from . import segment as seg
from .log import SegmentedLog, StorePolicy
from .offsets import OffsetsFile

_MANIFEST = "topics.json"
_UNSAFE = re.compile(r"[^A-Za-z0-9._-]")


def _dirname_for(topic: str) -> str:
    """Filesystem-safe directory name.  When sanitization had to change
    anything, a CRC of the REAL name is appended so two topics that
    sanitize identically ("a b" vs "a_b") can never share a directory —
    two SegmentedLogs interleaving one active segment is unrecoverable."""
    import zlib

    safe = _UNSAFE.sub("_", topic)
    if safe == topic:
        return safe
    return f"{safe or '_'}-{zlib.crc32(topic.encode()):08x}"


class StoreMount:
    """Owns the manifest, the offsets file and every partition log of
    one store directory.  The broker calls in under its own lock."""

    def __init__(self, dir: str, policy: Optional[StorePolicy] = None,
                 tier=None):
        self.dir = dir
        self.policy = policy or StorePolicy()
        #: TierPolicy with a uri → every partition log mounts as a
        #: TieredLog over one shared ArtifactStore backend; falsy →
        #: plain local SegmentedLogs (the seed behavior, zero cost)
        self.tier = tier if tier else None
        self._tier_store = None
        if self.tier is not None:
            from .remote import artifact_store_for

            self._tier_store = artifact_store_for(self.tier.uri)
        os.makedirs(dir, exist_ok=True)
        self._acquire_dir_lock()
        self._logs: Dict[tuple, SegmentedLog] = {}
        self._manifest: Dict[str, dict] = {}
        self._load_manifest()
        self.offsets = OffsetsFile(dir, fsync=self.policy.fsync,
                                   fsync_interval_s=self.policy
                                   .fsync_interval_s)

    def _acquire_dir_lock(self) -> None:
        """One broker PROCESS per store dir (Kafka's .lock file): two
        writers interleaving frames in one active segment is exactly the
        corruption recovery cannot undo.  POSIX record locks (lockf) on
        purpose — they conflict across processes but not within one, so
        a crash-simulating remount in the same process (the chaos
        runner's kill) still mounts, and the kernel drops the lock when
        a dead process's fds close (no stale-lockfile recovery needed)."""
        self._lock_fd = os.open(os.path.join(self.dir, ".lock"),
                                os.O_CREAT | os.O_RDWR, 0o644)
        try:
            import fcntl

            fcntl.lockf(self._lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except ImportError:  # non-POSIX: single-writer is unenforced
            pass
        except OSError:
            os.close(self._lock_fd)
            self._lock_fd = None
            raise RuntimeError(
                f"store dir {self.dir!r} is locked by another broker "
                f"process; two writers would corrupt the segments "
                f"(stop the other platform, or use a different "
                f"--store-dir)") from None

    # ---------------------------------------------------------- manifest
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, _MANIFEST)

    def _load_manifest(self) -> None:
        path = self._manifest_path()
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as fh:
                self._manifest = json.load(fh)

    def _save_manifest(self) -> None:
        blob = json.dumps(self._manifest, indent=2, sort_keys=True).encode()
        seg.atomic_write(self._manifest_path(), blob,
                         fsync=self.policy.fsync != "never")

    def topics(self) -> List[dict]:
        """Manifest entries for mount-time topic re-creation:
        [{name, partitions, retention_*}]."""
        return [dict(doc, name=name)
                for name, doc in sorted(self._manifest.items())]

    def register_topic(self, name: str, partitions: int,
                       retention_messages=None, retention_bytes=None,
                       retention_ms=None,
                       cleanup_policy: str = "delete") -> None:
        doc = {
            "dir": _dirname_for(name),
            "partitions": int(partitions),
            "retention_messages": retention_messages,
            "retention_bytes": retention_bytes,
            "retention_ms": retention_ms,
            "cleanup_policy": cleanup_policy,
        }
        if self._manifest.get(name) == doc:
            return  # mount-time re-registration: no rewrite+fsync per topic
        self._manifest[name] = doc
        self._save_manifest()

    # -------------------------------------------------------------- logs
    def log_for(self, topic: str, partition: int) -> SegmentedLog:
        key = (topic, int(partition))
        log = self._logs.get(key)
        if log is None:
            doc = self._manifest.get(topic) or {"dir": _dirname_for(topic)}
            pdir = os.path.join(self.dir, "segments", doc["dir"],
                                str(int(partition)))
            labels = {"topic": topic, "partition": str(partition)}
            if self._tier_store is not None:
                from .remote import RemoteTier
                from .tiered import TieredLog

                remote = RemoteTier(
                    self._tier_store,
                    prefix=f"tiered/{doc['dir']}/{int(partition)}")
                log = TieredLog(pdir, policy=self.policy, remote=remote,
                                tier=self.tier, metric_labels=labels)
            else:
                log = SegmentedLog(pdir, policy=self.policy,
                                   metric_labels=labels)
            self._logs[key] = log
        return log

    def recovered_truncated_bytes(self) -> int:
        return sum(l.recovered_truncated_bytes
                   for l in self._logs.values()) + \
            self.offsets.recovered_truncated_bytes

    def flush(self) -> None:
        for log in self._logs.values():
            log.flush()
        self.offsets.flush()

    def close(self) -> None:
        for log in self._logs.values():
            log.close()
        self.offsets.close()
        if getattr(self, "_lock_fd", None) is not None:
            os.close(self._lock_fd)  # releases the lockf lock
            self._lock_fd = None
