"""Durable consumer-group offsets — the __consumer_offsets equivalent.

The broker's ``commit`` table is the resume cursor for every consumer in
the framework (SURVEY §5: "the offset is the checkpoint"), so a durable
log without durable offsets would re-serve history to consumers that
already committed past it.  This file is the compacted key→value store
Kafka keeps in ``__consumer_offsets``: each commit appends one framed
record (``segment.py`` frame; key = ``group\\0topic\\0partition``, value
= offset as decimal ASCII), and when the appended history outgrows the
live key set by ``compact_ratio`` the whole file is rewritten with one
record per key and atomically renamed into place.  The keep/discard
decision is ``store.compact``'s (`latest_offsets` + `keep`) — the same
one implementation that compacts ``cleanup.policy=compact`` topic
segments, applied here to a single-file log.

Crash behavior is the segment format's: a torn tail record is dropped at
load (the commit it carried was never acknowledged as durable under
``fsync=always``; under laxer policies the consumer re-reads a slice —
at-least-once, the framework-wide delivery contract).
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

from . import segment as seg
from .segment import SegmentWriter

_FILENAME = "offsets"


class OffsetsFile:
    """Append + compact store for {(group, topic, partition): next_offset}."""

    def __init__(self, dir: str, fsync: str = "interval",
                 compact_ratio: int = 4, fsync_interval_s: float = 0.05):
        import time

        os.makedirs(dir, exist_ok=True)
        self.path = os.path.join(dir, _FILENAME)
        self.fsync = fsync
        self.fsync_interval_s = float(fsync_interval_s)
        self._last_fsync = time.monotonic()
        self.compact_ratio = max(int(compact_ratio), 2)
        self._table: Dict[Tuple[str, str, int], int] = {}
        self._records = 0  # appended records since the last compaction
        self.recovered_truncated_bytes = 0
        self._load()
        self._writer = SegmentWriter(self.path, fsync=fsync)

    # ------------------------------------------------------------- load
    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        data = seg.read_file(self.path)
        valid_end = 0
        for _pos, end, _off, key, value, _ts, _h in seg.scan_records(data):
            group, topic, part = key.decode().split("\x00")
            self._table[(group, topic, int(part))] = int(value)
            self._records += 1
            valid_end = end
        if valid_end < len(data):
            from .log import store_recovery_truncated

            torn = len(data) - valid_end
            self.recovered_truncated_bytes += torn
            store_recovery_truncated.inc(torn)  # same ledger as segments
            w = SegmentWriter(self.path, fsync=self.fsync)
            w.truncate_to(valid_end)
            w.close(sync=self.fsync != "never")

    # ------------------------------------------------------------ write
    def commit(self, group: str, topic: str, partition: int,
               next_offset: int, sync: bool = True) -> None:
        key = f"{group}\x00{topic}\x00{partition}".encode()
        frame = seg.encode_record(0, key, str(int(next_offset)).encode(),
                                  0, None)
        self._writer.append(frame)
        if self.fsync == "always":
            if sync:
                self._writer.sync()
        elif self.fsync == "interval":
            # same cadence contract as SegmentedLog.append: loss bounded
            # to the interval, not to "whenever compaction happens"
            import time

            now = time.monotonic()
            if now - self._last_fsync >= self.fsync_interval_s:
                self._writer.sync()
                self._last_fsync = now
        self._table[(group, topic, int(partition))] = int(next_offset)
        self._records += 1
        if self._records >= self.compact_ratio * max(len(self._table), 1):
            self.compact()

    def commit_many(self, group: str, topic: str, entries) -> None:
        """Commit [(partition, next_offset), ...] with ONE fsync."""
        for p, off in entries:
            self.commit(group, topic, p, off, sync=False)
        if self.fsync == "always":
            self._writer.sync()

    def compact(self) -> None:
        """Rewrite one record per live key; atomic-rename publication.

        Routes the keep/discard decision through the generic compactor
        (store.compact) so key-compaction semantics exist exactly once.
        Survivors are re-framed from the in-memory table — it IS the
        latest-per-key set (`_load` rebuilds it, ``get`` serves it), so
        this commit-hot path never re-reads the file from disk.  Frames
        are byte-identical to ``commit``'s (same key/value encoding,
        ts 0, no headers); offsets collapse to 0 — this file is a
        table, not an offset-addressed log, so renumbering is free.
        Tombstones never appear here (commits are never null), so the
        grace window is moot."""
        from . import compact as _compact

        records = [
            (i, f"{g}\x00{t}\x00{p}".encode(), str(off).encode(), 0, None)
            for i, ((g, t, p), off) in enumerate(self._table.items())]
        latest = _compact.latest_offsets(records)
        blob = b"".join(
            seg.encode_record(0, key, value, ts, headers)
            for off, key, value, ts, headers in records
            if _compact.keep((off, key, value, ts, headers), latest,
                             newest_ts=-1, grace_ms=None))
        self._writer.close(sync=False)
        seg.atomic_write(self.path, blob, fsync=self.fsync != "never")
        self._writer = SegmentWriter(self.path, fsync=self.fsync)
        self._records = len(self._table)

    # ------------------------------------------------------------- read
    def table(self) -> Dict[Tuple[str, str, int], int]:
        return dict(self._table)

    def get(self, group: str, topic: str, partition: int):
        return self._table.get((group, topic, int(partition)))

    def flush(self) -> None:
        if self.fsync != "never":
            self._writer.sync()
        else:
            self._writer.flush()

    def close(self) -> None:
        self._writer.close(sync=self.fsync != "never")
