"""Remote tier — sealed segments in an ArtifactStore, manifest-committed.

The object-store half of tiered log storage (KIP-405's shape on the
reference's own GCS bucket): sealed segment files and their index
sidecars upload as opaque blobs, and a per-partition ``manifest.json``
— written atomically (`ArtifactStore.put_text`) — is the ONE commit
marker.  Readers trust exactly what the manifest lists; a blob the
manifest does not name does not exist, no matter how many bytes of it
landed.  That is the same manifest-as-commit-marker protocol the model
registry uses (ARCHITECTURE §17), applied to log segments:

    upload ``<base>.stage`` marker        (intent, sweepable)
    upload ``<base>.log/.index/.timeindex``  (blobs, each atomic)
    --- crash here leaves garbage, never a servable segment ---
    commit manifest (atomic text write)   (the segment now EXISTS)
    delete the stage marker               (cleanup, best-effort)

Remote layout under one tier root::

    tiered/<topic_dir>/<partition>/manifest.json
    tiered/<topic_dir>/<partition>/00000000000000000000.log
    tiered/<topic_dir>/<partition>/00000000000000000000.index
    tiered/<topic_dir>/<partition>/00000000000000000000.timeindex

Manifest entries carry the log blob's size and CRC32C; the fetch path
verifies both before a downloaded segment is ever mounted, so a torn
blob (a backend without atomic puts, a truncated download) is an error,
never data.  `sweep()` garbage-collects everything unreferenced —
the blobs of a killed mid-upload, stale ``.stage`` markers, segments
dropped by remote retention.  One writer per partition prefix is
guaranteed upstream by the store dir's process lock (mount.py), so the
sweeper can never race an upload it didn't schedule itself.

Lint R9 (extended) confines this machinery — tier uploads, the remote
manifest, ``.stage`` markers — to ``iotml/store/``: remote durability
promises are made in exactly one place, like local ones.
"""

from __future__ import annotations

import json
import os
from typing import List, NamedTuple, Optional

from ..chaos import faults as chaos
from ..obs import metrics as obs_metrics
from . import segment as seg

_MANIFEST = "manifest.json"
_STAGE_SUFFIX = ".stage"
_LOG_SUFFIX = ".log"
_SIDECAR_SUFFIXES = (".index", ".timeindex")

tier_uploads = obs_metrics.default_registry.counter(
    "iotml_tier_uploads_total",
    "sealed segments committed to the remote tier (manifest commits)")
tier_upload_bytes = obs_metrics.default_registry.counter(
    "iotml_tier_upload_bytes_total",
    "log-segment bytes shipped to the remote tier")
tier_remote_fetches = obs_metrics.default_registry.counter(
    "iotml_tier_remote_fetch_total",
    "remote segments downloaded (and CRC-verified) into the local cache")
tier_swept_blobs = obs_metrics.default_registry.counter(
    "iotml_tier_swept_blobs_total",
    "unreferenced remote blobs garbage-collected (torn uploads, stage "
    "markers, retention-dropped segments)")


class RemoteSegmentMeta(NamedTuple):
    """One committed remote segment, exactly as the manifest records it."""

    base: int       # base offset (names the blobs, Kafka layout)
    next: int       # next_offset — the roll invariant, holes included
    size: int       # log blob bytes (fetch-time torn-blob check)
    max_ts: int     # newest record timestamp (remote retention anchor)
    crc: int        # CRC32C of the log blob (fetch-time corruption check)


def _seg_name(base: int) -> str:
    return f"{base:020d}"


def _file_crc(path: str) -> int:
    return seg.crc32c(seg.read_file(path))


class RemoteTier:
    """One partition's remote-tier view: blobs + the manifest commit.

    ``store`` is an ArtifactStore duck (upload/download/put_text/
    get_text/list/delete — the hardened interface); ``prefix`` is this
    partition's blob namespace.  All methods are synchronous I/O; the
    caller (TierUploader thread / the read path's cache fill) owns
    scheduling."""

    def __init__(self, store, prefix: str):
        self.store = store
        self.prefix = prefix.rstrip("/")

    # ------------------------------------------------------------ names
    def _blob(self, base: int, suffix: str) -> str:
        return f"{self.prefix}/{_seg_name(base)}{suffix}"

    @property
    def _manifest_name(self) -> str:
        return f"{self.prefix}/{_MANIFEST}"

    # --------------------------------------------------------- manifest
    def load(self) -> List[RemoteSegmentMeta]:
        """Committed segments, sorted by base offset.  [] when the tier
        has never committed (or the manifest is unreadable — an
        unreachable tier degrades to local-only serving, never an
        error at mount)."""
        text = self.store.get_text(self._manifest_name)
        if text is None:
            return []
        doc = json.loads(text)
        metas = [RemoteSegmentMeta(int(e["base"]), int(e["next"]),
                                   int(e["size"]), int(e["max_ts"]),
                                   int(e["crc"]))
                 for e in doc.get("segments", [])]
        return sorted(metas, key=lambda m: m.base)

    def _commit(self, metas: List[RemoteSegmentMeta]) -> None:
        doc = {"segments": [m._asdict() for m in
                            sorted(metas, key=lambda m: m.base)]}
        self.store.put_text(self._manifest_name,
                            json.dumps(doc, indent=2, sort_keys=True))

    # ------------------------------------------------------------ upload
    def upload_segment(self, log_path: str, index_path: str,
                       timeindex_path: str, base: int, next_offset: int,
                       max_ts: int) -> RemoteSegmentMeta:
        """Stage-then-commit one sealed segment (or a compacted rewrite
        of one — same base replaces the old entry).  A kill anywhere
        before the manifest commit leaves only unreferenced blobs and a
        stage marker for `sweep()`; the local copy stays authoritative
        because nothing below is servable until the commit."""
        size = os.path.getsize(log_path)
        crc = _file_crc(log_path)
        # intent marker first: a sweep finding this without a matching
        # manifest entry knows the blobs beside it are a torn upload
        self.store.put_text(self._blob(base, _STAGE_SUFFIX),
                            json.dumps({"base": base, "size": size}))
        self.store.upload(log_path, self._blob(base, _LOG_SUFFIX))
        for path, suffix in ((index_path, ".index"),
                             (timeindex_path, ".timeindex")):
            self.store.upload(path, self._blob(base, suffix))
        # the kill-mid-upload faultpoint: blobs landed, manifest NOT
        # committed — the exact window the commit-marker protocol exists
        # for (chaos scenario `tier-upload-crash` kills here)
        chaos.point("store.tier_upload")
        meta = RemoteSegmentMeta(base, int(next_offset), size,
                                 int(max_ts), crc)
        metas = [m for m in self.load() if m.base != base]
        metas.append(meta)
        self._commit(metas)
        try:
            self.store.delete(self._blob(base, _STAGE_SUFFIX))
        except OSError:
            pass  # sweep() collects it; the commit already happened
        tier_uploads.inc()
        tier_upload_bytes.inc(size)
        return meta

    # ------------------------------------------------------------- fetch
    def fetch_segment(self, meta: RemoteSegmentMeta, dest_dir: str) -> str:
        """Download one committed segment (+ sidecars) into `dest_dir`
        under its canonical names; the log blob must match the
        manifest's size AND CRC exactly or nothing is left behind —
        "no torn remote segment is ever served" is enforced here, not
        hoped for at the backend."""
        os.makedirs(dest_dir, exist_ok=True)
        log_dst = os.path.join(dest_dir, _seg_name(meta.base) + _LOG_SUFFIX)
        try:
            self.store.download(self._blob(meta.base, _LOG_SUFFIX), log_dst)
            if os.path.getsize(log_dst) != meta.size \
                    or _file_crc(log_dst) != meta.crc:
                raise OSError(
                    f"remote segment {meta.base} is torn/corrupt "
                    f"(size/CRC mismatch vs manifest); refusing to serve")
            for suffix in _SIDECAR_SUFFIXES:
                dst = os.path.join(dest_dir, _seg_name(meta.base) + suffix)
                try:
                    self.store.download(self._blob(meta.base, suffix), dst)
                except (OSError, FileNotFoundError):
                    # sidecars are an accelerator, never ground truth
                    # (same trust rule as the local mount): the cache
                    # mount rebuilds indexes from the log
                    if os.path.exists(dst):
                        os.remove(dst)
        except Exception:
            for name in os.listdir(dest_dir) if os.path.isdir(dest_dir) \
                    else ():
                os.remove(os.path.join(dest_dir, name))
            raise
        tier_remote_fetches.inc()
        return log_dst

    # -------------------------------------------------------- retention
    def enforce_retention(self, retention_ms: int,
                          newest_ts: int) -> List[RemoteSegmentMeta]:
        """Drop committed segments whose newest record aged past
        ``retention_ms`` against `newest_ts` (the log-wide newest
        timestamp — Kafka's rule, same anchor as local retention).
        The manifest shrinks FIRST (the drop commits), then blobs are
        deleted; a crash between the two leaves unreferenced blobs for
        `sweep()`.  Returns the dropped metas."""
        if not retention_ms or newest_ts < 0:
            return []
        cutoff = newest_ts - int(retention_ms)
        metas = self.load()
        keep = [m for m in metas if not (0 <= m.max_ts < cutoff)]
        dropped = [m for m in metas if 0 <= m.max_ts < cutoff]
        if not dropped:
            return []
        self._commit(keep)
        for m in dropped:
            for suffix in (_LOG_SUFFIX,) + _SIDECAR_SUFFIXES:
                try:
                    self.store.delete(self._blob(m.base, suffix))
                except OSError:
                    pass  # sweep() retries
        return dropped

    def retire(self, bases) -> List[RemoteSegmentMeta]:
        """Remove committed entries whose local segments a compaction
        pass merged away entirely — the rewrite landed in a NEIGHBOR
        base, so no re-upload will ever replace these and they would
        keep serving shadowed pre-compaction records.  Same ordering
        as retention: the manifest shrinks first (the drop commits),
        blobs after; a crash in between leaves `sweep()` work, never
        servable stale data.  Returns the dropped metas."""
        bases = set(bases)
        metas = self.load()
        keep = [m for m in metas if m.base not in bases]
        dropped = [m for m in metas if m.base in bases]
        if not dropped:
            return []
        self._commit(keep)
        for m in dropped:
            for suffix in (_LOG_SUFFIX,) + _SIDECAR_SUFFIXES:
                try:
                    self.store.delete(self._blob(m.base, suffix))
                except OSError:
                    pass  # sweep() retries
        return dropped

    # ------------------------------------------------------------- sweep
    def sweep(self) -> int:
        """Delete every blob under this partition's prefix the manifest
        does not reference — torn mid-upload leftovers, stale stage
        markers, retention stragglers.  Safe because the store dir's
        process lock makes this thread the only writer: an upload can
        never be in flight while its own thread sweeps."""
        referenced = {self._manifest_name}
        for m in self.load():
            for suffix in (_LOG_SUFFIX,) + _SIDECAR_SUFFIXES:
                referenced.add(self._blob(m.base, suffix))
        swept = 0
        for name in self.store.list(self.prefix):
            full = name if name.startswith(self.prefix) \
                else f"{self.prefix}/{name}"
            if full in referenced:
                continue
            try:
                if self.store.delete(full):
                    swept += 1
            except OSError:
                pass  # next pass retries
        if swept:
            tier_swept_blobs.inc(swept)
        return swept


def artifact_store_for(uri: str):
    """Build the ArtifactStore backend for a tier URI (a local directory
    or ``gs://…``).  Imported lazily: the train package hauls in the
    model stack, and a store mount without a tier must not pay for it."""
    from ..train.artifacts import ArtifactStore

    return ArtifactStore(uri)
