"""On-disk segment format — the durable record frame and its writer.

One segment is one append-only file holding length-prefixed records:

    u32  length        bytes after this field (frame body)
    u32  crc32c        Castagnoli CRC over every byte after this field
    u8   attrs         bit 0: record carries headers
                       bit 1: null value (a TOMBSTONE — compaction's
                       delete marker; value_len is 0 and the decoded
                       value is None, never b"")
    i64  offset        absolute log offset (self-describing: recovery
                       and index rebuilds never need external state)
    i64  timestamp_ms  record timestamp (the timestamp index key)
    i32  key_len       -1 = null key
    ..   key
    u32  value_len
    ..   value
    [headers when attrs bit 0:
      u16 n; per header: u16 key_len, key, u32 val_len, val]

CRC32C (not zlib's CRC32) deliberately: it is what Kafka's record
batches use, its software table is small, and keeping the polynomial
distinct from the wire protocol's CRC32 means a segment byte-range
accidentally framed as a MessageSet (or vice versa) cannot
checksum-collide its way through the wrong decoder.

``SegmentWriter`` is the ONE thing in this codebase allowed to write
under a store directory (lint R9): it owns the file descriptor, the
fsync policy (``never`` | ``interval`` | ``always``) and the
``iotml_store_fsync_seconds`` accounting, so durability promises are
made in exactly one place.

Torn writes are the expected crash artifact: a process dying mid-
``append`` leaves a record whose length prefix promises more bytes than
the file holds, or whose CRC does not match.  ``scan_records`` stops at
the first such record and reports the valid prefix length — recovery
(`log.SegmentedLog`) truncates there and counts the rest as
``iotml_store_recovery_truncated_bytes``.

Header values: a live in-process object that knows its byte form
(``.encode()``, e.g. ``obs.tracing.TraceContext``) is stored encoded and
comes back as ``bytes`` — exactly what ``tracing.from_headers`` accepts
on the transport path, so traces survive a durable hop the same way
they survive a wire hop.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Tuple

from ..obs import metrics as obs_metrics

store_fsync_seconds = obs_metrics.default_registry.histogram(
    "iotml_store_fsync_seconds", "segment/offsets fsync latency")

#: frame geometry
_LEN = struct.Struct(">I")
_HEAD = struct.Struct(">IBqqi")    # crc, attrs, offset, timestamp, key_len
_U32 = struct.Struct(">I")
_U16 = struct.Struct(">H")
_ATTR_HEADERS = 0x01
_ATTR_NULL_VALUE = 0x02  # tombstone: the frame body carries value_len 0,
# decode returns value=None — distinct from an empty (b"") value so
# compaction's delete markers survive a durable hop intact

#: the smallest possible frame body: crc+attrs+offset+ts+key_len + value_len
MIN_BODY = _HEAD.size + _U32.size


# ------------------------------------------------------------------ crc32c
def _make_crc32c_table() -> tuple:
    poly = 0x82F63B78  # Castagnoli, reflected
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return tuple(table)


_CRC32C_TABLE = _make_crc32c_table()


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    """Software CRC32C (Castagnoli) — the oracle and the fallback."""
    crc ^= 0xFFFFFFFF
    table = _CRC32C_TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _resolve_crc32c():
    """Prefer the C extension when the environment has one (the
    per-record software loop dominates append cost otherwise); parity
    with the table implementation is pinned by tests/test_store.py."""
    try:
        from google_crc32c import extend as _ext  # already a jax-stack dep

        def fast(data: bytes, crc: int = 0) -> int:
            return _ext(crc, bytes(data))

        if fast(b"123456789") == 0xE3069283:  # self-check before trusting
            return fast
    except Exception:  # noqa: BLE001 - any miss falls back to the table
        pass
    return _crc32c_py


crc32c = _resolve_crc32c()


# ------------------------------------------------------------ record codec
def _encode_headers(headers) -> bytes:
    out = [_U16.pack(len(headers))]
    for key, value in headers:
        kb = key.encode() if isinstance(key, str) else bytes(key)
        enc = getattr(value, "encode", None)
        if isinstance(value, (bytes, bytearray)):
            vb = bytes(value)
        elif enc is not None:
            vb = value.encode()  # TraceContext et al: transport byte form
            if isinstance(vb, str):
                vb = vb.encode()
        else:
            vb = str(value).encode()
        out.append(_U16.pack(len(kb)))
        out.append(kb)
        out.append(_U32.pack(len(vb)))
        out.append(vb)
    return b"".join(out)


def _decode_headers(body: bytes, pos: int) -> Optional[tuple]:
    (n,) = _U16.unpack_from(body, pos)
    pos += _U16.size
    out = []
    for _ in range(n):
        (klen,) = _U16.unpack_from(body, pos)
        pos += _U16.size
        key = body[pos:pos + klen].decode()
        pos += klen
        (vlen,) = _U32.unpack_from(body, pos)
        pos += _U32.size
        out.append((key, body[pos:pos + vlen]))
        pos += vlen
    return tuple(out)


def encode_record(offset: int, key: Optional[bytes], value: Optional[bytes],
                  timestamp_ms: int, headers: Optional[tuple]) -> bytes:
    """One framed record (length prefix included).  ``value=None`` frames
    a tombstone (attrs bit 1): byte-distinct from an empty value."""
    attrs = _ATTR_HEADERS if headers else 0
    if value is None:
        attrs |= _ATTR_NULL_VALUE
        value = b""
    parts = [_HEAD.pack(0, attrs, offset, timestamp_ms,
                        -1 if key is None else len(key))]
    if key is not None:
        parts.append(key)
    parts.append(_U32.pack(len(value)))
    parts.append(value)
    if headers:
        parts.append(_encode_headers(headers))
    body = bytearray(b"".join(parts))
    crc = crc32c(bytes(body[_U32.size:]))
    body[:_U32.size] = _U32.pack(crc)
    return _LEN.pack(len(body)) + bytes(body)


def decode_record(body: bytes) -> Tuple[int, Optional[bytes], bytes, int,
                                        Optional[tuple]]:
    """Frame body (length prefix stripped, CRC verified by the caller)
    → (offset, key, value, timestamp_ms, headers)."""
    _crc, attrs, offset, ts, key_len = _HEAD.unpack_from(body, 0)
    pos = _HEAD.size
    key = None
    if key_len >= 0:
        key = body[pos:pos + key_len]
        pos += key_len
    (vlen,) = _U32.unpack_from(body, pos)
    pos += _U32.size
    value = None if attrs & _ATTR_NULL_VALUE else body[pos:pos + vlen]
    pos += vlen
    headers = _decode_headers(body, pos) if attrs & _ATTR_HEADERS else None
    return offset, key, value, ts, headers


def scan_records(data: bytes):
    """Yield (file_pos, next_pos, offset, key, value, ts, headers) for
    every VALID record in `data`, stopping at the first torn/corrupt
    frame.  ``scan_records(data).valid_end`` is not a thing — callers
    take the last yielded ``next_pos`` as the valid prefix length."""
    pos = 0
    n = len(data)
    while pos + _LEN.size <= n:
        (length,) = _LEN.unpack_from(data, pos)
        body_start = pos + _LEN.size
        end = body_start + length
        if length < MIN_BODY or end > n:
            return  # torn: the length prefix promises bytes we don't have
        body = data[body_start:end]
        (crc,) = _U32.unpack_from(body, 0)
        if crc32c(body[_U32.size:]) != crc:
            return  # corrupt frame: recovery truncates here
        offset, key, value, ts, headers = decode_record(body)
        yield pos, end, offset, key, value, ts, headers
        pos = end


# ---------------------------------------------------------------- writer
class SegmentWriter:
    """Owner of every byte written under a store directory (lint R9).

    Wraps one file opened for append plus the fsync policy.  ``append``
    returns the file position the frame landed at (the offset-index
    entry).  ``maybe_fsync`` applies the ``interval`` policy using a
    caller-supplied monotonic clock so the segmented log, not each
    writer, owns the cadence state.
    """

    def __init__(self, path: str, fsync: str = "interval"):
        if fsync not in ("never", "interval", "always"):
            raise ValueError(f"fsync policy must be never|interval|always, "
                             f"got {fsync!r}")
        self.path = path
        self.fsync = fsync
        self._fh = open(path, "ab")
        self.position = self._fh.tell()

    def append(self, frame: bytes) -> int:
        """Buffered write; the OWNER (SegmentedLog / OffsetsFile) applies
        the fsync policy — batch appends ack once per batch, not once
        per record, without weakening the acked⇒durable contract."""
        pos = self.position
        self._fh.write(frame)
        self.position = pos + len(frame)
        return pos

    def write_blob(self, blob: bytes) -> int:
        """Raw bytes straight to the file — the offsets/manifest writer
        and the chaos runner's torn-tail injection (a deliberately
        invalid frame is still a write the store must own)."""
        return self.append(blob)

    def sync(self) -> None:
        import time

        self._fh.flush()
        t0 = time.perf_counter()
        os.fsync(self._fh.fileno())
        store_fsync_seconds.observe(time.perf_counter() - t0)

    def flush(self) -> None:
        self._fh.flush()

    def truncate_to(self, size: int) -> None:
        """Drop everything past `size` (recovery's torn-tail cut)."""
        self._fh.flush()
        self._fh.truncate(size)
        self._fh.seek(0, os.SEEK_END)
        self.position = size

    def close(self, sync: bool = False) -> None:
        if self._fh.closed:
            return
        if sync and self.fsync != "never":
            self.sync()
        else:
            self._fh.flush()
        self._fh.close()


def read_file(path: str) -> bytes:
    with open(path, "rb") as fh:
        return fh.read()


_SCAN_CHUNK = 256 * 1024


def iter_frames(path: str, pos: int):
    """Stream valid frames from `pos` in bounded chunks — a reader that
    stops early (max_records, first-timestamp-match) never pays for the
    rest of the segment.  Yields the same tuples as scan_records with
    TRUE file positions (file_pos/next_pos are absolute, not
    buffer-relative).  A frame split across a chunk boundary is
    completed by the next read; scanning stops permanently at a corrupt
    frame (same contract as scan_records — recovery truncates there)."""
    buf = b""
    base = pos  # absolute file position of buf[0]
    with open(path, "rb") as fh:
        fh.seek(pos)
        while True:
            chunk = fh.read(_SCAN_CHUNK)
            buf += chunk
            last_end = 0
            for fpos, fend, off, key, value, ts, hdrs in scan_records(buf):
                last_end = fend
                yield (base + fpos, base + fend, off, key, value, ts, hdrs)
            if not chunk:
                return  # EOF: whatever remains is torn/partial
            if last_end == 0 and len(buf) >= _LEN.size:
                # nothing validated: decide from the head frame's own
                # length prefix whether we are mid-frame (keep reading)
                # or parked on a corrupt frame (stop — nothing after a
                # bad frame is served, recovery's exact contract)
                (claimed,) = _LEN.unpack_from(buf, 0)
                if claimed < MIN_BODY or len(buf) >= _LEN.size + claimed:
                    return
            buf = buf[last_end:]
            base += last_end


def atomic_write(path: str, data: bytes, fsync: bool = True) -> None:
    """tmp + rename publication for manifest/offsets compaction — a
    reader never observes a half-written file.  Lives here (not at call
    sites) for the same R9 reason SegmentWriter exists."""
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        if fsync:
            fh.flush()
            os.fsync(fh.fileno())
    os.replace(tmp, path)


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a completed rename survives power loss.

    `os.replace` makes publication atomic against readers; making it
    durable needs the parent directory's metadata flushed too.  Lives
    here for the R9 reason above: fsync promises are made in one
    package (the mlops registry and the orbax checkpoint wrapper call
    this instead of growing their own fsync)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
