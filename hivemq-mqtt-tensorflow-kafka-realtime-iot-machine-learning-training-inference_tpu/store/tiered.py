"""Tiered log — local hot segments + ArtifactStore cold tier, one log.

`TieredLog` extends `SegmentedLog` with a remote tier (remote.py):
sealed segments upload in the background (`TierUploader`), local
retention becomes a hot-tier cache with its own eviction policy, and
every read API — ``read_from`` / ``read_raw`` / ``read_since`` /
``offset_for_timestamp`` — falls through to the remote tier when the
requested offset is below the local base.  The fall-through is
*transparent* by construction: ``base_offset`` reports the EARLIEST
offset retained in either tier, so the broker's out-of-range check,
the consumer's auto-reset accounting, the follower bootstrap mirror
and the twin changelog rebuild all see one log that simply retains
weeks instead of hours.  Remote segments are served through a bounded
`RemoteSegmentCache` that mounts each download as a read-only
single-segment `SegmentedLog` — the SAME frame scan, sparse index and
raw-read path as local segments, so the columnar decoder rides the
remote leg unchanged (the paper's one-hot-path rule, pinned by the
call-counted decoder test).

Segment lifecycle across tiers::

    active ──roll──▶ sealed ──upload+commit──▶ sealed+remote ──evict──▶ remote-only
                        │                          │                       │
                        │ (compaction rewrites:    │ (local retention /    │ (remote
                        │  size changes → the      │  hot-byte eviction    │  retention
                        │  uploader re-uploads,    │  may drop the local   │  drops the
                        │  same base replaces      │  copy — ONLY after    │  manifest
                        │  the manifest entry)     │  the manifest commit) │  entry, then
                        ▼                          ▼                       ▼  the blobs)

Two invariants the chaos scenario (`tier-upload-crash`) and the tests
pin:

- the LOCAL copy is authoritative until the remote manifest commits —
  local retention and hot eviction refuse to drop a segment the
  manifest does not list byte-for-byte;
- only sealed bytes below the quorum HWM ever tier out (the uploader
  is handed ``replication.fetch_ceiling`` as its ceiling), so the
  read-barrier semantics of acks=all are untouched.

Knobs ride the ``tier.*`` config section (``IOTML_TIER_URI``,
``IOTML_TIER_LOCAL_HOT_BYTES``, ``IOTML_TIER_UPLOAD_LAG_S``,
``IOTML_TIER_REMOTE_RETENTION_MS``, ``IOTML_TIER_CACHE_SEGMENTS``,
``IOTML_TIER_INTERVAL_S``).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics
from .log import (SegmentedLog, StorePolicy, _seg_name, store_replay_records)
from .remote import RemoteSegmentMeta, RemoteTier

tier_remote_records = obs_metrics.default_registry.counter(
    "iotml_tier_remote_records_total",
    "records served from remote-tier segments (read fall-through)")
tier_hot_evicted = obs_metrics.default_registry.counter(
    "iotml_tier_hot_evicted_bytes_total",
    "local hot-tier bytes evicted after their remote manifest commit")
tier_errors = obs_metrics.default_registry.counter(
    "iotml_tier_errors_total",
    "tier upload/sweep pass failures (logged, retried next interval)")

_CACHE_DIR = ".tiercache"


class TierPolicy:
    """The ``tier.*`` knobs (config.TierConfig's runtime mirror)."""

    def __init__(self, uri: str = "", local_hot_bytes: int = 0,
                 upload_lag_s: float = 0.0, remote_retention_ms: int = 0,
                 cache_segments: int = 4, interval_s: float = 5.0):
        self.uri = uri
        #: hot-tier byte budget per partition; 0 = never evict (the
        #: remote tier is then a pure replica of local history)
        self.local_hot_bytes = int(local_hot_bytes)
        #: minimum time a segment stays sealed before upload — lets the
        #: compactor's first pass over fresh seals win the race so the
        #: tier mostly stores compacted bytes
        self.upload_lag_s = float(upload_lag_s)
        #: age cap for remote history (0 = keep forever — "weeks" is
        #: the point); anchored at the log-wide newest timestamp
        self.remote_retention_ms = int(remote_retention_ms)
        #: bounded RemoteSegmentCache entries per partition
        self.cache_segments = int(cache_segments)
        #: background TierUploader cadence
        self.interval_s = float(interval_s)

    @classmethod
    def from_config(cls, tier_cfg) -> "TierPolicy":
        return cls(uri=tier_cfg.uri,
                   local_hot_bytes=tier_cfg.local_hot_bytes,
                   upload_lag_s=tier_cfg.upload_lag_s,
                   remote_retention_ms=tier_cfg.remote_retention_ms,
                   cache_segments=tier_cfg.cache_segments,
                   interval_s=tier_cfg.interval_s)

    def __bool__(self) -> bool:
        return bool(self.uri)


class RemoteSegmentCache:
    """Bounded LRU of downloaded remote segments, each mounted as a
    read-only single-segment `SegmentedLog`.

    The mount's full CRC scan doubles as the serve gate: a blob that
    passed the size/CRC check but holds a torn frame would be truncated
    by recovery — we refuse to serve that too (`recovered_truncated_
    bytes` must be zero), so a remote read can never return bytes the
    manifest didn't commit."""

    def __init__(self, dir: str, max_segments: int = 4):
        self.dir = dir
        self.max_segments = max(1, int(max_segments))
        self._entries: "OrderedDict[int, SegmentedLog]" = OrderedDict()

    def get(self, meta: RemoteSegmentMeta, remote: RemoteTier) -> SegmentedLog:
        log = self._entries.get(meta.base)
        if log is not None:
            self._entries.move_to_end(meta.base)
            return log
        dest = os.path.join(self.dir, _seg_name(meta.base))
        remote.fetch_segment(meta, dest)
        log = SegmentedLog(dest, policy=StorePolicy(fsync="never"))
        if log.recovered_truncated_bytes or log.total_bytes() != meta.size:
            log.close()
            shutil.rmtree(dest, ignore_errors=True)
            raise OSError(f"remote segment {meta.base} failed the frame "
                          f"scan; refusing to serve uncommitted bytes")
        self._entries[meta.base] = log
        while len(self._entries) > self.max_segments:
            _base, old = self._entries.popitem(last=False)
            old.close()
            shutil.rmtree(old.dir, ignore_errors=True)
        return log

    def drop(self, base: int) -> None:
        """Invalidate one entry (its remote blob was replaced by a
        compacted re-upload, or retention dropped it)."""
        log = self._entries.pop(base, None)
        if log is not None:
            log.close()
            shutil.rmtree(log.dir, ignore_errors=True)

    def clear(self) -> None:
        for base in list(self._entries):
            self.drop(base)

    def __len__(self) -> int:
        return len(self._entries)


class TieredLog(SegmentedLog):
    """SegmentedLog + a remote tier.  See the module docstring.

    Thread-safety matches the base class: the broker serializes
    mutation under its lock; reads snapshot.  `tier_sync` (the uploader
    thread's entry) does its blob I/O OUTSIDE any lock and publishes
    manifest/segment-list updates under the lock it is handed."""

    def __init__(self, dir: str, policy: Optional[StorePolicy] = None,
                 remote: Optional[RemoteTier] = None,
                 tier: Optional[TierPolicy] = None,
                 metric_labels: Optional[dict] = None):
        self.remote = remote
        self.tier = tier or TierPolicy()
        self._remote_metas: List[RemoteSegmentMeta] = []
        #: base → monotonic time first seen sealed (upload-lag clock;
        #: monotonic on purpose — R1's wall-clock rule)
        self._sealed_seen: Dict[int, float] = {}
        self.cache = RemoteSegmentCache(
            os.path.join(dir, _CACHE_DIR),
            max_segments=self.tier.cache_segments)
        super().__init__(dir, policy=policy, metric_labels=metric_labels)
        if self.remote is not None:
            try:
                self._remote_metas = self.remote.load()
            except (OSError, ValueError):
                # unreachable/garbled tier at mount: local history still
                # serves; the uploader's next pass re-reads the manifest
                self._remote_metas = []

    # ------------------------------------------------------------- state
    @property
    def base_offset(self) -> int:
        """Earliest offset retained in EITHER tier — what the broker's
        out-of-range check (and the consumer's auto-reset) sees."""
        local = self._segments[0].base_offset
        metas = self._remote_metas
        if metas and metas[0].base < local:
            return metas[0].base
        return local

    @property
    def local_base_offset(self) -> int:
        return self._segments[0].base_offset

    def remote_metas(self) -> List[RemoteSegmentMeta]:
        return list(self._remote_metas)

    @staticmethod
    def _meta_for(metas: List[RemoteSegmentMeta],
                  offset: int) -> Optional[RemoteSegmentMeta]:
        ans = None
        for m in metas:
            if m.base <= offset:
                ans = m
            else:
                break
        return ans

    def _local_floor(self) -> int:
        """First offset the LOCAL segments can serve.  Normally the
        local base; on a cold mount whose local log is still empty
        (a bootstrapping follower pointed at an existing tier)
        everything committed lives remotely, so the floor is the
        remote end."""
        local = self._segments[0].base_offset
        if self._remote_metas and self.end_offset <= local:
            return max(local, self._remote_metas[-1].next)
        return local

    def _remote_below_local(self) -> List[RemoteSegmentMeta]:
        local = self._local_floor()
        return [m for m in self._remote_metas if m.base < local]

    # -------------------------------------------------------------- read
    def read_from(self, offset: int, max_records: int = 1024,
                  _count_replay: bool = False) -> List[tuple]:
        local = self._local_floor()
        if self.remote is None or offset >= local:
            return super().read_from(offset, max_records, _count_replay)
        metas = self._remote_below_local()
        if not metas or offset < metas[0].base:
            raise LookupError(
                f"offset {offset} below retained base {self.base_offset}")
        out: List[tuple] = []
        remote_served = 0
        while len(out) < max_records and offset < local:
            m = self._meta_for(metas, offset)
            if m is None or offset >= m.next:
                # a hole between remote segments (remote retention, or a
                # compaction-punched gap): jump it — but only at the
                # START of a batch, the same no-mid-batch-gap rule as
                # the local scan (read_from's hole jump)
                if out:
                    break
                nxt = [x for x in metas if x.base > offset]
                offset = nxt[0].base if nxt else local
                continue
            try:
                cached = self.cache.get(m, self.remote)
            except (OSError, ValueError):
                if out:
                    break
                raise LookupError(
                    f"remote segment {m.base} unavailable; offset "
                    f"{offset} reads as trimmed history") from None
            chunk = cached.read_from(offset, max_records - len(out))
            if not chunk:
                offset = m.next
                continue
            if out and chunk[0][0] != out[-1][0] + 1:
                break  # never hide a gap mid-batch
            out.extend(chunk)
            remote_served += len(chunk)
            offset = chunk[-1][0] + 1
        if len(out) < max_records and offset >= local:
            if not out:
                return super().read_from(offset, max_records, _count_replay)
            # remote→local crossing inside one batch: only if contiguous
            try:
                more = super().read_from(offset, max_records - len(out))
            except LookupError:
                more = []
            if more and more[0][0] == out[-1][0] + 1:
                out.extend(more)
        if remote_served:
            tier_remote_records.inc(remote_served)
        if _count_replay and out:
            store_replay_records.inc(len(out))
        return out

    def read_raw(self, offset: int, max_bytes: int = 1 << 20
                 ) -> Optional[Tuple[bytes, int]]:
        local = self._local_floor()
        if self.remote is None or offset >= local:
            return super().read_raw(offset, max_bytes)
        metas = self._remote_below_local()
        if not metas or offset < metas[0].base:
            raise LookupError(
                f"offset {offset} below retained base {self.base_offset}")
        for _ in range(len(metas) + 1):
            if offset >= local:
                return super().read_raw(offset, max_bytes)
            m = self._meta_for(metas, offset)
            if m is None or offset >= m.next:
                nxt = [x for x in metas if x.base > offset]
                offset = nxt[0].base if nxt else local
                continue
            try:
                cached = self.cache.get(m, self.remote)
            except (OSError, ValueError):
                raise LookupError(
                    f"remote segment {m.base} unavailable; offset "
                    f"{offset} reads as trimmed history") from None
            res = cached.read_raw(offset, max_bytes)
            if res is not None:
                return res
            offset = m.next  # compaction-emptied remote segment: jump
        return super().read_raw(local, max_bytes)

    def offset_for_timestamp(self, timestamp_ms: int) -> int:
        if self.remote is not None:
            for m in self._remote_below_local():
                if m.max_ts < timestamp_ms:
                    continue
                try:
                    cached = self.cache.get(m, self.remote)
                except (OSError, ValueError):
                    continue  # trimmed-history semantics: later wins
                off = cached.offset_for_timestamp(timestamp_ms)
                if off < cached.end_offset:
                    return off
        return super().offset_for_timestamp(timestamp_ms)

    # --------------------------------------------------------- retention
    def _committed_remotely(self, s) -> bool:
        """True when the manifest lists this exact local segment —
        base, next_offset AND size byte-for-byte.  A compacted rewrite
        changes the size, so a not-yet-re-uploaded rewrite is NOT
        covered and the local copy stays authoritative."""
        m = self._meta_for(self._remote_metas, s.base_offset)
        return m is not None and m.base == s.base_offset \
            and m.next == s.next_offset and m.size == s.size

    def enforce_retention(self) -> int:
        if self.remote is None:
            return super().enforce_retention()
        dropped = 0
        pol = self.policy
        newest_ts = max((s.max_ts for s in self._segments), default=-1)
        while len(self._segments) > 1:
            head = self._segments[0]
            over_bytes = pol.retention_bytes and \
                self.total_bytes() > pol.retention_bytes
            over_count = pol.retention_messages and \
                (self.end_offset - self._segments[1].base_offset
                 >= pol.retention_messages)
            over_age = pol.retention_ms and newest_ts >= 0 and \
                0 <= head.max_ts < newest_ts - pol.retention_ms
            if not (over_bytes or over_count or over_age):
                break
            if not self._committed_remotely(head):
                # local is authoritative until the remote manifest
                # commits: retention WAITS rather than losing the only
                # copy (the uploader's next pass unblocks it)
                break
            dropped += head.next_offset - head.base_offset
            self._drop_head_segment()
        if dropped:
            self._update_size_gauge()
        return dropped

    def _drop_head_segment(self) -> None:
        head = self._segments[0]
        self._total_bytes -= head.size
        os.remove(head.path)
        self._remove_sidecars(head.base_offset)
        self._segments.pop(0)
        self._sealed_seen.pop(head.base_offset, None)

    def evict_hot(self, budget_bytes: Optional[int] = None) -> int:
        """Evict remote-committed head segments past the hot-tier byte
        budget (``tier.local_hot_bytes``); the records stay readable
        through the remote fall-through.  An explicit ``budget_bytes``
        overrides the policy (0 = evict every covered sealed segment —
        the cold-backfill bench and the trim tests use this)."""
        if self.remote is None:
            return 0
        budget = self.tier.local_hot_bytes if budget_bytes is None \
            else int(budget_bytes)
        if budget_bytes is None and not budget:
            return 0
        evicted = 0
        while len(self._segments) > 1 and self._total_bytes > budget:
            head = self._segments[0]
            if not self._committed_remotely(head):
                break  # manifest first, eviction second — always
            evicted += head.size
            self._drop_head_segment()
        if evicted:
            self._update_size_gauge()
            tier_hot_evicted.inc(evicted)
        return evicted

    # ------------------------------------------------------------ upload
    def tier_sync(self, ceiling: Optional[int] = None, lock=None,
                  upload_lag_s: Optional[float] = None) -> dict:
        """One tiering pass: upload eligible sealed segments, evict the
        hot tier, enforce remote retention, sweep garbage.  Blob I/O
        runs outside ``lock`` (the broker lock); manifest/segment-list
        publication happens inside it.  ``ceiling`` bounds what may
        tier out (the quorum HWM — only replicated bytes leave the hot
        tier); None = unreplicated, everything sealed is eligible."""
        if self.remote is None:
            return {"uploaded": 0, "bytes": 0, "evicted": 0,
                    "retained": 0, "retired": 0, "swept": 0}
        lock = lock if lock is not None else threading.Lock()
        lag = self.tier.upload_lag_s if upload_lag_s is None \
            else float(upload_lag_s)
        now = time.monotonic()
        with lock:
            self._persist_sidecars()  # uploads ship index sidecars too
            sealed = list(self._segments[:-1])
            metas_by_base = {m.base: m for m in self._remote_metas}
        uploaded, up_bytes = 0, 0
        for s in sealed:
            if ceiling is not None and s.next_offset > ceiling:
                break  # above the quorum HWM: not durable enough to tier
            first_seen = self._sealed_seen.setdefault(s.base_offset, now)
            if lag and now - first_seen < lag:
                continue
            m = metas_by_base.get(s.base_offset)
            if m is not None and m.next == s.next_offset \
                    and m.size == s.size:
                continue  # already committed, byte-for-byte
            idx = os.path.join(self.dir, _seg_name(s.base_offset) + ".index")
            tidx = os.path.join(self.dir,
                                _seg_name(s.base_offset) + ".timeindex")
            meta = self.remote.upload_segment(
                s.path, idx, tidx, base=s.base_offset,
                next_offset=s.next_offset, max_ts=s.max_ts)
            with lock:
                self._remote_metas = sorted(
                    [x for x in self._remote_metas if x.base != meta.base]
                    + [meta], key=lambda x: x.base)
                # a re-upload (compacted rewrite) invalidates any cached
                # download of the old blob
                self.cache.drop(meta.base)
            uploaded += 1
            up_bytes += meta.size
        # Compaction can MERGE sealed segments away entirely (their
        # survivors rewritten into a neighbor base).  A manifest entry
        # whose base lies inside the locally-covered sealed range but
        # matches no local segment is such an orphan: no re-upload will
        # ever replace it, and once the hot tier evicts it would serve
        # shadowed pre-compaction records.  Retire it BEFORE eviction
        # can make it reachable.  Entries below the local base are the
        # evicted history — those are the point of the tier; keep them.
        with lock:
            sealed_now = list(self._segments[:-1])
            local_bases = {s.base_offset for s in sealed_now}
            stale = []
            if sealed_now:
                lo = sealed_now[0].base_offset
                hi = sealed_now[-1].next_offset
                stale = [m for m in self._remote_metas
                         if lo <= m.base < hi and m.base not in local_bases]
        retired = 0
        if stale:
            dropped = self.remote.retire([m.base for m in stale])
            with lock:
                gone = {m.base for m in dropped}
                self._remote_metas = [m for m in self._remote_metas
                                      if m.base not in gone]
                for base in gone:
                    self.cache.drop(base)
            retired = len(dropped)
        with lock:
            evicted = self.evict_hot()
        retained = 0
        if self.tier.remote_retention_ms:
            newest_ts = max(
                [s.max_ts for s in self._segments]
                + [m.max_ts for m in self._remote_metas] or [-1])
            dropped = self.remote.enforce_retention(
                self.tier.remote_retention_ms, newest_ts)
            if dropped:
                with lock:
                    gone = {m.base for m in dropped}
                    self._remote_metas = [m for m in self._remote_metas
                                          if m.base not in gone]
                    for base in gone:
                        self.cache.drop(base)
                retained = len(dropped)
        swept = self.remote.sweep()
        return {"uploaded": uploaded, "bytes": up_bytes,
                "evicted": evicted, "retained": retained,
                "retired": retired, "swept": swept}

    def close(self) -> None:
        self.cache.clear()
        super().close()


# ---------------------------------------------------- background uploader
class TierUploader:
    """Background tiering for one broker: periodically runs
    ``broker.run_tiering()`` (upload → evict → remote retention →
    sweep per tiered partition).  Same supervised-thread discipline as
    `StoreCompactor` (lint R8); ``run_once`` is the deterministic entry
    tests, drills and the chaos runner drive directly."""

    def __init__(self, broker, interval_s: float = 5.0):
        self.broker = broker
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_once(self) -> dict:
        return self.broker.run_tiering()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except (OSError, RuntimeError, ValueError):
                # a transient pass failure (unreachable bucket, ENOSPC
                # on the stage copy, a chaos kill) must not stop the
                # tier: count it, retry next interval — the local copy
                # is still authoritative
                tier_errors.inc()

    def start(self) -> "TierUploader":
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self._loop, daemon=True, name="iotml-tier-uploader"))
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
