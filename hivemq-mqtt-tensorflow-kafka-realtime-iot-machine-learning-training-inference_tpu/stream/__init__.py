from .broker import Broker, TopicSpec, Message  # noqa: F401
from .consumer import StreamConsumer, parse_spec  # noqa: F401
from .producer import OutputSequence  # noqa: F401
from .csv_source import replay_csv  # noqa: F401
from .group import GroupCoordinator, GroupConsumer  # noqa: F401
from .registry import SchemaRegistry, RegisteredSchema, parse_avsc  # noqa: F401
from .registry_server import SchemaRegistryServer  # noqa: F401
from .replica import FollowerReplica  # noqa: F401
