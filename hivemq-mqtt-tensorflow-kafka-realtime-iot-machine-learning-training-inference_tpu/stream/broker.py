"""In-process broker emulator — the framework's test/dev data backbone.

The reference's data plane is a full Confluent deployment (3 brokers, topics
`sensor-data` / `model-predictions` with 10 partitions, RF 3 — reference
`01_installConfluentPlatform.sh:180-183`), and its offline test story is a
FileStreamSource connector replaying a CSV into a topic (reference
`testdata/Test-Load-csv/`).  This module provides the equivalent in-process:
a partitioned, offset-addressed append-only log with consumer-group offset
storage and optional size/retention bounds, so every pipeline in the
framework — train, score, streamproc, generator — runs unchanged against it.

Two partition backends behind one `Broker`:

- **in-memory** (default): a Python list per partition — fast, dies with
  the process;
- **durable** (`store_dir=`): an `iotml.store.SegmentedLog` per
  partition — CRC-framed segments on disk, crash recovery at mount,
  retention by bytes and time, committed consumer offsets persisted in
  a compacted offsets file.  This is what makes the paper's "train from
  the commit log, no data lake" claim survive a restart.

The same `Broker` duck-type is what the native (C++) engine and a real
librdkafka-backed client expose, so swapping the emulator for a real cluster
is a constructor change, not a code path change.

Threading: one lock guards all mutation (topic metadata, appends, retention
trims) and `fetch` — producers and background prefetch threads interleave
freely.  This is the correctness-first emulator; the native C++ engine owns
the lock-free hot path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import zlib
from typing import Dict, List, NamedTuple, Optional

from ..chaos import faults as chaos


class TopicOwnershipError(PermissionError):
    """Produce to an engine-owned topic without the owner's grant.

    Engine-owned topics (the stream-proc AVRO leg and its derivatives)
    are written exclusively by the owning engine — that exclusivity is
    what makes trusted_passthrough sound (the engine skips re-validating
    bytes only its own validating encoder could have written).  The wire
    server maps this to Kafka's TOPIC_AUTHORIZATION_FAILED."""


class SchemaIdMismatchError(ValueError):
    """A fused/columnar decode found a Confluent writer-schema id other
    than the reader's pinned id at the current cursor.

    The runtime guard behind the v1-only fast paths: instead of blind-
    stripping 5 bytes and positionally mis-reading an evolved (v2)
    writer's record, the native decoders STOP at the foreign frame and
    raise this — the consumer re-reads that chunk through the name-
    resolving Python path (`ops.avro.ResolvingCodec`) and then resumes
    the fast path.  Nothing is consumed past the mismatch."""

    def __init__(self, topic: str, partition: int, offset: int):
        super().__init__(
            f"non-pinned Confluent schema id at {topic}:{partition}"
            f"@{offset}: evolved writer on a pinned topic — resolve by "
            f"name in Python (chunk fallback), never strip blindly")
        self.topic = topic
        self.partition = partition
        self.offset = offset


class CorruptMessageError(ValueError):
    """A pre-framed RAW_PRODUCE batch failed CRC/offset validation.

    The whole batch is rejected BEFORE any byte lands in the segment —
    no torn/partial appends, ever (the write-path twin of crash
    recovery's truncate-at-first-bad-frame).  The wire server answers
    Kafka CORRUPT_MESSAGE (2); the producing client re-frames and
    redelivers (caller-owns-redelivery, like every produce)."""

    def __init__(self, topic: str, partition: int, index: int):
        super().__init__(
            f"corrupt pre-framed batch for {topic}:{partition} at frame "
            f"{index}: whole batch rejected, nothing appended "
            f"(Kafka CORRUPT_MESSAGE)")
        self.topic = topic
        self.partition = partition
        self.index = index


class OffsetOutOfRangeError(LookupError):
    """Fetch below the partition's retained base offset.

    Retention (or a replica realignment) trimmed the log head past the
    requested offset.  The old behavior — silently clamping the read
    forward — made trimmed history indistinguishable from delivered
    history; now the signal is explicit: the wire server answers Kafka
    error 1 (OFFSET_OUT_OF_RANGE), the wire client re-raises this, and
    `StreamConsumer` implements the documented auto-reset-to-earliest
    (`auto.offset.reset=earliest` semantics)."""

    def __init__(self, topic: str, partition: int, offset: int,
                 earliest: int):
        super().__init__(
            f"fetch {topic}:{partition}@{offset} below retained base "
            f"{earliest}: the log head was trimmed (retention); consumers "
            f"auto-reset to earliest, raw callers decide")
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.earliest = earliest


# Thread-local produce grants: a thread pumping an owning engine enters
# `producer_grant(token)` and may produce to the topics that token
# restricts; every other producer is rejected.  Thread-local (not an
# instance flag) so a grant cannot leak across the wire server's
# handler threads.
_grants = threading.local()


class Message(NamedTuple):
    """One record as fetched from a partition log.

    A NamedTuple, not a dataclass: fetch constructs one per record on the
    hot path, and the frozen-dataclass __init__ (object.__setattr__ per
    field) was the single largest cost left in the KSQL pump profile —
    tuple construction is C-speed with the same immutable attribute API."""

    topic: str
    partition: int
    offset: int
    value: bytes
    key: Optional[bytes] = None
    timestamp_ms: int = 0
    #: optional ((name, value), ...) record headers — the trace-context
    #: carrier (obs.tracing): metadata rides beside the payload so the
    #: Avro bytes are untouched.  None (the untraced default) costs
    #: nothing.  Wire/native clients drop headers (no MessageSet v1
    #: slot); the durable backend round-trips them in their transport
    #: byte form (tracing.from_headers accepts both).
    headers: Optional[tuple] = None


@dataclasses.dataclass
class TopicSpec:
    name: str
    partitions: int = 1
    # retention by message count (deterministic test-friendly bound),
    # by total bytes, and by record-timestamp age — the reference sets
    # retention.ms=100000 (01_installConfluentPlatform.sh:180-183);
    # retention_ms is that knob's native analog.  None = UNSET (durable
    # brokers fall back to the store-wide policy default; in-memory has
    # no default, so unbounded); 0 = EXPLICITLY unlimited (the wire
    # maps Kafka's retention.*=-1 sentinel here) — the only way a topic
    # on a durable broker opts out of the store default.
    retention_messages: Optional[int] = None
    retention_bytes: Optional[int] = None
    retention_ms: Optional[int] = None
    # "delete" (default) reclaims whole segments by retention; "compact"
    # additionally reclaims records shadowed by a newer record with the
    # same key (Kafka's cleanup.policy) — the changelog-topic contract
    # the digital twin's CAR_TWIN rides on.  Durable brokers compact
    # sealed segments in place (store/compact.py); the in-memory backend
    # keeps the policy as metadata only (its logs die with the process,
    # so there is nothing to reclaim durably).
    cleanup_policy: str = "delete"


class _Partition:
    """In-memory partition: the list-backed log (the seed backend)."""

    __slots__ = ("log", "base_offset", "bytes", "max_ts")

    def __init__(self):
        self.log: List[tuple] = []  # (key, value, ts, headers)
        self.base_offset = 0  # offset of log[0] after retention trimming
        self.bytes = 0        # payload bytes retained (retention_bytes)
        self.max_ts = 0       # newest record ts seen (retention_ms anchor)

    # one method per broker touch-point so the durable partition can
    # substitute — the broker's lock discipline stays identical
    def append(self, key, value, ts, headers, sync: bool = True) -> int:
        self.log.append((key, value, ts, headers))
        # value None = tombstone (compaction's delete marker): zero bytes
        self.bytes += (len(value) if value else 0) + (len(key) if key else 0)
        if ts > self.max_ts:
            self.max_ts = ts
        return self.base_offset + len(self.log) - 1

    def sync_batch(self) -> None:
        pass  # durability is the durable backend's concern

    def note_replay(self, n: int) -> None:
        pass  # iotml_store_* metrics are the durable backend's alone

    def end(self) -> int:
        return self.base_offset + len(self.log)

    def base(self) -> int:
        return self.base_offset

    def read(self, offset: int, max_messages: int) -> List[tuple]:
        """[(offset, key, value, ts, headers)] from `offset`."""
        idx = offset - self.base_offset
        return [(offset + i, key, value, ts, hdrs)
                for i, (key, value, ts, hdrs)
                in enumerate(self.log[idx:idx + max_messages])]

    def read_raw(self, offset: int, max_bytes: int) -> Optional[tuple]:
        """Store-format frame bytes from `offset` (the raw-batch duck-
        type shared with `_DurablePartition`).  The in-memory emulator
        has no on-disk frames, so it RE-FRAMES the slice through the one
        frame codec (`ops.framing.encode_frame_batch`) — the
        compatibility path; the durable backend serves disk bytes
        directly.  Returns (frame_bytes, start_offset) or None."""
        from ..ops.framing import encode_frame_batch

        idx = offset - self.base_offset
        if idx >= len(self.log):
            return None
        out = []
        size = 0
        i = idx
        while i < len(self.log) and size < max_bytes:
            key, value, ts, hdrs = self.log[i]
            out.append((offset + (i - idx), key, value, ts, hdrs))
            size += (len(value) if value else 0) + \
                (len(key) if key else 0) + 64
            i += 1
        return encode_frame_batch(out), offset

    def drop_head(self, count: int) -> None:
        for key, value, _ts, _h in self.log[:count]:
            self.bytes -= (len(value) if value else 0) + \
                (len(key) if key else 0)
        del self.log[:count]
        self.base_offset += count

    def enforce_retention(self, spec: TopicSpec) -> None:
        if spec.retention_messages and len(self.log) > spec.retention_messages:
            self.drop_head(len(self.log) - spec.retention_messages)
        if spec.retention_bytes:
            drop = 0
            freed = 0
            while self.bytes - freed > spec.retention_bytes and \
                    drop < len(self.log) - 1:
                key, value, _ts, _h = self.log[drop]
                freed += (len(value) if value else 0) + \
                    (len(key) if key else 0)
                drop += 1
            if drop:
                self.drop_head(drop)
        if spec.retention_ms:
            # age against the NEWEST record timestamp (Kafka's rule),
            # tracked incrementally at append — an O(n) scan here would
            # run per produce under the broker lock.  Untimestamped
            # (ts=0) streams never age out, deterministically.
            cutoff = self.max_ts - spec.retention_ms
            drop = 0
            while drop < len(self.log) - 1 and self.log[drop][2] < cutoff:
                drop += 1
            if drop and self.log[drop - 1][2] < cutoff:
                self.drop_head(drop)

    def append_at(self, offset, key, value, ts, headers,
                  sync: bool = True) -> int:
        """Offset-explicit append — the replica's mirror path for
        COMPACTED topics.  The in-memory list is dense (it cannot hold
        offset holes), so only a gap-free continuation is representable;
        a true hole must realign via reset (the durable backend handles
        holes natively)."""
        end = self.base_offset + len(self.log)
        if int(offset) != end:
            raise ValueError(
                f"in-memory partition cannot represent an offset hole "
                f"({offset} != end {end}); mount a durable follower for "
                f"compacted-topic mirroring")
        return self.append(key, value, ts, headers, sync=sync)

    def append_raw(self, blob, count, first, last, max_ts,
                   sync: bool = True) -> int:
        """Land a validated raw frame batch.  The in-memory emulator has
        no segment to append bytes to, so it decodes through the ONE
        frame parser (`ops.framing.iter_frame_entries`) — the compat
        path; the durable backend appends the batch's own bytes.  Offset
        holes follow append_at's rule (dense list = gap-free only)."""
        from ..ops.framing import iter_frame_entries

        for off, key, value, ts, hdrs in iter_frame_entries(blob):
            self.append_at(off, key, value, ts, hdrs, sync=False)
        return first

    def align_base(self, offset: int) -> None:
        if self.log:
            raise ValueError("partition not empty; base is immutable")
        self.base_offset = max(self.base_offset, int(offset))

    def reset(self, base_offset: int) -> None:
        self.log.clear()
        self.bytes = 0
        self.max_ts = 0
        self.base_offset = int(base_offset)

    def offset_for_timestamp(self, ts_ms: int) -> int:
        for i, (_k, _v, ts, _h) in enumerate(self.log):
            if ts >= ts_ms:
                return self.base_offset + i
        return self.end()


class _DurablePartition:
    """Durable partition: an `iotml.store.SegmentedLog` behind the same
    touch-points as `_Partition`.  All three retention knobs map to
    whole-segment deletes (Kafka's own granularity): count/bytes/time
    caps may over-retain up to one segment, never under-retain."""

    __slots__ = ("slog",)

    def __init__(self, slog):
        self.slog = slog

    def append(self, key, value, ts, headers, sync: bool = True) -> int:
        return self.slog.append(key, value, ts, headers, sync=sync)

    def append_at(self, offset, key, value, ts, headers,
                  sync: bool = True) -> int:
        return self.slog.append_at(offset, key, value, ts, headers,
                                   sync=sync)

    def append_raw(self, blob, count, first, last, max_ts,
                   sync: bool = True) -> int:
        """Append a validated raw frame batch SEGMENT-VERBATIM — the
        batch's own bytes become the log's bytes, no re-serialisation
        (the zero-copy write path; offset holes reproduce exactly)."""
        return self.slog.append_raw(blob, count, first, last, max_ts,
                                    sync=sync)

    def sync_batch(self) -> None:
        self.slog.sync_batch()

    def note_replay(self, n: int) -> None:
        from ..store.log import store_replay_records

        store_replay_records.inc(n)

    def end(self) -> int:
        return self.slog.end_offset

    def base(self) -> int:
        return self.slog.base_offset

    def read(self, offset: int, max_messages: int) -> List[tuple]:
        return self.slog.read_from(offset, max_messages)

    def read_raw(self, offset: int, max_bytes: int) -> Optional[tuple]:
        return self.slog.read_raw(offset, max_bytes)

    def enforce_retention(self, spec: TopicSpec) -> None:
        pol = self.slog.policy
        # topic spec overrides the store-wide defaults when present
        prev = (pol.retention_bytes, pol.retention_ms,
                pol.retention_messages)
        if spec.retention_bytes is not None:
            pol.retention_bytes = spec.retention_bytes
        if spec.retention_ms is not None:
            pol.retention_ms = spec.retention_ms
        if spec.retention_messages is not None:
            pol.retention_messages = spec.retention_messages
        try:
            self.slog.enforce_retention()
        finally:
            (pol.retention_bytes, pol.retention_ms,
             pol.retention_messages) = prev

    def align_base(self, offset: int) -> None:
        self.slog.align_base(offset)

    def reset(self, base_offset: int) -> None:
        self.slog.reset(base_offset)

    def offset_for_timestamp(self, ts_ms: int) -> int:
        return self.slog.offset_for_timestamp(ts_ms)


class Broker:
    """Partitioned commit log with Kafka-shaped semantics.

    ``Broker()`` is the in-memory emulator.  ``Broker(store_dir=...)``
    mounts (and crash-recovers) a durable segmented log per partition:
    topics from the manifest are re-created before serving, committed
    consumer offsets load from the compacted offsets file, and every
    subsequent commit is persisted through it."""

    def __init__(self, store_dir: Optional[str] = None, store_policy=None,
                 tier=None):
        self._lock = threading.Lock()
        # serializes whole compaction PASSES (background compactor vs a
        # forced drill pass); the data lock above covers only the swaps
        self._compact_pass_lock = threading.Lock()
        # serializes whole TIERING passes the same way (background
        # TierUploader vs a drill/test's forced run_tiering)
        self._tier_pass_lock = threading.Lock()
        #: quorum replication state (iotml.replication.ReplicationState)
        #: when this broker LEADS replicated partitions — consulted by
        #: fetch/fetch_raw (consumer reads stop at the quorum high-water
        #: mark) and by the wire server (acks=all waits, follower fetch
        #: observations).  None = unreplicated, zero-cost.
        self.replication = None
        self._topics: Dict[str, TopicSpec] = {}
        self._parts: Dict[str, List] = {}
        self._group_offsets: Dict[tuple, int] = {}  # (group, topic, part) → next offset
        self._rr: Dict[str, int] = {}  # round-robin cursor per topic
        self._owned: Dict[str, object] = {}  # topic prefix → owner token
        self.store = None
        if store_dir:
            from ..store import StoreMount

            self.store = StoreMount(store_dir, policy=store_policy,
                                    tier=tier)
            for doc in self.store.topics():
                self.create_topic(
                    doc["name"], partitions=doc["partitions"],
                    retention_messages=doc.get("retention_messages"),
                    retention_bytes=doc.get("retention_bytes"),
                    retention_ms=doc.get("retention_ms"),
                    cleanup_policy=doc.get("cleanup_policy", "delete"))
            self._group_offsets.update(self.store.offsets.table())

    @property
    def durable(self) -> bool:
        return self.store is not None

    # --------------------------------------------------------- ownership
    def restrict_topic(self, prefix: str,
                       token: Optional[object] = None) -> object:
        """Mark every topic named `prefix`* engine-owned: produces are
        rejected (TopicOwnershipError) unless the calling thread holds
        the returned token via `producer_grant`.  Reads, commits and
        topic creation stay open — the invariant is write exclusivity."""
        token = token if token is not None else object()
        with self._lock:
            self._owned[prefix] = token
        return token

    @contextlib.contextmanager
    def producer_grant(self, token: object):
        """Authorize this thread to produce to the topics `token`
        restricts for the duration of the block (re-entrant)."""
        held = getattr(_grants, "tokens", None)
        if held is None:
            held = _grants.tokens = []
        held.append(token)
        try:
            yield self
        finally:
            held.pop()

    def _check_producer(self, topic: str) -> None:
        if not self._owned:
            return
        with self._lock:  # snapshot: restrict_topic may race a produce
            owned = list(self._owned.items())
        for prefix, token in owned:
            if topic.startswith(prefix) and \
                    token not in getattr(_grants, "tokens", ()):
                raise TopicOwnershipError(
                    f"topic {topic!r} is engine-owned (prefix {prefix!r}): "
                    f"produce requires the owner's grant "
                    f"(Broker.producer_grant)")

    # ------------------------------------------------------------- topics
    @staticmethod
    def _validate_retention(name: str, value: Optional[int]) -> Optional[int]:
        if value is not None and value < 0:
            # a negative cap would delete every produced record while
            # producers believe writes succeed
            raise ValueError(f"{name} must be >= 0 or None, got {value}")
        # 0 is preserved, not collapsed to None: on a durable broker
        # None means "inherit the store-wide default" while 0 means
        # "explicitly unlimited" — collapsing them made unlimited
        # unexpressible per topic (both read as unbounded in-memory)
        return value

    def _make_partition(self, topic: str, partition: int):
        if self.store is not None:
            return _DurablePartition(self.store.log_for(topic, partition))
        return _Partition()

    def create_topic(self, name: str, partitions: int = 1,
                     retention_messages: Optional[int] = None,
                     retention_bytes: Optional[int] = None,
                     retention_ms: Optional[int] = None,
                     cleanup_policy: str = "delete") -> TopicSpec:
        retention_messages = self._validate_retention(
            "retention_messages", retention_messages)
        retention_bytes = self._validate_retention(
            "retention_bytes", retention_bytes)
        retention_ms = self._validate_retention("retention_ms", retention_ms)
        if cleanup_policy not in ("delete", "compact"):
            # "compact,delete" deliberately unsupported as a single
            # string: compaction COMPOSES with retention here (both
            # apply when both are configured), so the combined form
            # would be redundant, not new semantics
            raise ValueError(f"cleanup_policy must be 'delete' or "
                             f"'compact', got {cleanup_policy!r}")
        with self._lock:
            if name in self._topics:
                return self._topics[name]
            spec = TopicSpec(name, partitions, retention_messages,
                             retention_bytes, retention_ms,
                             cleanup_policy)
            self._topics[name] = spec
            if self.store is not None:
                self.store.register_topic(
                    name, partitions,
                    retention_messages=retention_messages,
                    retention_bytes=retention_bytes,
                    retention_ms=retention_ms,
                    cleanup_policy=cleanup_policy)
            self._parts[name] = [self._make_partition(name, p)
                                 for p in range(partitions)]
            self._rr[name] = 0
            return spec

    def topics(self) -> List[str]:
        with self._lock:
            return sorted(self._topics)

    def topic(self, name: str) -> TopicSpec:
        return self._topics[name]

    def _partition_for(self, topic: str, key: Optional[bytes]) -> int:
        n = self._topics[topic].partitions
        if key is None:
            self._rr[topic] = (self._rr[topic] + 1) % n
            return self._rr[topic]
        # stable keyed partitioning (murmur-free but deterministic)
        return zlib.crc32(key) % n

    # ------------------------------------------------------------ produce
    def produce(self, topic: str, value: Optional[bytes],
                key: Optional[bytes] = None,
                partition: Optional[int] = None, timestamp_ms: int = 0,
                headers: Optional[tuple] = None) -> int:
        """Append one record; returns its offset. Auto-creates 1-partition
        topics (matching Kafka's auto.create default used by the reference's
        local demos).  ``value=None`` appends a TOMBSTONE (Kafka's null
        value): on a ``cleanup.policy=compact`` topic it deletes the key
        once compaction's grace window passes; fetches surface it as
        ``Message.value is None``, never as an empty payload."""
        chaos.point("broker.produce")
        self._check_producer(topic)
        if topic not in self._topics:
            self.create_topic(topic)
        with self._lock:
            p = self._partition_for(topic, key) if partition is None else partition
            part = self._parts[topic][p]
            off = part.append(key, value, timestamp_ms, headers)
            part.enforce_retention(self._topics[topic])
            return off

    def produce_batch(self, topic: str, values, key=None, partition=None) -> int:
        """Append many records; returns the offset of the last one."""
        off = -1
        for v in values:
            off = self.produce(topic, v, key=key, partition=partition)
        return off

    @staticmethod
    def _raw_produce_enabled() -> bool:
        """IOTML_RAW_PRODUCE gate for the broker-internal durable
        framing fusion (on/auto = fused when the native engine loads;
        off = the per-record python encoder, the debug escape hatch)."""
        from ..data.pipeline import raw_produce_mode

        return raw_produce_mode() != "off"

    def produce_many(self, topic: str, entries,
                     partition: Optional[int] = None) -> int:
        """Bulk append [(key, value, timestamp_ms[, headers]), ...] under
        ONE lock acquisition; returns the offset of the last record
        appended.

        Same signature and return contract as the wire/native clients'
        produce_many (the Broker duck-type family), and the same
        per-record semantics as produce() (key-hash partitioning,
        retention trimming) — minus a lock round-trip and method dispatch
        per message, the ingest bridges' hot path.  The optional 4th
        element carries record headers (trace context); wire/native
        clients accept and drop it (no header slot on MessageSet v1).

        Durable backends FUSE the framing (ISSUE 12): each partition's
        slice is framed as ONE native batch (`ops.framing.frame_entries`,
        byte-identical to the per-record codec) and appended
        segment-verbatim — the per-record python encode loop disappears
        behind a batch call.  Traced entries (record headers) keep the
        per-record path, which is the headers' only encoder."""
        chaos.point("broker.produce")
        self._check_producer(topic)
        entries = list(entries)
        if topic not in self._topics:
            self.create_topic(topic)
        last_off = -1
        fuse = self.store is not None and self._raw_produce_enabled()
        with self._lock:
            parts = self._parts[topic]
            spec = self._topics[topic]
            if fuse and entries and \
                    not any(len(e) > 3 and e[3] for e in entries):
                from ..ops.framing import frame_entries
                by_part: Dict[int, list] = {}
                last_p = partition
                if partition is None:
                    for entry in entries:
                        p = self._partition_for(topic, entry[0])
                        by_part.setdefault(p, []).append(entry)
                        last_p = p
                else:
                    by_part[partition] = entries
                ends: Dict[int, int] = {}
                for p, ents in by_part.items():
                    part = parts[p]
                    base = part.end()
                    blob = frame_entries(ents, base)
                    part.append_raw(blob, len(ents), base,
                                    base + len(ents) - 1,
                                    max(e[2] for e in ents), sync=False)
                    ends[p] = base + len(ents) - 1
                    part.sync_batch()
                    part.enforce_retention(spec)
                # same return contract as the per-record loop: the offset
                # the FINAL entry landed at (its partition's batch end)
                return ends[last_p]
            touched = set()
            for entry in entries:
                key, value, ts = entry[0], entry[1], entry[2]
                p = self._partition_for(topic, key) if partition is None \
                    else partition
                last_off = parts[p].append(
                    key, value, ts, entry[3] if len(entry) > 3 else None,
                    sync=False)
                touched.add(p)
            for p in touched:
                # ONE fsync per touched partition per batch (fsync=always):
                # the ack (this method returning) still follows the sync,
                # so everything acked is durable — per-record fsync would
                # only add latency, not safety.  Retention likewise:
                # untouched partitions cannot have grown past their caps.
                parts[p].sync_batch()
                parts[p].enforce_retention(spec)
        return last_off

    def produce_at(self, topic: str, partition: int, offset: int,
                   value: Optional[bytes], key: Optional[bytes] = None,
                   timestamp_ms: int = 0,
                   headers: Optional[tuple] = None) -> int:
        """Append one record AT an explicit offset at/after the log end —
        the replica's mirror path for compacted topics, whose fetched
        batches carry offset holes.  Forward jumps reproduce the hole on
        the durable backend; the in-memory backend accepts only gap-free
        continuations (ValueError otherwise — the replica surfaces it as
        a sync error instead of silently renumbering)."""
        self._check_producer(topic)
        if topic not in self._topics:
            self.create_topic(topic)
        with self._lock:
            return self._parts[topic][partition].append_at(
                offset, key, value, timestamp_ms, headers)

    # -------------------------------------------------------- raw produce
    def produce_raw(self, topic: str, partition: int,
                    frames: bytes) -> int:
        """Append a PRE-FRAMED batch (contiguous store frames, offsets
        unstamped) — the RAW_PRODUCE landing: every CRC is validated
        WHOLE-batch first, then the real log offsets are stamped into
        the frame heads (CRCs recomputed) and the durable backend
        appends the batch's own bytes segment-verbatim; the in-memory
        emulator decodes through the one `ops.framing` parser (compat
        path).  Returns the batch's base offset.

        A torn/corrupt batch raises `CorruptMessageError` BEFORE any
        byte lands (Kafka CORRUPT_MESSAGE=2 on the wire): no partial
        appends, acked counts and replay stay byte-identical after a
        rejection.  NOT idempotent — caller owns redelivery, exactly
        like produce."""
        from ..ops import framing as _fr

        act = chaos.point("broker.produce_raw")
        if act is not None and act.kind == "corrupt":
            # seeded corruption of the in-flight batch: one flipped byte
            # must reject the WHOLE batch with zero bytes landed
            mangled = bytearray(frames)
            if mangled:
                mangled[len(mangled) // 2] ^= 0xFF
            frames = bytes(mangled)
        self._check_producer(topic)
        if topic not in self._topics:
            self.create_topic(topic, partitions=max(partition + 1, 1))
        part = self._parts[topic][partition]
        with self._lock:
            base = part.end()
            try:
                stamped, count, max_ts = _fr.restamp_frame_batch(
                    frames, base)
            except _fr.CorruptFrameError as e:
                raise CorruptMessageError(topic, partition,
                                          e.index) from e
            if count:
                part.append_raw(stamped, count, base, base + count - 1,
                                max_ts, sync=False)
                part.sync_batch()
                part.enforce_retention(self._topics[topic])
        return base

    def produce_raw_at(self, topic: str, partition: int,
                       frames: bytes) -> int:
        """Append a raw frame batch AT its own stamped offsets — the
        replica's zero-copy mirror leg (RAW_FETCH hands back frames
        with the leader's offsets already in the heads; after CRC
        validation they append verbatim, holes reproduced).  The
        in-memory backend decodes per record and accepts only gap-free
        continuations (append_at's rule).  Returns the last offset
        appended (-1 for an empty batch)."""
        from ..ops import framing as _fr

        self._check_producer(topic)
        if topic not in self._topics:
            self.create_topic(topic, partitions=max(partition + 1, 1))
        try:
            v = _fr.validate_frame_batch(frames, strict=True)
        except _fr.CorruptFrameError as e:
            raise CorruptMessageError(topic, partition, e.index) from e
        if not v["count"]:
            return -1
        part = self._parts[topic][partition]
        with self._lock:
            end = part.end()
            if v["first"] < end:
                raise ValueError(
                    f"raw mirror batch for {topic}:{partition} starts at "
                    f"{v['first']} behind log end {end}: offsets only "
                    f"move forward")
            part.append_raw(frames, v["count"], v["first"], v["last"],
                            v["max_ts"], sync=False)
            part.sync_batch()
            part.enforce_retention(self._topics[topic])
        return v["last"]

    # ---------------------------------------------------------- compaction
    def run_compaction(self, force: bool = False) -> Dict[tuple, object]:
        """One compaction pass over every ``cleanup.policy=compact``
        topic partition (durable broker only — the in-memory backend has
        nothing durable to reclaim).  Applies the dirty-ratio gate
        unless ``force``; returns {(topic, partition): CompactionStats}.
        Driven by the background ``store.StoreCompactor`` in production
        and called directly by tests/drills for determinism.

        Concurrency: whole passes are serialized by ``_compact_pass_lock``
        (background compactor vs a forced drill pass never interleave on
        the same segments); the broker data lock is taken only around
        each segment swap (`compact_log`), so produce/fetch proceed
        through a pass.  On a ShardBroker, unowned partitions hold no
        log and are skipped — each shard compacts only what it leads."""
        if self.store is None:
            return {}
        out: Dict[tuple, object] = {}
        pol = self.store.policy
        with self._compact_pass_lock:
            with self._lock:
                compacted = [(name, spec)
                             for name, spec in self._topics.items()
                             if spec.cleanup_policy == "compact"]
            for name, spec in compacted:
                for p in range(spec.partitions):
                    part = self._parts[name][p]
                    slog = getattr(part, "slog", None)
                    if slog is None:
                        continue  # cluster: partition not led by this shard
                    if not force and slog.dirty_ratio() < \
                            pol.compact_min_dirty_ratio:
                        continue
                    stats = slog.compact(grace_ms=pol.compact_grace_ms,
                                         lock=self._lock)
                    if stats.segments_rewritten:
                        out[(name, p)] = stats
        return out

    def run_tiering(self) -> Dict[tuple, dict]:
        """One tiering pass over every tiered partition (durable broker
        mounted with a tier only): upload eligible sealed segments to
        the remote tier, evict the hot tier past its byte budget,
        enforce remote retention, sweep unreferenced blobs.  Returns
        {(topic, partition): stats} for partitions that did anything.
        Driven by the background ``store.TierUploader`` in production
        and called directly by tests/drills/the chaos runner.

        Only below-quorum-HWM bytes ever tier out: on a replicated
        leader each partition's upload ceiling is
        ``replication.fetch_ceiling`` — the read-barrier the consumers
        already honor — so a record a failover could un-write can never
        reach the remote tier either.  Blob I/O runs outside the broker
        lock (`TieredLog.tier_sync` takes it only around manifest/
        segment-list publication), so produce/fetch proceed through a
        pass; whole passes serialize on ``_tier_pass_lock``."""
        if self.store is None:
            return {}
        out: Dict[tuple, dict] = {}
        with self._tier_pass_lock:
            with self._lock:
                tiered = [(name, p, part.slog)
                          for name, parts in self._parts.items()
                          for p, part in enumerate(parts)
                          if getattr(part, "slog", None) is not None
                          and getattr(part.slog, "remote", None)
                          is not None]
            for name, p, slog in tiered:
                ceiling = None
                if self.replication is not None:
                    ceiling = self.replication.fetch_ceiling(name, p)
                stats = slog.tier_sync(ceiling=ceiling, lock=self._lock)
                if any(stats.values()):
                    out[(name, p)] = stats
        return out

    # -------------------------------------------------------------- fetch
    def end_offset(self, topic: str, partition: int = 0) -> int:
        return self._parts[topic][partition].end()

    def begin_offset(self, topic: str, partition: int = 0) -> int:
        return self._parts[topic][partition].base()

    def align_base_offset(self, topic: str, partition: int,
                          offset: int) -> None:
        """Seed an EMPTY partition's base offset — replica bootstrap: a
        follower mirroring a leader whose log head was already trimmed
        must append the first copied message at the leader's earliest
        retained offset, not 0, so offsets stay identical across the
        pair (consumer cursors survive a failover unchanged)."""
        part = self._parts[topic][partition]
        with self._lock:
            part.align_base(offset)

    def reset_partition(self, topic: str, partition: int,
                        base_offset: int) -> None:
        """Drop a partition's log and restart it at `base_offset` —
        replica REALIGNMENT when the leader's retention outran
        replication: appending the post-gap messages at the local end
        would shift every subsequent offset and silently break the
        offsets-identical failover contract."""
        part = self._parts[topic][partition]
        with self._lock:
            part.reset(base_offset)

    def fetch(self, topic: str, partition: int, offset: int,
              max_messages: int = 1024) -> List[Message]:
        """Read up to max_messages starting at offset (monotone, no
        blocking).  A fetch below the retained base raises
        OffsetOutOfRangeError — trimmed history is an explicit signal,
        never a silent skip (consumers auto-reset to earliest).

        On a replicated leader, CONSUMER reads stop at the quorum
        high-water mark — the un-replicated tail is invisible until
        every ISR member holds it, so a record a failover could
        un-write can never have been observed.  Replica mirror fetches
        use ``fetch_tail`` (they exist to read that tail)."""
        msgs = self.fetch_tail(topic, partition, offset, max_messages)
        repl = self.replication
        if repl is not None and msgs:
            ceiling = repl.fetch_ceiling(topic, partition)
            if ceiling is not None and msgs[-1].offset >= ceiling:
                msgs = [m for m in msgs if m.offset < ceiling]
        return msgs

    def fetch_tail(self, topic: str, partition: int, offset: int,
                   max_messages: int = 1024) -> List[Message]:
        """`fetch` without the quorum read barrier — the replica mirror
        leg (followers must read past the HWM to advance it)."""
        chaos.point("broker.fetch")  # before the lock: a chaos stall must
        # park this fetcher, never every thread contending the broker
        part = self._parts[topic][partition]
        with self._lock:
            base = part.base()
            if offset < base:
                raise OffsetOutOfRangeError(topic, partition, offset, base)
            if isinstance(part, _Partition):
                # in-memory: a list slice, cheap enough to hold the lock
                chunk = part.read(offset, max_messages)
            else:
                chunk = None
        if chunk is None:
            # durable: disk I/O happens OUTSIDE the broker lock — one
            # cold read must not park every producer and fetcher.  The
            # segmented log reads a snapshot (appends only grow files;
            # a concurrent trim reads as trimmed history), so the only
            # race is retention passing `offset` mid-read → re-signal.
            try:
                chunk = part.read(offset, max_messages)
            except LookupError:
                raise OffsetOutOfRangeError(topic, partition, offset,
                                            part.base()) from None
        return [Message(topic, partition, off, value, key, ts, hdrs)
                for off, key, value, ts, hdrs in chunk]

    def fetch_raw(self, topic: str, partition: int, offset: int,
                  max_bytes: int = 1 << 20):
        """Raw-batch fetch: up to ~max_bytes of CONTIGUOUS store-format
        frames from `offset`, as a `RawFrameBatch` — no materialised
        `Message` list, no per-record Python objects.  The durable
        backend serves the segment's own disk bytes (outside the broker
        lock, like `fetch`); the in-memory emulator re-frames its list
        slice through the one frame codec.  Returns None at/after the
        log end; raises OffsetOutOfRangeError below the retained base
        (same contract as `fetch`)."""
        raw = self.fetch_raw_tail(topic, partition, offset, max_bytes)
        repl = self.replication
        if raw is not None and repl is not None:
            ceiling = repl.fetch_ceiling(topic, partition)
            if ceiling is not None and \
                    self._parts[topic][partition].end() > ceiling:
                # the batch may cross the quorum HWM: cut it at the
                # frame boundary below the ceiling (rare — only while
                # an un-replicated tail exists)
                if offset >= ceiling:
                    return None
                from ..ops.framing import (RawFrameBatch,
                                           truncate_frame_batch)

                data = truncate_frame_batch(raw.data, ceiling)
                if not data:
                    return None
                raw = RawFrameBatch(topic, partition, raw.start_offset,
                                    data)
        return raw

    def fetch_raw_tail(self, topic: str, partition: int, offset: int,
                       max_bytes: int = 1 << 20):
        """`fetch_raw` without the quorum read barrier (the replica's
        zero-copy mirror leg)."""
        from ..ops.framing import RawFrameBatch

        chaos.point("broker.fetch")  # the same faultpoint as fetch: a
        # raw batch is still one fetch to the chaos schedule
        part = self._parts[topic][partition]
        with self._lock:
            base = part.base()
            if offset < base:
                raise OffsetOutOfRangeError(topic, partition, offset, base)
            if isinstance(part, _Partition):
                res = part.read_raw(offset, max_bytes)
            else:
                res = False  # durable: disk I/O outside the lock (below)
        if res is False:
            try:
                res = part.read_raw(offset, max_bytes)
            except LookupError:
                raise OffsetOutOfRangeError(topic, partition, offset,
                                            part.base()) from None
        if res is None:
            return None
        data, start = res
        return RawFrameBatch(topic, partition, start, data)

    # ------------------------------------------------------------- replay
    def offset_for_timestamp(self, topic: str, partition: int,
                             timestamp_ms: int) -> int:
        """Earliest offset whose record timestamp is >= `timestamp_ms`
        (end offset when no such record) — the replay cursor behind
        `read_since` and the wire protocol's ListOffsets-by-timestamp."""
        part = self._parts[topic][partition]
        with self._lock:
            return part.offset_for_timestamp(timestamp_ms)

    def read_since(self, topic: str, partition: int, timestamp_ms: int,
                   max_messages: int = 1024) -> List[Message]:
        """Replay from the first record at/after `timestamp_ms` — how
        `ContinuousTrainer` backfills history on a cold start instead of
        training only on post-start records."""
        offset = self.offset_for_timestamp(topic, partition, timestamp_ms)
        for _ in range(3):
            try:
                msgs = self.fetch(topic, partition, offset, max_messages)
                break
            except OffsetOutOfRangeError as e:
                # raced a retention trim between the timestamp lookup
                # and the read: skip ahead like every other fetch caller
                offset = e.earliest
        else:
            msgs = []
        if msgs:
            # iotml_store_replay_records_total, counted by the durable
            # backend only — an in-memory replay must not show up on a
            # store dashboard (and the stream layer stays metric-free)
            self._parts[topic][partition].note_replay(len(msgs))
        return msgs

    # ------------------------------------------------- consumer-group API
    def commit(self, group: str, topic: str, partition: int, next_offset: int):
        # under the broker lock like every other mutation: a dict store is
        # atomic under the GIL, but the lockcheck race detector (rightly)
        # has no way to prove that, and free-threaded builds won't either
        with self._lock:
            self._group_offsets[(group, topic, partition)] = next_offset
            if self.store is not None:
                self.store.offsets.commit(group, topic, partition,
                                          next_offset)

    def commit_many(self, group: str, topic: str, entries) -> None:
        """Commit [(partition, next_offset), ...] of one topic under ONE
        lock acquisition — and, durable, ONE offsets-file fsync
        (StreamConsumer.commit's fast path, same contract as the wire
        client's commit_many)."""
        with self._lock:
            for p, off in entries:
                self._group_offsets[(group, topic, p)] = off
            if self.store is not None:
                self.store.offsets.commit_many(group, topic, entries)

    def committed(self, group: str, topic: str, partition: int) -> Optional[int]:
        with self._lock:
            return self._group_offsets.get((group, topic, partition))

    def committed_many(self, group: str, pairs):
        """Committed offsets for [(topic, partition), ...] under ONE
        lock acquisition; pairs with no committed offset are omitted
        (same contract as the wire client's one-OffsetFetch version)."""
        out = {}
        with self._lock:
            for t, p in pairs:
                off = self._group_offsets.get((group, t, p))
                if off is not None:
                    out[(t, p)] = off
        return out

    # ---------------------------------------------------------- lifecycle
    def flush(self) -> None:
        """Durable broker: fsync every partition log + the offsets file
        (no-op in-memory)."""
        if self.store is not None:
            with self._lock:
                self.store.flush()

    def close(self) -> None:
        """Release the durable backend's file handles (clean restart
        path; crash recovery handles the unclean one)."""
        if self.store is not None:
            with self._lock:
                self.store.close()
