"""In-process broker emulator — the framework's test/dev data backbone.

The reference's data plane is a full Confluent deployment (3 brokers, topics
`sensor-data` / `model-predictions` with 10 partitions, RF 3 — reference
`01_installConfluentPlatform.sh:180-183`), and its offline test story is a
FileStreamSource connector replaying a CSV into a topic (reference
`testdata/Test-Load-csv/`).  This module provides the equivalent in-process:
a partitioned, offset-addressed append-only log with consumer-group offset
storage and optional size/retention bounds, so every pipeline in the
framework — train, score, streamproc, generator — runs unchanged against it.

The same `Broker` duck-type is what the native (C++) engine and a real
librdkafka-backed client expose, so swapping the emulator for a real cluster
is a constructor change, not a code path change.

Threading: one lock guards all mutation (topic metadata, appends, retention
trims) and `fetch` — producers and background prefetch threads interleave
freely.  This is the correctness-first emulator; the native C++ engine owns
the lock-free hot path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import zlib
from typing import Dict, List, NamedTuple, Optional

from ..chaos import faults as chaos


class TopicOwnershipError(PermissionError):
    """Produce to an engine-owned topic without the owner's grant.

    Engine-owned topics (the stream-proc AVRO leg and its derivatives)
    are written exclusively by the owning engine — that exclusivity is
    what makes trusted_passthrough sound (the engine skips re-validating
    bytes only its own validating encoder could have written).  The wire
    server maps this to Kafka's TOPIC_AUTHORIZATION_FAILED."""


# Thread-local produce grants: a thread pumping an owning engine enters
# `producer_grant(token)` and may produce to the topics that token
# restricts; every other producer is rejected.  Thread-local (not an
# instance flag) so a grant cannot leak across the wire server's
# handler threads.
_grants = threading.local()


class Message(NamedTuple):
    """One record as fetched from a partition log.

    A NamedTuple, not a dataclass: fetch constructs one per record on the
    hot path, and the frozen-dataclass __init__ (object.__setattr__ per
    field) was the single largest cost left in the KSQL pump profile —
    tuple construction is C-speed with the same immutable attribute API."""

    topic: str
    partition: int
    offset: int
    value: bytes
    key: Optional[bytes] = None
    timestamp_ms: int = 0
    #: optional ((name, value), ...) record headers — the trace-context
    #: carrier (obs.tracing): metadata rides beside the payload so the
    #: Avro bytes are untouched.  None (the untraced default) costs
    #: nothing.  In-process only: MessageSet v1 on the wire has no
    #: header slot, so wire/native clients drop them.
    headers: Optional[tuple] = None


@dataclasses.dataclass
class TopicSpec:
    name: str
    partitions: int = 1
    # retention by message count (the reference uses retention.ms=100000 —
    # time-based; count-based is the deterministic test-friendly analogue).
    retention_messages: Optional[int] = None


class _Partition:
    __slots__ = ("log", "base_offset")

    def __init__(self):
        self.log: List[tuple] = []  # (key, value, ts, headers)
        self.base_offset = 0  # offset of log[0] after retention trimming


class Broker:
    """Partitioned in-memory commit log with Kafka-shaped semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._topics: Dict[str, TopicSpec] = {}
        self._parts: Dict[str, List[_Partition]] = {}
        self._group_offsets: Dict[tuple, int] = {}  # (group, topic, part) → next offset
        self._rr: Dict[str, int] = {}  # round-robin cursor per topic
        self._owned: Dict[str, object] = {}  # topic prefix → owner token

    # --------------------------------------------------------- ownership
    def restrict_topic(self, prefix: str,
                       token: Optional[object] = None) -> object:
        """Mark every topic named `prefix`* engine-owned: produces are
        rejected (TopicOwnershipError) unless the calling thread holds
        the returned token via `producer_grant`.  Reads, commits and
        topic creation stay open — the invariant is write exclusivity."""
        token = token if token is not None else object()
        with self._lock:
            self._owned[prefix] = token
        return token

    @contextlib.contextmanager
    def producer_grant(self, token: object):
        """Authorize this thread to produce to the topics `token`
        restricts for the duration of the block (re-entrant)."""
        held = getattr(_grants, "tokens", None)
        if held is None:
            held = _grants.tokens = []
        held.append(token)
        try:
            yield self
        finally:
            held.pop()

    def _check_producer(self, topic: str) -> None:
        if not self._owned:
            return
        with self._lock:  # snapshot: restrict_topic may race a produce
            owned = list(self._owned.items())
        for prefix, token in owned:
            if topic.startswith(prefix) and \
                    token not in getattr(_grants, "tokens", ()):
                raise TopicOwnershipError(
                    f"topic {topic!r} is engine-owned (prefix {prefix!r}): "
                    f"produce requires the owner's grant "
                    f"(Broker.producer_grant)")

    # ------------------------------------------------------------- topics
    def create_topic(self, name: str, partitions: int = 1,
                     retention_messages: Optional[int] = None) -> TopicSpec:
        if retention_messages is not None and retention_messages < 0:
            # a negative cap would delete every produced record while
            # producers believe writes succeed
            raise ValueError(f"retention_messages must be >= 0 or None, "
                             f"got {retention_messages}")
        if not retention_messages:
            retention_messages = None  # 0 = unbounded (BrokerConfig sentinel)
        with self._lock:
            if name in self._topics:
                return self._topics[name]
            spec = TopicSpec(name, partitions, retention_messages)
            self._topics[name] = spec
            self._parts[name] = [_Partition() for _ in range(partitions)]
            self._rr[name] = 0
            return spec

    def topics(self) -> List[str]:
        with self._lock:
            return sorted(self._topics)

    def topic(self, name: str) -> TopicSpec:
        return self._topics[name]

    def _partition_for(self, topic: str, key: Optional[bytes]) -> int:
        n = self._topics[topic].partitions
        if key is None:
            self._rr[topic] = (self._rr[topic] + 1) % n
            return self._rr[topic]
        # stable keyed partitioning (murmur-free but deterministic)
        return zlib.crc32(key) % n

    # ------------------------------------------------------------ produce
    def produce(self, topic: str, value: bytes, key: Optional[bytes] = None,
                partition: Optional[int] = None, timestamp_ms: int = 0,
                headers: Optional[tuple] = None) -> int:
        """Append one record; returns its offset. Auto-creates 1-partition
        topics (matching Kafka's auto.create default used by the reference's
        local demos)."""
        chaos.point("broker.produce")
        self._check_producer(topic)
        if topic not in self._topics:
            self.create_topic(topic)
        with self._lock:
            p = self._partition_for(topic, key) if partition is None else partition
            part = self._parts[topic][p]
            part.log.append((key, value, timestamp_ms, headers))
            off = part.base_offset + len(part.log) - 1
            spec = self._topics[topic]
            if spec.retention_messages and len(part.log) > spec.retention_messages:
                drop = len(part.log) - spec.retention_messages
                del part.log[:drop]
                part.base_offset += drop
            return off

    def produce_batch(self, topic: str, values, key=None, partition=None) -> int:
        """Append many records; returns the offset of the last one."""
        off = -1
        for v in values:
            off = self.produce(topic, v, key=key, partition=partition)
        return off

    def produce_many(self, topic: str, entries,
                     partition: Optional[int] = None) -> int:
        """Bulk append [(key, value, timestamp_ms[, headers]), ...] under
        ONE lock acquisition; returns the offset of the last record
        appended.

        Same signature and return contract as the wire/native clients'
        produce_many (the Broker duck-type family), and the same
        per-record semantics as produce() (key-hash partitioning,
        retention trimming) — minus a lock round-trip and method dispatch
        per message, the ingest bridges' hot path.  The optional 4th
        element carries record headers (trace context); wire/native
        clients accept and drop it (no header slot on MessageSet v1)."""
        chaos.point("broker.produce")
        self._check_producer(topic)
        entries = list(entries)
        if topic not in self._topics:
            self.create_topic(topic)
        last_off = -1
        with self._lock:
            parts = self._parts[topic]
            spec = self._topics[topic]
            for entry in entries:
                key, value, ts = entry[0], entry[1], entry[2]
                p = self._partition_for(topic, key) if partition is None \
                    else partition
                part = parts[p]
                part.log.append((key, value, ts,
                                 entry[3] if len(entry) > 3 else None))
                last_off = part.base_offset + len(part.log) - 1
            if spec.retention_messages:
                for part in parts:
                    if len(part.log) > spec.retention_messages:
                        drop = len(part.log) - spec.retention_messages
                        del part.log[:drop]
                        part.base_offset += drop
        return last_off

    # -------------------------------------------------------------- fetch
    def end_offset(self, topic: str, partition: int = 0) -> int:
        part = self._parts[topic][partition]
        return part.base_offset + len(part.log)

    def begin_offset(self, topic: str, partition: int = 0) -> int:
        return self._parts[topic][partition].base_offset

    def align_base_offset(self, topic: str, partition: int,
                          offset: int) -> None:
        """Seed an EMPTY partition's base offset — replica bootstrap: a
        follower mirroring a leader whose log head was already trimmed
        must append the first copied message at the leader's earliest
        retained offset, not 0, so offsets stay identical across the
        pair (consumer cursors survive a failover unchanged)."""
        part = self._parts[topic][partition]
        with self._lock:
            if part.log:
                raise ValueError(
                    f"{topic}:{partition} not empty; base is immutable")
            part.base_offset = max(part.base_offset, int(offset))

    def reset_partition(self, topic: str, partition: int,
                        base_offset: int) -> None:
        """Drop a partition's log and restart it at `base_offset` —
        replica REALIGNMENT when the leader's retention outran
        replication: appending the post-gap messages at the local end
        would shift every subsequent offset and silently break the
        offsets-identical failover contract.  Readers see the same thing
        a leader-side trim shows them (fetch clamps to the new base)."""
        part = self._parts[topic][partition]
        with self._lock:
            part.log.clear()
            part.base_offset = int(base_offset)

    def fetch(self, topic: str, partition: int, offset: int,
              max_messages: int = 1024) -> List[Message]:
        """Read up to max_messages starting at offset (monotone, no blocking)."""
        chaos.point("broker.fetch")  # before the lock: a chaos stall must
        # park this fetcher, never every thread contending the broker
        part = self._parts[topic][partition]
        with self._lock:
            start = max(offset, part.base_offset)
            idx = start - part.base_offset
            chunk = part.log[idx:idx + max_messages]
        return [Message(topic, partition, start + i, value, key, ts, hdrs)
                for i, (key, value, ts, hdrs) in enumerate(chunk)]

    # ------------------------------------------------- consumer-group API
    def commit(self, group: str, topic: str, partition: int, next_offset: int):
        # under the broker lock like every other mutation: a dict store is
        # atomic under the GIL, but the lockcheck race detector (rightly)
        # has no way to prove that, and free-threaded builds won't either
        with self._lock:
            self._group_offsets[(group, topic, partition)] = next_offset

    def committed(self, group: str, topic: str, partition: int) -> Optional[int]:
        with self._lock:
            return self._group_offsets.get((group, topic, partition))
