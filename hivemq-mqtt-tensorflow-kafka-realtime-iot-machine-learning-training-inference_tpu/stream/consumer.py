"""Offset-cursored stream consumer — the KafkaDataset equivalent.

The reference consumes with ``kafka_io.KafkaDataset(["topic:partition:offset"],
group=..., eof=True)`` (cardata-v3.py:46-47): an absolute-offset cursor over
one partition, EOF when the log end is reached, re-readable from the same
offset every epoch (the reference re-reads the topic per epoch,
python-scripts/README.md:114-117).

`StreamConsumer` reproduces those semantics over any broker duck-type
(emulator or native engine) and adds what the reference lacked: explicit
multi-partition specs, committed-offset resume, and a `seek` for epoch
re-reads without reconstructing the pipeline.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from struct import error as struct_error

from ..obs import metrics as obs_metrics
from ..obs import tracing, watermark
from .broker import (Broker, Message, OffsetOutOfRangeError,
                     SchemaIdMismatchError)


def parse_spec(spec: str) -> tuple:
    """Parse the reference's "topic:partition:offset" subscription string."""
    parts = spec.split(":")
    if len(parts) == 1:
        return parts[0], 0, 0
    if len(parts) == 2:
        return parts[0], int(parts[1]), 0
    return parts[0], int(parts[1]), int(parts[2])


class StreamConsumer:
    """Cursor over one or more (topic, partition) logs.

    Args:
      broker: broker duck-type (`fetch`, `end_offset`, `commit`, `committed`).
      specs: "topic:partition:offset" strings (reference subscription format).
      group: consumer-group id for offset commits.
      eof: if True, `poll` returns [] once all cursors hit the log end
           (reference eof=True batch-mode); if False, callers may poll again
           as data arrives (continuous scoring mode).
    """

    def __init__(self, broker: Broker, specs: Sequence[str],
                 group: str = "iotml", eof: bool = True):
        self.broker = broker
        self.group = group
        self.eof = eof
        self._cursors = []  # [topic, partition, next_offset]
        for s in specs:
            t, p, o = parse_spec(s)
            self._cursors.append([t, p, o])
        self._start = [c[2] for c in self._cursors]
        self._rr = 0
        # event-time accounting (ISSUE 13): per-(topic, partition)
        # [min_ts, max_ts] of records consumed since the last
        # take_event_time() — the consume paths fold decoder-reported
        # (columnar) or message (classic) timestamps in at batch
        # granularity; processing stages (scorer/trainer/twin) take the
        # ranges at their drain/commit boundary and publish the
        # ingest→stage watermark lag.
        self._event_ts: dict = {}
        # batch-granular trace contexts extracted from RAW batch frame
        # headers (the wire-trace leg): bounded, drained by the batcher
        import collections

        self._batch_traces: "collections.deque" = collections.deque(
            maxlen=1024)

    @classmethod
    def from_committed(cls, broker: Broker, topic: str, partitions: Sequence[int],
                       group: str, fallback_offset: int = 0, **kw):
        """Resume from committed group offsets (cursor-checkpoint restart)."""
        specs = []
        for p in partitions:
            off = broker.committed(group, topic, p)
            specs.append(f"{topic}:{p}:{off if off is not None else fallback_offset}")
        return cls(broker, specs, group=group, **kw)

    def rewind_to_committed(self) -> None:
        """Reset in-memory cursors to the last committed offsets (or the
        original start offsets when nothing was committed).  Used when a
        processing round aborts mid-chunk: `poll` has already advanced the
        cursors, so without a rewind the failed records would be silently
        skipped; rewinding retries them next round (at-least-once)."""
        for i, cur in enumerate(self._cursors):
            topic, part, _ = cur
            off = self.broker.committed(self.group, topic, part)
            cur[2] = off if off is not None else self._start[i]

    # ------------------------------------------- event-time watermarks
    def _note_event_ts(self, topic: str, part: int,
                       ts_min: int, ts_max: int) -> None:
        """Fold one consumed batch's event-time bounds into the
        per-partition accumulation AND publish the consume-stage
        watermark — batch-granular, the columnar plane's substitute for
        per-record spans (ISSUE 13)."""
        if ts_max is None or ts_max < 0:
            return
        lo = ts_min if ts_min is not None and ts_min >= 0 else ts_max
        cur = self._event_ts.get((topic, part))
        if cur is None:
            self._event_ts[(topic, part)] = [lo, ts_max]
        else:
            if lo < cur[0]:
                cur[0] = lo
            if ts_max > cur[1]:
                cur[1] = ts_max
        # group-labeled: a trainer and a scorer consuming the same
        # partition in one process are different frontiers — without
        # the group the gauge would flap between them
        watermark.observe("consume", topic, part, lo, ts_max,
                          group=self.group)

    def take_event_time(self) -> dict:
        """{(topic, partition): (ts_min, ts_max)} of event time consumed
        since the last take, cleared on read — the processing stage's
        half of the watermark contract: take at the drain/commit
        boundary (where consumed == processed) and hand the ranges to
        ``watermark.observe_taken(stage, ...)``."""
        out = {k: tuple(v) for k, v in self._event_ts.items()}
        self._event_ts.clear()
        return out

    def take_batch_traces(self) -> list:
        """Drain batch-granular trace contexts extracted from RAW batch
        frame headers (the wire-trace leg): the batcher appends them to
        its pending set so the pipeline closer (scorer / train step)
        closes them with the e2e span, exactly like record traces."""
        out: list = []
        while True:
            try:
                out.append(self._batch_traces.popleft())
            except IndexError:
                return out

    def record_lag(self) -> int:
        """Refresh ``iotml_consumer_lag_records{group,topic,partition}``
        from the high-water mark and return the total lag.  Wire
        brokers answer from the hwm CACHED off every fetch response —
        classic FETCH and RAW_FETCH both carry it (zero extra round
        trips); otherwise one ``end_offset`` read per partition —
        called at commit/drain granularity, never per record.  This is
        TELEMETRY riding the commit path: no failure here may crash a
        drain, so anything the broker throws (dead socket, transient
        wire error, racing topic deletion) degrades to a skipped
        refresh."""
        total = 0
        hwm_of = getattr(self.broker, "last_hwm", None)
        for topic, part, off in self._cursors:
            try:
                hwm = hwm_of(topic, part) if hwm_of is not None else None
                if hwm is None:
                    hwm = self.broker.end_offset(topic, part)
            except (KeyError, RuntimeError, OSError):
                # OSError covers ConnectionError AND socket timeouts;
                # RuntimeError is the wire client's non-OK error answer
                continue
            lag = max(int(hwm) - int(off), 0)
            total += lag
            obs_metrics.consumer_lag_records.set(
                lag, group=self.group, topic=topic, partition=part)
        return total

    def _fetch_autoreset(self, topic: str, part: int, off: int,
                         max_messages: int) -> tuple:
        """One broker fetch with the documented out-of-range policy:
        a cursor below the retained base (retention trimmed the head
        past it) auto-resets to EARLIEST — `auto.offset.reset=earliest`
        semantics, counted in iotml_consumer_autoresets_total so a
        consumer chronically outrun by retention is visible.  Returns
        (batch, effective_offset)."""
        for _ in range(4):  # retention may trim again between the calls
            try:
                return self.broker.fetch(topic, part, off, max_messages), off
            except OffsetOutOfRangeError as e:
                off = max(e.earliest, self.broker.begin_offset(topic, part))
                obs_metrics.consumer_autoresets.inc(topic=topic)
        # chronically outrun by retention (it trimmed past every reset):
        # an empty batch with the cursor parked at the last-known
        # earliest keeps the documented contract — poll() never raises
        # for trimmed history, the next poll resumes the chase
        return [], off

    # --------------------------------------------------------------- read
    def poll(self, max_messages: int = 1024) -> List[Message]:
        """Fetch up to max_messages across cursors (round-robin between
        partitions so one hot partition cannot starve the rest).  A
        cursor stranded below the retained base auto-resets to earliest
        (see _fetch_autoreset)."""
        out: List[Message] = []
        n = len(self._cursors)
        attempts = 0
        while len(out) < max_messages and attempts < n:
            cur = self._cursors[self._rr % n]
            self._rr += 1
            attempts += 1
            topic, part, off = cur
            batch, off = self._fetch_autoreset(topic, part, off,
                                               max_messages - len(out))
            cur[2] = off  # an auto-reset moved the cursor even if empty
            if batch:
                cur[2] = batch[-1].offset + 1
                out.extend(batch)
                attempts = 0  # progress was made; give others another chance
                # true min/max over the batch — event timestamps are
                # NOT append-monotone (a flap-recovered car's store-and-
                # forward buffer appends old event times after fresh
                # ones), and endpoint sampling would hide exactly those
                # records' lag.  O(n) attribute reads over an already-
                # materialised message list; the columnar path gets the
                # same bounds from the decoder's walk for free.
                self._note_event_ts(
                    topic, part,
                    min(m.timestamp_ms for m in batch),
                    max(m.timestamp_ms for m in batch))
                tracing.touch("consume")
        if out:
            # batch-shape telemetry: a drifting-down batch size under
            # constant load means the consumer is outpacing the producers
            # (or fetches are being truncated) — only non-empty polls
            # observe, so idle polling does not flood the 1-bucket
            obs_metrics.fetch_batch_size.observe(len(out))
        return out

    def poll_decoded(self, codec, strip: int = 5, max_messages: int = 4096,
                     with_keys: bool = False):
        """Fused native poll: fetch + framing strip + Avro decode in one
        C++ call per partition (broker `fetch_decode`, the KafkaDataset-
        equivalent hot path).  Returns (numeric [n, F] float64, labels
        [n, S] bytes) — with `with_keys`, (numeric, labels, keys [n]
        bytes) — or None when this broker has no native decode path (for
        with_keys that includes brokers without `fetch_decode_keys`);
        n == 0 signals the same end-of-poll as an empty `poll()`."""
        fd = getattr(self.broker,
                     "fetch_decode_keys" if with_keys else "fetch_decode",
                     None)
        if fd is None:
            return None
        nums, labs, keys = [], [], []
        got = 0
        n = len(self._cursors)
        attempts = 0
        while got < max_messages and attempts < n:
            cur = self._cursors[self._rr % n]
            self._rr += 1
            attempts += 1
            topic, part, off = cur
            try:
                res = fd(topic, part, off, codec, strip=strip,
                         max_rows=max_messages - got)
            except OffsetOutOfRangeError as e:
                # same documented auto-reset-to-earliest as poll(): the
                # fused native path must not turn a retention trim into
                # a crashed trainer/scorer loop
                cur[2] = max(e.earliest,
                             self.broker.begin_offset(topic, part))
                obs_metrics.consumer_autoresets.inc(topic=topic)
                continue
            except SchemaIdMismatchError:
                # the runtime guard behind the blind strip=5 decode: an
                # evolved writer's frame sits at the cursor.  Return
                # whatever decoded BEFORE it (cursors already stop
                # there); with nothing decoded, surface the signal so
                # the batcher takes its resolving-Python chunk.
                if got:
                    break
                raise
            numeric, labels = res[0], res[1]
            next_off = res[-1]
            if len(numeric):
                cur[2] = next_off
                nums.append(numeric)
                labs.append(labels)
                if with_keys:
                    keys.append(res[2])
                got += len(numeric)
                attempts = 0
        if not nums:
            from .native import LABEL_STRIDE

            empty = (np.zeros((0, codec.n_numeric)),
                     np.zeros((0, codec.n_strings), f"S{LABEL_STRIDE}"))
            return empty + (np.zeros((0,), "S1"),) if with_keys else empty
        out = (np.concatenate(nums), np.concatenate(labs))
        return out + (np.concatenate(keys),) if with_keys else out

    def poll_into(self, decoder, out_numeric, out_labels, out_keys=None,
                  max_rows: int = 4096, max_bytes: int = 1 << 20):
        """Columnar poll over RAW frame batches — THE zero-copy hot path
        and the ONE decode entry point for live consume and timestamp-
        replay backfill alike (a backfill is just this after
        ``seek_to_timestamp``).

        Fetches contiguous store-format frames (`Broker.fetch_raw` /
        wire RAW_FETCH) and decodes them straight into the CALLER-OWNED
        preallocated buffers via `decoder` (stream.native.FrameDecoder):
        zero per-record Python objects end to end.

        Returns ``(rows, fallback)`` — rows decoded into the buffers
        (cursors advanced past exactly those), and ``fallback=True``
        when the cursor is parked on a chunk the raw path must not
        decode (an evolved writer's schema id, or bytes only the
        resolving/legacy path can handle): the caller takes ONE legacy
        poll chunk and re-enters.  Returns None when the broker has no
        raw-batch support (callers use the legacy paths).  A cursor
        below the retained base auto-resets to earliest like poll()."""
        fr = getattr(self.broker, "fetch_raw", None)
        if fr is None or getattr(self, "_raw_unsupported", False):
            return None
        from .native import FRAMES_STOP_SCHEMA, FRAMES_STOP_TORN

        rows = 0
        n = len(self._cursors)
        attempts = 0
        while rows < max_rows and attempts < n:
            cur = self._cursors[self._rr % n]
            self._rr += 1
            attempts += 1
            topic, part, off = cur
            raw = None
            for _ in range(4):  # same retry envelope as _fetch_autoreset
                try:
                    raw = fr(topic, part, off, max_bytes=max_bytes)
                    break
                except NotImplementedError:
                    # wire server without the RAW_FETCH extension:
                    # remember and hand the caller back to the legacy
                    # paths for good (rows already decoded are
                    # returned, their cursors are final)
                    self._raw_unsupported = True
                    return (rows, False) if rows else None
                except OffsetOutOfRangeError as e:
                    # documented auto-reset-to-earliest, then RETRY the
                    # fetch at the reset cursor — a retention trim must
                    # not surface as a phantom end-of-stream
                    off = max(e.earliest,
                              self.broker.begin_offset(topic, part))
                    cur[2] = off
                    obs_metrics.consumer_autoresets.inc(topic=topic)
            if raw is None:
                continue
            got, next_off, flags, _skipped = decoder.decode_into(
                raw.data, off,
                out_numeric[rows:], out_labels[rows:],
                out_keys[rows:] if out_keys is not None else None,
                cap_rows=max_rows - rows)
            if got or next_off > off:
                # progress: decoded rows and/or skipped tombstones.
                # Event-time bounds fall out of the decoder's frame walk
                # for free (ISSUE 13): fold them into the watermark and
                # beat the consume-stage liveness — the batch-granular
                # telemetry the zero-record path otherwise cannot have.
                cur[2] = next_off
                rows += got
                attempts = 0
                self._note_event_ts(topic, part,
                                    getattr(decoder, "last_ts_min", -1),
                                    getattr(decoder, "last_ts_max", -1))
                if tracing.ENABLED:
                    tracing.touch("consume")
                    self._extract_batch_trace(raw, topic, part, off,
                                              next_off, got)
                continue
            if flags & FRAMES_STOP_SCHEMA:
                # evolved writer at the cursor: the caller resolves this
                # chunk by name in Python, then resumes columnar
                return rows, True
            if flags & FRAMES_STOP_TORN:
                # parked on bytes the raw scan can't cross: distinguish
                # a recovery hole (probe jumps it), a decodable-by-
                # legacy record (fall back for one chunk), and an
                # in-flight partial append (no data yet).  One bounded
                # 1-record probe — never per-record work.
                probe, eff = self._fetch_autoreset(topic, part, off, 1)
                cur[2] = eff
                if probe and probe[0].offset > eff:
                    cur[2] = probe[0].offset  # hole jumped; retry raw
                    continue
                if probe:
                    return rows, True
        if rows:
            obs_metrics.fetch_batch_size.observe(rows)
        return rows, False

    def _extract_batch_trace(self, raw, topic: str, part: int,
                             first_off: int, next_off: int,
                             got: int) -> None:
        """Wire-trace leg (ISSUE 13): a SAMPLED raw batch carries a
        trace context in its first frame's headers — ONE bounded
        first-frame parse per RAW fetch (only under tracing), never a
        batch walk.  The context is marked `consume` with the batch's
        offset range and held for the pipeline closer (scorer / train
        step) to close with its e2e span.  Gated at the cursor: a
        sparse-index-aligned re-serve of the batch head (first frame
        below `first_off`) is NOT a new batch — re-extracting it would
        close the same trace once per slice."""
        from ..ops.framing import first_frame_headers

        try:
            hdrs = first_frame_headers(raw.data, at_or_after=first_off)
        except (ValueError, struct_error):
            return
        ctx = tracing.from_headers(hdrs)
        if ctx is None:
            return
        tracing.mark_batch(ctx, "consume", topic, part, first_off,
                           next_off - 1, got)
        if len(self._batch_traces) == self._batch_traces.maxlen:
            # bounded like the batcher's pending set, and COUNTED like
            # it: a drill losing its cross-process traces to this bound
            # must show counter evidence of why
            tracing.spans_dropped.inc()
        self._batch_traces.append(ctx)

    def at_end(self) -> bool:
        return all(off >= self.broker.end_offset(t, p)
                   for t, p, off in self._cursors)

    def __iter__(self):
        """Iterate to EOF (reference eof=True semantics)."""
        while True:
            batch = self.poll()
            if not batch:
                if self.eof or self.at_end():
                    return
            yield from batch

    # ------------------------------------------------------------- cursor
    def seek_to_start(self):
        """Rewind to the construction offsets (per-epoch stream re-read)."""
        for cur, off in zip(self._cursors, self._start):
            cur[2] = off

    def seek_to_timestamp(self, timestamp_ms: int) -> None:
        """Move every cursor to the first record at/after `timestamp_ms`
        (the broker's timestamp index / ListOffsets-by-timestamp) — the
        replay entry point for training backfill.  Brokers without the
        replay API (native engine) leave the cursors untouched."""
        oft = getattr(self.broker, "offset_for_timestamp", None)
        if oft is None:
            return
        for cur in self._cursors:
            cur[2] = oft(cur[0], cur[1], timestamp_ms)

    def seek(self, topic: str, partition: int, offset: int):
        for cur in self._cursors:
            if cur[0] == topic and cur[1] == partition:
                cur[2] = offset
                return
        raise KeyError((topic, partition))

    def positions(self) -> List[tuple]:
        """Current (topic, partition, next_offset) cursor state — this tuple
        is the stream-side resume checkpoint (SURVEY §5 'offset is the resume
        cursor')."""
        return [tuple(c) for c in self._cursors]

    def commit(self):
        # commit is the drain boundary — the batch-granular spot to
        # refresh the first-class lag gauge (ISSUE 13 satellite)
        self.record_lag()
        with obs_metrics.commit_seconds.time():
            commit_many = getattr(self.broker, "commit_many", None)
            if commit_many is not None:
                # one request per topic instead of one per partition — over
                # the wire each commit is a round trip into the broker
                # process
                by_topic: dict = {}
                for t, p, off in self._cursors:
                    by_topic.setdefault(t, []).append((p, off))
                for t, entries in by_topic.items():
                    commit_many(self.group, t, entries)
                return
            for t, p, off in self._cursors:
                self.broker.commit(self.group, t, p, off)
