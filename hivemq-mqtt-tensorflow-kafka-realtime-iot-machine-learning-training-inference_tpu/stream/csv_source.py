"""CSV replay source — the FileStreamSource-connector equivalent.

The reference's offline test fixture replays `testdata/car-sensor-data.csv`
into a topic via a Kafka Connect FileStreamSource + KSQL DELIMITED→AVRO
conversion (reference `testdata/Test-Load-csv/`).  Here the whole fixture is
one function: read the CSV, encode each row per the requested schema
(JSON for the raw `sensor-data` stage, Confluent-framed Avro for the
KSQL-output stage), and append to a broker topic keyed by car id.
"""

from __future__ import annotations

import csv
import json
from typing import Optional

from ..core.schema import CAR_SCHEMA, KSQL_CAR_SCHEMA, RecordSchema
from ..ops.avro import AvroCodec
from ..ops.framing import frame


def _row_to_record(row: dict, schema: RecordSchema, label: str):
    """Map a CSV row (producer-schema lower_snake_case names) onto `schema`,
    tolerating the KSQL variant's renamed upper-case fields."""
    by_lower = {}
    for f in CAR_SCHEMA.fields:
        by_lower[f.name] = row[f.name]
    rec = {}
    for f in schema.fields:
        if schema.label_field and f.name == schema.label_field:
            rec[f.name] = label
            continue
        # KSQL upper-case names map back positionally: schemas share order.
        src = CAR_SCHEMA.fields[
            [x.name for x in schema.sensor_fields].index(f.name)
        ].name if f.name not in by_lower else f.name
        v = by_lower[src]
        rec[f.name] = int(float(v)) if f.avro_type in ("int", "long") else float(v)
    return rec


def replay_csv(broker, topic: str, csv_path: str,
               schema: RecordSchema = KSQL_CAR_SCHEMA,
               encoding: str = "avro", label: str = "false",
               limit: Optional[int] = None, partitions: int = 1) -> int:
    """Replay a car-sensor CSV into `topic`. Returns the record count.

    encoding="avro": Confluent-framed Avro (what the ML layer consumes).
    encoding="json": raw JSON (what lands on `sensor-data` pre-KSQL).
    """
    broker.create_topic(topic, partitions=partitions)
    codec = AvroCodec(schema)
    n = 0
    with open(csv_path, newline="") as fh:
        for row in csv.DictReader(fh):
            rec = _row_to_record(row, schema, label=label)
            if encoding == "avro":
                payload = frame(codec.encode(rec))
            else:
                payload = json.dumps(rec).encode()
            key = row.get("car", "").encode() or None
            ts = int(float(row.get("time", 0)) * 1000)
            broker.produce(topic, payload, key=key,
                           partition=None if partitions > 1 else 0,
                           timestamp_ms=ts)
            n += 1
            if limit and n >= limit:
                break
    return n
