"""Consumer-group coordination: membership, rebalance, elastic recovery.

The reference leans on Kafka's group coordinator for its scale story — 10
partitions × consumer groups, predict pods as a scalable Deployment that
K8s restarts freely (SURVEY §2.7, reference `python-scripts/README.md:73`).
That only works because a crashed consumer's partitions are *reassigned* to
survivors and resumed from committed offsets.  This module provides those
semantics for the framework's broker duck-type:

- `GroupCoordinator`: generation-numbered membership with heartbeats and a
  session timeout; any join/leave/expiry bumps the generation and
  recomputes assignments (range or round-robin assignor — Kafka's two
  classic strategies).
- `GroupConsumer`: a self-healing consumer.  Every `poll()` heartbeats; on
  a generation change it rejoins, rebuilds per-partition cursors from the
  group's committed offsets, and carries on.  Crash = stop polling: after
  the session timeout the coordinator expires the member and survivors pick
  up its partitions at the last commit (at-least-once, exactly Kafka's
  contract).

The committed offset is the resume cursor — the same state the reference
treats as its checkpoint (SURVEY §5: "the Kafka offset is the resume
cursor").
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from .broker import Message

TopicPartition = Tuple[str, int]


def range_assign(members: Sequence[str], topic_partitions: Dict[str, int]
                 ) -> Dict[str, List[TopicPartition]]:
    """Kafka's RangeAssignor: per topic, contiguous chunks in member order;
    the first (len % n) members get one extra partition."""
    out: Dict[str, List[TopicPartition]] = {m: [] for m in members}
    ms = sorted(members)
    if not ms:
        return out
    for topic in sorted(topic_partitions):
        n_parts = topic_partitions[topic]
        per, extra = divmod(n_parts, len(ms))
        p = 0
        for i, m in enumerate(ms):
            take = per + (1 if i < extra else 0)
            out[m].extend((topic, q) for q in range(p, p + take))
            p += take
    return out


def roundrobin_assign(members: Sequence[str],
                      topic_partitions: Dict[str, int]
                      ) -> Dict[str, List[TopicPartition]]:
    """Kafka's RoundRobinAssignor: all (topic, partition) pairs dealt out
    in order across members."""
    out: Dict[str, List[TopicPartition]] = {m: [] for m in members}
    ms = sorted(members)
    if not ms:
        return out
    cycle = itertools.cycle(ms)
    for topic in sorted(topic_partitions):
        for q in range(topic_partitions[topic]):
            out[next(cycle)].append((topic, q))
    return out


ASSIGNORS = {"range": range_assign, "roundrobin": roundrobin_assign}


class GroupCoordinator:
    """Generation-numbered group membership over a broker's topics."""

    def __init__(self, broker, group_id: str,
                 session_timeout_s: float = 10.0, assignor: str = "range",
                 clock=time.monotonic):
        if assignor not in ASSIGNORS:
            raise ValueError(f"unknown assignor {assignor!r}; "
                             f"choose from {sorted(ASSIGNORS)}")
        self.broker = broker
        self.group_id = group_id
        self.session_timeout_s = session_timeout_s
        self.assignor = ASSIGNORS[assignor]
        self._clock = clock
        self._lock = threading.RLock()
        self.generation = 0
        self._heartbeats: Dict[str, float] = {}
        self._subscriptions: Dict[str, Tuple[str, ...]] = {}
        self._assignments: Dict[str, List[TopicPartition]] = {}

    # ------------------------------------------------------------ lifecycle
    def join(self, topics: Sequence[str], member_id: Optional[str] = None
             ) -> Tuple[str, int, List[TopicPartition]]:
        """(Re)join the group; returns (member_id, generation, assignment)."""
        with self._lock:
            self._expire_dead()
            member_id = member_id or f"{self.group_id}-{uuid.uuid4().hex[:8]}"
            self._heartbeats[member_id] = self._clock()
            self._subscriptions[member_id] = tuple(sorted(topics))
            self._rebalance()
            return member_id, self.generation, list(
                self._assignments.get(member_id, []))

    def leave(self, member_id: str) -> None:
        with self._lock:
            if member_id in self._heartbeats:
                del self._heartbeats[member_id]
                del self._subscriptions[member_id]
                self._rebalance()

    def heartbeat(self, member_id: str, generation: int) -> bool:
        """True iff the member is still current; False demands a rejoin."""
        with self._lock:
            self._expire_dead()
            if member_id not in self._heartbeats or \
                    generation != self.generation:
                return False
            self._heartbeats[member_id] = self._clock()
            return True

    def assignment(self, member_id: str) -> List[TopicPartition]:
        with self._lock:
            return list(self._assignments.get(member_id, []))

    def members(self) -> List[str]:
        with self._lock:
            self._expire_dead()
            return sorted(self._heartbeats)

    # ------------------------------------------------------------ internals
    def _expire_dead(self) -> None:
        now = self._clock()
        dead = [m for m, hb in self._heartbeats.items()
                if now - hb > self.session_timeout_s]
        for m in dead:
            del self._heartbeats[m]
            del self._subscriptions[m]
        if dead:
            self._rebalance()

    def _rebalance(self) -> None:
        topics: Dict[str, int] = {}
        for subs in self._subscriptions.values():
            for t in subs:
                topics[t] = self.broker.topic(t).partitions
        members = sorted(self._heartbeats)
        assignments = self.assignor(members, topics)
        # only members subscribed to a topic may receive its partitions
        for m in members:
            subs = set(self._subscriptions[m])
            assignments[m] = [tp for tp in assignments[m] if tp[0] in subs]
        self._assignments = assignments
        self.generation += 1


class GroupConsumer:
    """Self-healing consumer: rebalance-aware polling with committed-offset
    resume.  At-least-once: records between the last `commit()` and a crash
    are redelivered to whichever member inherits the partition."""

    def __init__(self, coordinator: GroupCoordinator, topics: Sequence[str],
                 member_id: Optional[str] = None,
                 fallback_offset: int = 0):
        self.coord = coordinator
        self.broker = coordinator.broker
        self.group = coordinator.group_id
        self.topics = tuple(topics)
        self.fallback_offset = fallback_offset
        self._cursors: Dict[TopicPartition, int] = {}
        self._rr = 0
        self.rebalances = 0
        self.member_id, self.generation, assigned = \
            coordinator.join(self.topics, member_id)
        self._adopt(assigned)

    # ------------------------------------------------------------- polling
    def _adopt(self, assigned: List[TopicPartition]) -> None:
        cursors = {}
        for tp in assigned:
            committed = self.broker.committed(self.group, tp[0], tp[1])
            cursors[tp] = committed if committed is not None \
                else self.fallback_offset
        self._cursors = cursors

    def _ensure_membership(self) -> None:
        if not self.coord.heartbeat(self.member_id, self.generation):
            self.member_id, self.generation, assigned = \
                self.coord.join(self.topics, self.member_id)
            self._adopt(assigned)
            self.rebalances += 1

    @property
    def assignment(self) -> List[TopicPartition]:
        return sorted(self._cursors)

    def poll(self, max_messages: int = 1024) -> List[Message]:
        """Heartbeat, heal membership if the group moved on, then fetch from
        assigned partitions round-robin."""
        self._ensure_membership()
        tps = sorted(self._cursors)
        out: List[Message] = []
        for i in range(len(tps)):
            if len(out) >= max_messages:
                break
            tp = tps[(self._rr + i) % len(tps)]
            msgs = self.broker.fetch(tp[0], tp[1], self._cursors[tp],
                                     max_messages - len(out))
            if msgs:
                self._cursors[tp] = msgs[-1].offset + 1
                out.extend(msgs)
        self._rr += 1
        return out

    def poll_decoded(self, codec, strip: int = 5, max_messages: int = 4096):
        """StreamConsumer-compatible fused native poll over the *assigned*
        partitions (see consumer.StreamConsumer.poll_decoded); lets
        SensorBatches/StreamScorer run group-elastic without code changes."""
        import numpy as np

        fd = getattr(self.broker, "fetch_decode", None)
        if fd is None:
            return None
        self._ensure_membership()
        nums, labs = [], []
        got = 0
        tps = sorted(self._cursors)
        for i in range(len(tps)):
            if got >= max_messages:
                break
            tp = tps[(self._rr + i) % len(tps)]
            numeric, labels, next_off = fd(tp[0], tp[1], self._cursors[tp],
                                           codec, strip=strip,
                                           max_rows=max_messages - got)
            if len(numeric):
                self._cursors[tp] = next_off
                nums.append(numeric)
                labs.append(labels)
                got += len(numeric)
        self._rr += 1
        if not nums:
            from .native import LABEL_STRIDE

            return (np.zeros((0, codec.n_numeric)),
                    np.zeros((0, codec.n_strings), f"S{LABEL_STRIDE}"))
        return np.concatenate(nums), np.concatenate(labs)

    def at_end(self) -> bool:
        return all(off >= self.broker.end_offset(t, p)
                   for (t, p), off in self._cursors.items())

    def __iter__(self):
        while True:
            batch = self.poll()
            if not batch:
                return
            yield from batch

    def positions(self) -> List[Tuple[str, int, int]]:
        return sorted((t, p, off) for (t, p), off in self._cursors.items())

    def seek_to_start(self) -> None:
        """Group semantics: 'start' is the group's committed position (the
        resume cursor), not offset 0."""
        self._adopt(list(self._cursors))

    def commit(self) -> None:
        for (t, p), off in self._cursors.items():
            self.broker.commit(self.group, t, p, off)

    def close(self) -> None:
        self.commit()
        self.coord.leave(self.member_id)
