"""Consumer-group coordination: membership, rebalance, elastic recovery.

The reference leans on Kafka's group coordinator for its scale story — 10
partitions × consumer groups, predict pods as a scalable Deployment that
K8s restarts freely (SURVEY §2.7, reference `python-scripts/README.md:73`).
That only works because a crashed consumer's partitions are *reassigned* to
survivors and resumed from committed offsets.  This module provides those
semantics for the framework's broker duck-type:

- `GroupCoordinator`: generation-numbered membership with heartbeats and a
  session timeout; a membership change (new member, leave, expiry,
  subscription change, topic metadata change) bumps the generation and
  recomputes assignments (range or round-robin assignor — Kafka's two
  classic strategies).  A rejoin from a current member with an unchanged
  subscription does NOT bump the generation — it simply hands back the
  current assignment, so members converge after a rebalance instead of
  invalidating each other forever.
- `GroupConsumer`: a self-healing consumer.  Every `poll()` heartbeats; on
  a generation change it rejoins, rebuilds per-partition cursors from the
  group's committed offsets, and carries on.  Crash = stop polling: after
  the session timeout the coordinator expires the member and survivors pick
  up its partitions at the last commit (at-least-once, exactly Kafka's
  contract).  Commits are generation-fenced: a member that fell behind a
  rebalance cannot clobber offsets committed by the partition's current
  owner (Kafka's ILLEGAL_GENERATION check).

The committed offset is the resume cursor — the same state the reference
treats as its checkpoint (SURVEY §5: "the Kafka offset is the resume
cursor").
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from .broker import Message
from .consumer import StreamConsumer

TopicPartition = Tuple[str, int]


def range_assign(members: Sequence[str], topic_partitions: Dict[str, int]
                 ) -> Dict[str, List[TopicPartition]]:
    """Kafka's RangeAssignor: per topic, contiguous chunks in member order;
    the first (len % n) members get one extra partition."""
    out: Dict[str, List[TopicPartition]] = {m: [] for m in members}
    ms = sorted(members)
    if not ms:
        return out
    for topic in sorted(topic_partitions):
        n_parts = topic_partitions[topic]
        per, extra = divmod(n_parts, len(ms))
        p = 0
        for i, m in enumerate(ms):
            take = per + (1 if i < extra else 0)
            out[m].extend((topic, q) for q in range(p, p + take))
            p += take
    return out


def roundrobin_assign(members: Sequence[str],
                      topic_partitions: Dict[str, int]
                      ) -> Dict[str, List[TopicPartition]]:
    """Kafka's RoundRobinAssignor: all (topic, partition) pairs dealt out
    in order across members."""
    out: Dict[str, List[TopicPartition]] = {m: [] for m in members}
    ms = sorted(members)
    if not ms:
        return out
    cycle = itertools.cycle(ms)
    for topic in sorted(topic_partitions):
        for q in range(topic_partitions[topic]):
            out[next(cycle)].append((topic, q))
    return out


ASSIGNORS = {"range": range_assign, "roundrobin": roundrobin_assign}


class GroupCoordinator:
    """Generation-numbered group membership over a broker's topics."""

    def __init__(self, broker, group_id: str,
                 session_timeout_s: float = 10.0, assignor: str = "range",
                 clock=time.monotonic, metadata_max_age_s: float = 5.0):
        if assignor not in ASSIGNORS:
            raise ValueError(f"unknown assignor {assignor!r}; "
                             f"choose from {sorted(ASSIGNORS)}")
        self.broker = broker
        self.group_id = group_id
        self.session_timeout_s = session_timeout_s
        self.assignor = ASSIGNORS[assignor]
        self._clock = clock
        self._lock = threading.RLock()
        self.generation = 0
        self._heartbeats: Dict[str, float] = {}
        self._subscriptions: Dict[str, Tuple[str, ...]] = {}
        self._assignments: Dict[str, List[TopicPartition]] = {}
        self._last_topics: Dict[str, int] = {}  # metadata at last rebalance
        # revocation grace (Kafka's PreparingRebalance window): when a
        # rebalance bumps the generation, every SURVIVING member that has
        # not yet rejoined is remembered here with its pre-bump
        # (generation, assignment).  A commit it issues at that old
        # generation — the "commit before release" a revoked member owes
        # its successor — is still accepted for its OLD partitions, but
        # never rewinds an offset (the new owner may have moved it).
        self._pending_rejoin: Dict[str, Tuple[int,
                                              List[TopicPartition]]] = {}
        # metadata.max.age.ms analogue: heartbeats between sweeps reuse the
        # cached topic view, so the per-poll cost stays O(1) and a broker
        # whose metadata lookups are network calls isn't probed per poll
        self.metadata_max_age_s = metadata_max_age_s
        self._meta_checked_at: Optional[float] = None

    # ------------------------------------------------------------ lifecycle
    def join(self, topics: Sequence[str], member_id: Optional[str] = None
             ) -> Tuple[str, int, List[TopicPartition]]:
        """(Re)join the group; returns (member_id, generation, assignment).

        Only a *change* — new member, changed subscription, expired peers,
        or topic metadata drift — triggers a rebalance.  A current member
        rejoining identically just receives the standing assignment, which
        is what lets every member converge onto one generation after a
        rebalance instead of livelocking on mutual invalidation."""
        with self._lock:
            self._expire_dead()
            known = member_id is not None and member_id in self._heartbeats
            member_id = member_id or f"{self.group_id}-{uuid.uuid4().hex[:8]}"
            subs = tuple(sorted(topics))
            sub_changed = (not known
                           or self._subscriptions.get(member_id) != subs)
            self._heartbeats[member_id] = self._clock()
            self._subscriptions[member_id] = subs
            # one probe, taken after the subscription update so it covers
            # this member's topics; _rebalance reuses it (no double probe)
            meta = self._topic_metadata(force=True)
            if sub_changed or meta != self._last_topics:
                self._rebalance(meta)
            # rejoining at the current generation closes the member's
            # revocation-grace window: from here on only current-
            # generation commits are its voice
            self._pending_rejoin.pop(member_id, None)
            return member_id, self.generation, list(
                self._assignments.get(member_id, []))

    def leave(self, member_id: str) -> None:
        with self._lock:
            self._pending_rejoin.pop(member_id, None)
            if member_id in self._heartbeats:
                del self._heartbeats[member_id]
                del self._subscriptions[member_id]
                self._rebalance()

    def heartbeat(self, member_id: str, generation: int) -> bool:
        """True iff the member is still current; False demands a rejoin.

        Also watches topic metadata: a subscribed topic appearing (or
        growing partitions) triggers a rebalance, so consumers deployed
        before their producers pick the topic up once it exists — Kafka's
        metadata-refresh rebalance.  `heartbeat_verdict` gives the
        protocol-grade distinction between the failure modes."""
        return self.heartbeat_verdict(member_id, generation) == "ok"

    def fenced_commit(self, member_id: str, generation: int,
                      positions: Sequence[Tuple[str, int, int]]) -> bool:
        """Commit offsets iff the member is current for this generation.

        Kafka rejects commits from fenced members (ILLEGAL_GENERATION);
        without this, a consumer that fell behind a rebalance could
        overwrite newer offsets committed by the partition's new owner.
        Only partitions in the member's *current* assignment are written.
        Returns True when the commit was accepted."""
        return self.fenced_commit_detailed(member_id, generation,
                                           positions) is not None

    def fenced_commit_detailed(self, member_id: str, generation: int,
                               positions: Sequence[Tuple[str, int, int]]
                               ) -> Optional[set]:
        """Like `fenced_commit`, with per-partition granularity: None when
        the member is fenced (nothing written), else the set of (topic,
        partition) actually committed — so callers can flag positions that
        named partitions outside the member's assignment."""
        with self._lock:
            if member_id in self._heartbeats and \
                    generation == self.generation:
                owned = set(self._assignments.get(member_id, []))
                done = set()
                for t, p, off in positions:
                    if (t, p) in owned:
                        self.broker.commit(self.group_id, t, p, off)
                        done.add((t, p))
                return done
            # revocation grace: a surviving member that hasn't seen the
            # rebalance yet commits its progress at the OLD generation
            # before releasing its partitions.  Accepted only for the
            # partitions it owned THEN, and never backwards — the
            # inheriting member may already have committed further, and
            # rewinding its cursor would redeliver history it fenced.
            pending = self._pending_rejoin.get(member_id)
            if member_id not in self._heartbeats or pending is None or \
                    generation != pending[0]:
                return None
            owned = set(pending[1])
            done = set()
            for t, p, off in positions:
                if (t, p) not in owned:
                    continue
                cur = self.broker.committed(self.group_id, t, p)
                if cur is None or off >= cur:
                    self.broker.commit(self.group_id, t, p, off)
                done.add((t, p))
            return done

    def sync(self, member_id: str, generation: int
             ) -> Tuple[str, List[TopicPartition]]:
        """Atomic membership check + assignment fetch (the SyncGroup
        operation): one lock acquisition, so a concurrent join cannot slip
        between the validity check and the assignment read.  Returns
        ("ok"|"unknown_member"|"illegal_generation", assignment)."""
        with self._lock:
            if member_id not in self._heartbeats:
                return "unknown_member", []
            if generation != self.generation:
                return "illegal_generation", []
            return "ok", list(self._assignments.get(member_id, []))

    def assignment(self, member_id: str) -> List[TopicPartition]:
        with self._lock:
            return list(self._assignments.get(member_id, []))

    def members(self) -> List[str]:
        with self._lock:
            self._expire_dead()
            return sorted(self._heartbeats)

    def subscriptions(self) -> Dict[str, Tuple[str, ...]]:
        """member_id → subscribed topics (what JoinGroup hands the elected
        leader so it can compute a client-side assignment)."""
        with self._lock:
            return dict(self._subscriptions)

    def heartbeat_verdict(self, member_id: str, generation: int) -> str:
        """Protocol-grade heartbeat: "ok" | "unknown_member" |
        "rebalance_in_progress" — external wire clients need the distinction
        (UNKNOWN_MEMBER_ID means drop your member id and rejoin fresh;
        REBALANCE_IN_PROGRESS means rejoin with the same id)."""
        with self._lock:
            self._expire_dead()
            if member_id not in self._heartbeats:
                return "unknown_member"
            if generation != self.generation:
                return "rebalance_in_progress"
            meta = self._topic_metadata()
            if meta is not self._last_topics and meta != self._last_topics:
                self._rebalance(meta)
                return "rebalance_in_progress"
            self._heartbeats[member_id] = self._clock()
            return "ok"

    # ------------------------------------------------------------ internals
    def _topic_metadata(self, force: bool = False) -> Dict[str, int]:
        """Partition counts for subscribed topics that exist right now.
        A subscribed-but-absent topic simply contributes nothing yet
        (Kafka consumers may legally subscribe before the topic is
        created).

        Probes at most once per `metadata_max_age_s` unless forced; each
        unique topic is queried once per sweep.  Brokers that cache topic
        metadata (NativeKafkaBroker) are asked to refresh via
        `refresh_topic`, so partition growth becomes visible."""
        now = self._clock()
        if (not force and self._meta_checked_at is not None
                and now - self._meta_checked_at < self.metadata_max_age_s):
            return self._last_topics
        self._meta_checked_at = now
        subscribed = set()
        for subs in self._subscriptions.values():
            subscribed.update(subs)
        refresh = getattr(self.broker, "refresh_topic", None)
        topics: Dict[str, int] = {}
        for t in sorted(subscribed):
            if refresh is not None:
                n = refresh(t)
                if n:
                    topics[t] = n
            else:
                try:
                    topics[t] = self.broker.topic(t).partitions
                except KeyError:
                    continue
        return topics

    def _expire_dead(self) -> None:
        now = self._clock()
        dead = [m for m, hb in self._heartbeats.items()
                if now - hb > self.session_timeout_s]
        for m in dead:
            del self._heartbeats[m]
            del self._subscriptions[m]
            # an EXPIRED member gets no grace: it is presumed crashed,
            # and a zombie resurfacing must not clobber its successor
            self._pending_rejoin.pop(m, None)
        if dead:
            self._rebalance()

    def _rebalance(self, topics: Optional[Dict[str, int]] = None) -> None:
        if topics is None:
            topics = self._topic_metadata(force=True)
        members = sorted(self._heartbeats)
        # open the revocation-grace window for every surviving member:
        # until it rejoins, a commit at the outgoing generation is still
        # its legitimate "commit before release".  The earliest pending
        # generation wins for a member that misses several rebalances —
        # its uncommitted progress dates from the assignment it last saw.
        for m in members:
            if m not in self._pending_rejoin:
                self._pending_rejoin[m] = (
                    self.generation, list(self._assignments.get(m, [])))
        assignments = self.assignor(members, topics)
        # only members subscribed to a topic may receive its partitions
        for m in members:
            subs = set(self._subscriptions[m])
            assignments[m] = [tp for tp in assignments[m] if tp[0] in subs]
        self._assignments = assignments
        self._last_topics = topics
        self.generation += 1


class GroupConsumer:
    """Self-healing consumer: rebalance-aware polling with committed-offset
    resume.  At-least-once: records between the last `commit()` and a crash
    are redelivered to whichever member inherits the partition.

    Internally delegates fetching to a `StreamConsumer` rebuilt on every
    rebalance, so the fused native decode hot path (`poll_decoded`) and the
    cursor bookkeeping live in exactly one place."""

    def __init__(self, coordinator: GroupCoordinator, topics: Sequence[str],
                 member_id: Optional[str] = None,
                 fallback_offset: int = 0):
        self.coord = coordinator
        self.broker = coordinator.broker
        self.group = coordinator.group_id
        self.topics = tuple(topics)
        self.fallback_offset = fallback_offset
        self.rebalances = 0
        self.member_id, self.generation, assigned = \
            coordinator.join(self.topics, member_id)
        self._adopt(assigned)

    # ------------------------------------------------------------- polling
    def _adopt(self, assigned: List[TopicPartition],
               sticky: bool = True) -> None:
        # Cooperative-sticky semantics: partitions this member kept across
        # the rebalance carry their in-memory position forward (no duplicate
        # redelivery of uncommitted progress); only newly-inherited
        # partitions resume from the group's committed offset.
        held = ({(t, p): off for t, p, off in self._sc.positions()}
                if sticky and hasattr(self, "_sc") else {})
        # ONE OffsetFetch for the whole assignment (remote consumers:
        # the per-partition committed() loop cost a coordinator round
        # trip each, on every rebalance)
        frontier = self.broker.committed_many(self.group, list(assigned)) \
            if assigned else {}
        specs = []
        for t, p in assigned:
            committed = frontier.get((t, p))
            if (t, p) in held:
                off = held[(t, p)]
                if committed is not None and committed > off:
                    # the GROUP's committed frontier moved past our held
                    # cursor: an interim owner consumed this partition
                    # while we were out of the group (coordinator
                    # failover, long GC pause).  Trusting the stale
                    # in-memory cursor would re-read the interim owner's
                    # committed work — resume at the frontier instead.
                    off = committed
            else:
                off = committed if committed is not None \
                    else self.fallback_offset
            specs.append(f"{t}:{p}:{off}")
        self._sc = StreamConsumer(self.broker, specs, group=self.group,
                                  eof=False)

    def _ensure_membership(self) -> None:
        if not self.coord.heartbeat(self.member_id, self.generation):
            # revocation: commit this member's progress BEFORE releasing
            # its partitions to the rebalance, inside the coordinator's
            # grace window — the successor then resumes at our real
            # frontier instead of redelivering everything since the last
            # periodic commit.  Best-effort: a fenced/expired member
            # falls back to plain at-least-once redelivery.
            try:
                self.coord.fenced_commit(self.member_id, self.generation,
                                         self._sc.positions())
            except ConnectionError:
                pass  # coordinator moved/died: rejoin below re-resolves
            self.member_id, self.generation, assigned = \
                self.coord.join(self.topics, self.member_id)
            self._adopt(assigned)
            self.rebalances += 1

    @property
    def assignment(self) -> List[TopicPartition]:
        return sorted((t, p) for t, p, _ in self._sc.positions())

    def poll(self, max_messages: int = 1024) -> List[Message]:
        """Heartbeat, heal membership if the group moved on, then fetch from
        assigned partitions round-robin."""
        self._ensure_membership()
        return self._sc.poll(max_messages)

    def poll_decoded(self, codec, strip: int = 5, max_messages: int = 4096,
                     with_keys: bool = False):
        """StreamConsumer-compatible fused native poll over the *assigned*
        partitions (see consumer.StreamConsumer.poll_decoded); lets
        SensorBatches/StreamScorer run group-elastic without code changes."""
        fd = getattr(self.broker,
                     "fetch_decode_keys" if with_keys else "fetch_decode",
                     None)
        if fd is None:
            return None
        self._ensure_membership()
        return self._sc.poll_decoded(codec, strip=strip,
                                     max_messages=max_messages,
                                     with_keys=with_keys)

    def poll_into(self, decoder, out_numeric, out_labels, out_keys=None,
                  max_rows: int = 4096, max_bytes: int = 1 << 20):
        """StreamConsumer-compatible columnar raw-batch poll over the
        *assigned* partitions (see consumer.StreamConsumer.poll_into) —
        the zero-copy pipeline runs group-elastic without code
        changes."""
        if getattr(self.broker, "fetch_raw", None) is None:
            return None
        self._ensure_membership()
        return self._sc.poll_into(decoder, out_numeric, out_labels,
                                  out_keys=out_keys, max_rows=max_rows,
                                  max_bytes=max_bytes)

    def at_end(self) -> bool:
        return self._sc.at_end()

    def take_event_time(self) -> dict:
        """Event-time ranges consumed since the last take (ISSUE 13) —
        delegated so group-elastic pipelines publish the same
        ingest→stage watermarks as static ones."""
        return self._sc.take_event_time()

    def take_batch_traces(self) -> list:
        """Wire-carried batch traces extracted by the columnar poll —
        delegated (see StreamConsumer.take_batch_traces)."""
        return self._sc.take_batch_traces()

    def record_lag(self) -> int:
        """Refresh iotml_consumer_lag_records for the assigned
        partitions (see StreamConsumer.record_lag)."""
        return self._sc.record_lag()

    def __iter__(self):
        while True:
            batch = self.poll()
            if not batch:
                return
            yield from batch

    def positions(self) -> List[Tuple[str, int, int]]:
        return sorted(self._sc.positions())

    def seek_to_start(self) -> None:
        """Group semantics: 'start' is the group's committed position (the
        resume cursor), not offset 0."""
        self._adopt([(t, p) for t, p, _ in self._sc.positions()],
                    sticky=False)

    def rewind_to_committed(self) -> None:
        """Reset in-memory cursors to the group's committed offsets —
        the redelivery entry point after a ConnectionError mid-drain
        (same contract as StreamConsumer.rewind_to_committed)."""
        self._sc.rewind_to_committed()

    def commit(self) -> bool:
        """Generation-fenced commit; returns False (and writes nothing) when
        this member has been fenced by a rebalance it hasn't seen yet."""
        self._sc.record_lag()  # drain boundary: refresh the lag gauge
        return self.coord.fenced_commit(self.member_id, self.generation,
                                        self._sc.positions())

    def close(self) -> None:
        self.commit()
        self.coord.leave(self.member_id)
