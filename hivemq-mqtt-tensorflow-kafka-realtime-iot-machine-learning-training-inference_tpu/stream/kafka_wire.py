"""Kafka wire protocol — TCP client and server for the stream layer.

The reference's entire data plane is the Kafka protocol: `KafkaDataset`
consumes `kafka:9071` with SASL/PLAIN (reference cardata-v3.py:7-15,46-47),
`KafkaOutputSequence` produces to it, topics are provisioned with
`kafka-topics --create` (reference `01_installConfluentPlatform.sh:180-183`).
This module implements the protocol subset those paths need, natively:

- `KafkaWireBroker` — a *client* exposing the same duck-type as
  `stream.broker.Broker` (produce / fetch / end_offset / commit / ...), so
  `StreamConsumer`, `SensorBatches`, `OutputSequence` and every CLI run
  unchanged against a real cluster: `Broker()` → `KafkaWireBroker("host:port")`
  is the whole migration.
- `KafkaWireServer` — a TCP front for the in-process `Broker` emulator
  speaking the same protocol, so the client (and any standard Kafka client)
  can be exercised end-to-end without a cluster — the same trick as
  `mqtt.wire.MqttServer`.

Protocol details (all big-endian, classic encoding — no flexible/tagged
fields): request header v1 (api_key, api_version, correlation_id,
client_id); MessageSet v1 entries (magic 1, CRC over magic..value) for
Produce v2 / Fetch v2; Metadata v1; ListOffsets v1; OffsetCommit v2 /
OffsetFetch v1 (simple-consumer group offsets, generation −1);
CreateTopics v0; ApiVersions v0; SaslHandshake v0 + raw PLAIN token frame
(the pre-KIP-152 exchange the reference's SASL_PLAIN config uses).
"""

from __future__ import annotations

import re
import socket
import socketserver
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..chaos import faults as chaos
from ..obs import tracing as _tracing
from ..utils.net import recv_exact
from .broker import (Broker, CorruptMessageError, Message,
                     OffsetOutOfRangeError, TopicSpec)

# api keys
PRODUCE, FETCH, LIST_OFFSETS, METADATA = 0, 1, 2, 3
OFFSET_COMMIT, OFFSET_FETCH = 8, 9
FIND_COORDINATOR, JOIN_GROUP, HEARTBEAT, LEAVE_GROUP, SYNC_GROUP = \
    10, 11, 12, 13, 14
SASL_HANDSHAKE, API_VERSIONS, CREATE_TOPICS = 17, 18, 19
# Emulator-family protocol extension (key far outside Kafka's range,
# like the retention.messages config entry): a fetch whose response is
# the broker's RAW store-frame bytes — [len|crc|attrs|offset|ts|key|
# value|headers] frames verbatim (ops.framing.RawFrameBatch) — so the
# consumer's columnar decoder runs over ONE buffer with zero
# per-record work on either side of the socket.  Standard Kafka
# clients never send it; standard servers answer UNSUPPORTED_VERSION
# and the client falls back to classic FETCH.
RAW_FETCH = 64
# The write-path mirror of RAW_FETCH (ISSUE 12): a produce whose
# payload is PRE-FRAMED store frames (offsets unstamped) the broker
# appends segment-verbatim after whole-batch CRC validation + offset
# stamping.  NOT idempotent (caller-owns-redelivery, exactly like
# PRODUCE — deliberately absent from IDEMPOTENT_APIS); a corrupt batch
# answers Kafka CORRUPT_MESSAGE (2) with nothing appended, and servers
# without the extension answer UNSUPPORTED_VERSION so producing clients
# pin back to classic PRODUCE.
RAW_PRODUCE = 65
# Emulator-family admin extension (ISSUE 14): elastic reassignment
# verbs against a live cluster — `python -m iotml.cluster add-broker /
# drain-broker` connect to any broker's wire port and drive the
# controller's online reassignment (new replica bootstraps over
# RAW_FETCH, joins the ISR, leadership moves, the old replica
# retires).  Served only when the wire server carries an `admin` hook
# (the ClusterController); everyone else answers UNSUPPORTED_VERSION.
CLUSTER_ADMIN = 66

# error codes
ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_CORRUPT_MESSAGE = 2
ERR_UNKNOWN_TOPIC = 3
ERR_NOT_LEADER_FOR_PARTITION = 6
ERR_REQUEST_TIMED_OUT = 7
ERR_NOT_ENOUGH_REPLICAS = 19
ERR_INVALID_REQUIRED_ACKS = 21
ERR_NOT_COORDINATOR = 16
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER_ID = 25
ERR_REBALANCE_IN_PROGRESS = 27
ERR_TOPIC_AUTHORIZATION_FAILED = 29
ERR_UNSUPPORTED_VERSION = 35
ERR_TOPIC_EXISTS = 36
ERR_SASL_AUTH_FAILED = 58
ERR_INVALID_CONFIG = 40
ERR_FENCED_LEADER_EPOCH = 74  # Kafka's own fencing error code
# Kafka's UNKNOWN_SERVER_ERROR: the CLUSTER_ADMIN handler answers it
# when a reassignment verb raises — named so clients can map it typed
# (the protocol-conformance pass rejects bare numeric codes)
ERR_UNKNOWN_SERVER = -1

_SUPPORTED = {PRODUCE: (2, 2), FETCH: (2, 2), LIST_OFFSETS: (1, 1),
              METADATA: (1, 1), OFFSET_COMMIT: (2, 2), OFFSET_FETCH: (1, 1),
              FIND_COORDINATOR: (0, 0), JOIN_GROUP: (0, 0),
              HEARTBEAT: (0, 0), LEAVE_GROUP: (0, 0), SYNC_GROUP: (0, 0),
              SASL_HANDSHAKE: (0, 0), API_VERSIONS: (0, 0),
              CREATE_TOPICS: (0, 0), RAW_FETCH: (0, 0),
              RAW_PRODUCE: (0, 0), CLUSTER_ADMIN: (0, 0)}

# APIs the client may auto-retry after a reconnect (see _request): a
# duplicate of any of these is invisible (pure reads) or a no-op
# (liveness signal).  Everything else — produce, offset-commit, topic
# creation, group membership changes — may have been APPLIED by the dead
# server before it died, so a blind retry double-applies; those surface
# ConnectionError and the caller owns redelivery.  The R2 lint
# (iotml.analysis) holds every _request call site to this list.
IDEMPOTENT_APIS = frozenset({FETCH, RAW_FETCH, METADATA, LIST_OFFSETS,
                             OFFSET_FETCH, API_VERSIONS, SASL_HANDSHAKE,
                             HEARTBEAT, FIND_COORDINATOR})


class SaslAuthError(ConnectionError):
    """The server explicitly REJECTED the credentials (handshake error
    or non-empty auth response) — as opposed to dying mid-handshake.
    Failover must not retry rejected credentials against every
    bootstrap server; connectivity errors it may."""


class NotLeaderForPartitionError(ConnectionError):
    """The addressed broker does not lead this (topic, partition).

    Kafka error 6: the cluster's partition map moved (shard failover,
    stale client metadata) and this broker — alive and reachable —
    refuses to serve a partition it doesn't own.  Routing clients
    (``iotml.cluster.ClusterClient``) catch it, refresh their cached
    metadata, and retry against the real leader; it subclasses
    ConnectionError so non-routing callers' existing redelivery loops
    treat it as the failover signal it is."""

    def __init__(self, topic: str, partition: int):
        super().__init__(
            f"broker is not the leader for {topic}:{partition}; refresh "
            f"metadata and route to the owning broker (Kafka error 6)")
        self.topic = topic
        self.partition = partition


class CoordinatorMovedError(ConnectionError):
    """A group/offset request landed on a broker that is not the group
    coordinator (Kafka error 16, NOT_COORDINATOR).  The caller
    re-discovers the coordinator via FIND_COORDINATOR and retries —
    cluster group state is pinned to exactly one broker."""


class NotEnoughReplicasError(ConnectionError):
    """An ``acks=all`` produce was refused because the in-sync-replica
    set is below ``min_isr`` — or the topic has no ISR configured at
    all on a quorum-enabled broker (Kafka error 19,
    NOT_ENOUGH_REPLICAS).  NOTHING was appended, so redelivery is safe;
    it subclasses ConnectionError because the condition is retriable
    (an evicted follower re-admits, a reassignment completes) and every
    existing redelivery loop already treats ConnectionError as the
    try-again signal."""


class ProduceTimedOutError(ConnectionError):
    """An ``acks=all`` produce was APPENDED on the leader but the
    quorum high-water mark did not reach it within the request timeout
    (Kafka error 7, REQUEST_TIMED_OUT).  The record is durable on the
    leader yet unacked — the caller redelivers (at-least-once, exactly
    Kafka's producer-timeout contract; consumers cannot have observed
    the unacked copy, it sits above the quorum HWM)."""


class FencedEpochError(ConnectionError):
    """A produce/commit was refused because the leadership epochs
    disagree — either this client slept through a failover (its epoch
    is stale) or it reached a RESURRECTED OLD LEADER (the server's
    epoch is stale).  Both directions protect the log from splitting.
    Subclasses ConnectionError so every existing redelivery loop
    (scorer rewind, replica reconnect) treats it as a failover signal;
    the client has already re-resolved topology before raising."""


# ---------------------------------------------------------- epoch carrier
# The fencing epoch rides the request header's client_id as a trailing
# `@e<N>` tag — the one header field the classic encoding lets us extend
# without changing a single wire type, so standard Kafka clients (no
# tag → unfenced legacy path) remain byte-compatible with the server.
_EPOCH_TAG_RE = re.compile(r"^(.*)@e(\d+)$")


def tag_client_id(client_id: str, epoch: Optional[int]) -> str:
    return client_id if epoch is None else f"{client_id}@e{int(epoch)}"


def parse_client_epoch(client_id: Optional[str]) -> Tuple[str, Optional[int]]:
    """(bare client id, stamped epoch or None) from a header client_id."""
    if not client_id:
        return client_id or "", None
    m = _EPOCH_TAG_RE.match(client_id)
    if m is None:
        return client_id, None
    return m.group(1), int(m.group(2))


# ------------------------------------------------------------- primitives
class _Writer:
    def __init__(self):
        self.buf = bytearray()

    def i8(self, v):  self.buf += struct.pack(">b", v); return self
    def i16(self, v): self.buf += struct.pack(">h", v); return self
    def i32(self, v): self.buf += struct.pack(">i", v); return self
    def i64(self, v): self.buf += struct.pack(">q", v); return self
    def u32(self, v): self.buf += struct.pack(">I", v); return self

    def string(self, s: Optional[str]):
        if s is None:
            return self.i16(-1)
        b = s.encode()
        self.i16(len(b))
        self.buf += b
        return self

    def bytes_(self, b: Optional[bytes]):
        if b is None:
            return self.i32(-1)
        self.i32(len(b))
        self.buf += b
        return self

    def array(self, items, fn):
        self.i32(len(items))
        for it in items:
            fn(self, it)
        return self


class _Reader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _unpack(self, fmt, size):
        (v,) = struct.unpack_from(fmt, self.buf, self.pos)
        self.pos += size
        return v

    def i8(self):  return self._unpack(">b", 1)
    def i16(self): return self._unpack(">h", 2)
    def i32(self): return self._unpack(">i", 4)
    def i64(self): return self._unpack(">q", 8)
    def u32(self): return self._unpack(">I", 4)

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        s = self.buf[self.pos:self.pos + n].decode()
        self.pos += n
        return s

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def array(self, fn) -> list:
        n = self.i32()
        return [fn(self) for _ in range(max(n, 0))]


# ---------------------------------------------------------- message sets
# Native (C++) codec for the hot directions: the pure-Python loops below
# are the oracle and the fallback, but at platform rates (two consumers +
# a producer through one wire server = tens of thousands of records/s)
# the per-record Writer/Reader + crc32 work was a large slice of the
# server process's core.  Loaded lazily; byte parity is pinned by
# tests/test_kafka_wire.py.
_NATIVE_LIB = None
_NATIVE_TRIED = False


def _native_lib():
    global _NATIVE_LIB, _NATIVE_TRIED
    if not _NATIVE_TRIED:
        _NATIVE_TRIED = True
        try:
            import ctypes

            from .native import load

            lib = load()
            if lib is not None:
                c = ctypes
                i64p = c.POINTER(c.c_int64)
                u8p = c.POINTER(c.c_uint8)
                lib.iotml_msgset_encode.restype = c.c_int64
                lib.iotml_msgset_encode.argtypes = [
                    c.c_char_p, i64p, c.c_char_p, i64p, u8p, i64p, i64p,
                    c.c_int64, u8p, c.c_int64]
                lib.iotml_msgset_decode.restype = c.c_int64
                lib.iotml_msgset_decode.argtypes = [
                    c.c_char_p, c.c_int64, c.c_int64, i64p, i64p, i64p,
                    u8p, u8p, c.c_int64, i64p, u8p, u8p, c.c_int64]
                _NATIVE_LIB = lib
        except Exception:
            _NATIVE_LIB = None
    return _NATIVE_LIB


def _encode_message_set_py(entries) -> bytes:
    out = _Writer()
    for offset, key, value, ts in entries:
        body = _Writer()
        body.i8(1).i8(0).i64(ts)          # magic 1, attributes 0, timestamp
        body.bytes_(key).bytes_(value)
        msg = struct.pack(">I", zlib.crc32(bytes(body.buf))) + bytes(body.buf)
        out.i64(offset).i32(len(msg))
        out.buf += msg
    return bytes(out.buf)


def columnar_kvt(kvt_entries):
    """[(key, value, ts)] → (values, voff, keys, koff, knull, ts) arrays —
    the columnar layout both native produce paths (the C++ client's
    produce_many and the server-side msgset encoder) hand to the C ABI.
    keys/koff/knull are None when every key is None (callers pass NULL
    pointers, the all-unkeyed fast case)."""
    import numpy as np

    n = len(kvt_entries)
    values = b"".join(v for _, v, _ in kvt_entries)
    voff = np.zeros((n + 1,), np.int64)
    np.cumsum([len(v) for _, v, _ in kvt_entries], out=voff[1:])
    ts = np.asarray([t for _, _, t in kvt_entries], np.int64)
    if not any(k is not None for k, _, _ in kvt_entries):
        return values, voff, None, None, None, ts
    keys = b"".join(k or b"" for k, _, _ in kvt_entries)
    koff = np.zeros((n + 1,), np.int64)
    np.cumsum([len(k or b"") for k, _, _ in kvt_entries], out=koff[1:])
    knull = np.asarray([1 if k is None else 0 for k, _, _ in kvt_entries],
                       np.uint8)
    return values, voff, keys, koff, knull, ts


def encode_message_set(entries: List[Tuple[int, Optional[bytes],
                                           Optional[bytes], int]]) -> bytes:
    """entries: [(offset, key, value, timestamp_ms)] → MessageSet v1 bytes."""
    lib = _native_lib()
    # a null VALUE has no native representation on the encode side (the
    # server never stores them); fall back for exactness
    if lib is None or not entries or \
            any(v is None for _, _, v, _ in entries):
        return _encode_message_set_py(entries)
    import ctypes

    import numpy as np

    n = len(entries)
    values, voff, keys, koff, knull, ts = columnar_kvt(
        [(k, v, t) for _, k, v, t in entries])
    offs = np.asarray([o for o, _, _, _ in entries], np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    if keys is None:
        kargs = (None, None, None)
        keys_len = 0
    else:
        kargs = (ctypes.c_char_p(keys), koff.ctypes.data_as(i64p),
                 knull.ctypes.data_as(u8p))
        keys_len = len(keys)
    cap = len(values) + keys_len + 40 * n
    out = ctypes.create_string_buffer(cap)
    rc = lib.iotml_msgset_encode(
        ctypes.c_char_p(values), voff.ctypes.data_as(i64p), *kargs,
        ts.ctypes.data_as(i64p), offs.ctypes.data_as(i64p), n,
        ctypes.cast(out, u8p), cap)
    if rc < 0:
        return _encode_message_set_py(entries)
    return out.raw[:rc]


def _decode_message_set_py(buf: bytes):
    out = []
    r = _Reader(buf)
    while r.pos + 12 <= len(buf):
        offset = r.i64()
        size = r.i32()
        if r.pos + size > len(buf):
            break  # partial trailing message
        end = r.pos + size
        crc = r.u32()
        if zlib.crc32(buf[r.pos:end]) != crc:
            raise ValueError(f"message CRC mismatch at offset {offset}")
        magic = r.i8()
        r.i8()  # attributes (no compression support needed)
        ts = r.i64() if magic >= 1 else 0
        key = r.bytes_()
        value = r.bytes_()
        r.pos = end
        out.append((offset, key, value, ts))
    return out


def decode_message_set(buf: bytes) -> List[Tuple[int, Optional[bytes],
                                                 Optional[bytes], int]]:
    """MessageSet v1 bytes → [(offset, key, value, timestamp_ms)].  A
    truncated trailing entry (Kafka allows partial final messages in fetch
    responses) is dropped."""
    lib = _native_lib()
    if lib is None or len(buf) < 26:
        return _decode_message_set_py(buf)
    import ctypes

    import numpy as np

    max_n = len(buf) // 26 + 1  # 26 = min bytes per v1 record
    offs = np.zeros((max_n,), np.int64)
    ts = np.zeros((max_n,), np.int64)
    koff = np.zeros((max_n + 1,), np.int64)
    knull = np.zeros((max_n,), np.uint8)
    voff = np.zeros((max_n + 1,), np.int64)
    vnull = np.zeros((max_n,), np.uint8)
    keys = ctypes.create_string_buffer(max(len(buf), 1))
    values = ctypes.create_string_buffer(max(len(buf), 1))
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = lib.iotml_msgset_decode(
        ctypes.c_char_p(buf), len(buf), max_n,
        offs.ctypes.data_as(i64p), ts.ctypes.data_as(i64p),
        koff.ctypes.data_as(i64p), knull.ctypes.data_as(u8p),
        ctypes.cast(keys, u8p), len(buf),
        voff.ctypes.data_as(i64p), vnull.ctypes.data_as(u8p),
        ctypes.cast(values, u8p), len(buf))
    if rc < 0:
        # CRC/framing errors fall back so the Python decoder raises its
        # exact error text (the wire contract tests pin it)
        return _decode_message_set_py(buf)
    kraw = keys.raw
    vraw = values.raw
    return [(int(offs[i]), None if knull[i] else kraw[koff[i]:koff[i + 1]],
             None if vnull[i] else vraw[voff[i]:voff[i + 1]], int(ts[i]))
            for i in range(rc)]


def _req_header(api_key: int, api_version: int, corr: int,
                client_id: str) -> bytes:
    w = _Writer()
    w.i16(api_key).i16(api_version).i32(corr).string(client_id)
    return bytes(w.buf)


# ------------------------------------------------------------------ client
class ProducePartitionMixin:
    """Client-side keyed partitioner + produce conveniences shared by the
    Python and native (C++) wire clients.  One implementation so keyed
    records land on the same partition no matter which client produced them
    (per-key ordering is a cross-client invariant).  Subclasses provide
    `_partition_count_or_default(topic)` and `produce_many`, plus the
    `_rr` round-robin state dict.
    """

    def _partition_for(self, topic: str, key: Optional[bytes]) -> int:
        n = self._partition_count_or_default(topic)
        if key is None:
            self._rr[topic] = (self._rr.get(topic, -1) + 1) % n
            return self._rr[topic]
        return zlib.crc32(key) % n

    def produce(self, topic: str, value: bytes, key: Optional[bytes] = None,
                partition: Optional[int] = None, timestamp_ms: int = 0,
                headers: Optional[tuple] = None) -> int:
        # headers accepted for Broker duck-type parity and dropped: the
        # wire protocol (MessageSet v1) has no header slot
        return self.produce_many(topic, [(key, value, timestamp_ms)],
                                 partition=partition)

    def produce_batch(self, topic: str, values, key=None, partition=None) -> int:
        return self.produce_many(topic, [(key, v, 0) for v in values],
                                 partition=partition)


class KafkaWireBroker(ProducePartitionMixin):
    """Kafka-protocol client with the `Broker` emulator's duck-type.

    One socket, one lock: requests are serialized (the reference's data
    path is single-consumer per process too).  Metadata is cached for the
    client-side partitioner and refreshed on topic misses.
    """

    def __init__(self, servers: str, client_id: str = "iotml",
                 sasl_username: Optional[str] = None,
                 sasl_password: Optional[str] = None,
                 timeout_s: float = 30.0, topology=None,
                 epoch: Optional[int] = None,
                 acks: Optional[int] = None,
                 replica_id: int = -1):
        self.client_id = client_id
        #: default required_acks for produce paths (None = -1, the
        #: classic client default: quorum where the topic is
        #: replicated, leader-ack otherwise — Kafka RF-1 semantics).
        #: Per-call `acks=` overrides (the bench's acks=1 leg).
        self._acks = -1 if acks is None else int(acks)
        #: >= 0 marks this client as replica `replica_id`'s mirror leg:
        #: FETCH/RAW_FETCH carry the id, the leader tracks the fetch
        #: position in its ISR, and the quorum read barrier is bypassed
        #: (a follower exists to read the un-replicated tail).
        self._replica_id = int(replica_id)
        self._lock = threading.Lock()
        self._corr = 0
        # bootstrap list: try each server in order (a standard client's
        # bootstrap.servers semantics), keep the first that answers.  The
        # full list is retained for FAILOVER: a request that hits a dead
        # socket reconnects to the next reachable server and retries once
        # (see _request) — how a consumer survives a leader death when a
        # FollowerReplica serves the same topics on the second address.
        from ..utils.net import parse_bootstrap

        self._servers = list(parse_bootstrap(servers))
        self._servers_repr = servers
        self._timeout_s = timeout_s
        self._sasl_creds = ((sasl_username, sasl_password or "")
                            if sasl_username is not None else None)
        # supervised topology (iotml.supervise.Topology duck-type): when
        # given, every (re)connect re-resolves the ACTIVE leader + epoch
        # from it instead of walking the static bootstrap order, and the
        # epoch is stamped into each request's client id so the server
        # can fence a stale party (see FencedEpochError).
        self._topology = topology
        self._epoch = epoch
        self._sock = None
        self._connect_any()  # resolves topology first (its only caller)
        self._meta: Dict[str, int] = {}  # topic → partition count
        self._rr: Dict[str, int] = {}
        # high-water marks stashed off every classic fetch response —
        # the consumer-lag source that costs zero extra round trips
        # (ISSUE 13 satellite; see last_hwm)
        self._hwm: Dict[tuple, int] = {}

    # ------------------------------------------------------ epoch fencing
    @property
    def epoch(self) -> Optional[int]:
        return self._epoch

    def set_epoch(self, epoch: Optional[int]) -> None:
        """Stamp `epoch` into subsequent request headers (None = legacy
        unfenced client)."""
        self._epoch = epoch

    def set_replica_id(self, replica_id: int) -> None:
        """Mark this client as a replica's mirror leg: subsequent
        FETCH/RAW_FETCH requests carry `replica_id` so the leader's ISR
        tracker observes the fetch positions (and serves the tail)."""
        self._replica_id = int(replica_id)

    def _refresh_topology(self) -> None:
        """Re-resolve (servers, epoch) from the published topology.
        Caller must hold the lock (or be __init__, pre-threading)."""
        if self._topology is None:
            return
        from ..utils.net import parse_bootstrap

        servers, epoch = self._topology.resolve()
        self._servers = list(parse_bootstrap(",".join(servers)))
        self._servers_repr = ",".join(servers)
        self._epoch = epoch

    def _fenced(self, what: str) -> "FencedEpochError":
        """Build the fence error AFTER re-resolving topology and
        reconnecting, so the caller's retry (its redelivery loop) talks
        to the real leader at the current epoch instead of failing
        identically forever."""
        stale = self._epoch
        # lint-ok: R4 single-socket client by design (same contract as
        # _request): reconnect I/O is bounded by timeout_s and requests
        # are serialized over one connection anyway.
        with self._lock:
            try:
                self._connect_any()  # re-resolves topology first
            except OSError:
                # nothing reachable right now: the next request's
                # reconnect path retries; the fence error still stands
                pass
        return FencedEpochError(
            f"{what} fenced: leadership epoch mismatch (client was at "
            f"epoch {stale}, now {self._epoch}); topology re-resolved — "
            f"the caller owns redelivery")

    # ---------------------------------------------------------- transport
    def _connect_any(self) -> None:
        """Connect to the first reachable bootstrap server (+ SASL).
        Caller must hold the lock (or be __init__, pre-threading).

        An explicit SASL REJECTION raises immediately (the credentials
        are wrong everywhere — retrying them fleet-wide would spam auth
        failures); a server dying mid-handshake is connectivity and
        falls through to the next server.  Either way the dead/rejected
        socket is closed, never leaked."""
        # a supervised client re-reads the published topology on every
        # reconnect: after a promotion the first server tried is the new
        # leader (and the stamp below carries the new epoch), not
        # whatever the static bootstrap order said at construction
        self._refresh_topology()
        last_err: Optional[Exception] = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        for host, port in self._servers:
            try:
                sock = socket.create_connection((host, port),
                                                timeout=self._timeout_s)
            except OSError as e:
                last_err = e
                continue
            try:
                self._sock = sock
                if self._sasl_creds is not None:
                    self._sasl_plain_raw(*self._sasl_creds)
                return
            except SaslAuthError:
                self._sock = None
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            except OSError as e:
                last_err = e
                self._sock = None
                try:
                    sock.close()
                except OSError:
                    pass
        raise last_err or \
            OSError(f"no reachable broker in {self._servers_repr!r}")

    def _recv_exact(self, n: int) -> bytes:
        return recv_exact(self._sock, n, "broker closed connection")

    def _send_frame(self, payload: bytes) -> None:
        act = chaos.point("kafka_wire.send")
        data = struct.pack(">i", len(payload)) + payload
        if act is not None and act.kind == "short_write":
            # a torn frame on the wire: the server sees a truncated
            # request and drops the connection, this client fails over
            self._sock.sendall(data[: len(data) // 2])
            raise OSError("chaos[kafka_wire.send]: short write")
        self._sock.sendall(data)

    def _recv_frame(self) -> bytes:
        chaos.point("kafka_wire.recv")
        (size,) = struct.unpack(">i", self._recv_exact(4))
        return self._recv_exact(size)

    def _exchange(self, api_key: int, api_version: int,
                  body: bytes) -> tuple:
        """One request/response on the current socket; caller holds the
        lock.  Returns (corr, resp bytes)."""
        self._corr += 1
        corr = self._corr
        self._send_frame(_req_header(
            api_key, api_version, corr,
            tag_client_id(self.client_id, self._epoch)) + body)
        return corr, self._recv_frame()

    def _request(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        # lint-ok: R4 single-socket client by design: requests are
        # serialized over one connection and every socket op is bounded by
        # timeout_s, so a stalled broker parks callers for at most that.
        with self._lock:
            try:
                if self._sock is None:
                    # a previous reconnect found no reachable server and
                    # left no socket; try again now (the outage may be a
                    # restart in flight) instead of dying on a dead handle
                    self._connect_any()
                corr, resp = self._exchange(api_key, api_version, body)
            except OSError as e:
                # dead server: fail over across the bootstrap list, then
                # retry ONCE — but only IDEMPOTENT_APIS.  The dead server
                # may have applied the request before dying, so retrying
                # produce/commit would double-apply records/offsets; those
                # surface ConnectionError (on a now-reconnected client) and
                # the caller opts into redelivery explicitly.
                self._connect_any()
                if api_key not in IDEMPOTENT_APIS:
                    raise ConnectionError(
                        f"connection lost during non-idempotent request "
                        f"(api_key={api_key}); not auto-retried — the dead "
                        f"server may have applied it.  Reconnected; the "
                        f"caller decides whether to redeliver.") from e
                corr, resp = self._exchange(api_key, api_version, body)
        r = _Reader(resp)
        got = r.i32()
        if got != corr:
            raise ConnectionError(f"correlation id mismatch: {got} != {corr}")
        return r

    def _sasl_plain_raw(self, username: str, password: str) -> None:
        """SASL PLAIN on the current socket, no locking (used by
        _connect_any, which runs under the lock or from __init__)."""
        w = _Writer()
        w.string("PLAIN")
        corr, resp = self._exchange(SASL_HANDSHAKE, 0, bytes(w.buf))
        r = _Reader(resp)
        if r.i32() != corr:
            raise ConnectionError("correlation id mismatch in handshake")
        err = r.i16()
        mechanisms = r.array(lambda rd: rd.string())
        if err == ERR_SASL_AUTH_FAILED:
            # the server rejected the MECHANISM (not the credentials —
            # those are checked on the raw token exchange below)
            raise SaslAuthError(
                f"server rejected SASL mechanism PLAIN; offers "
                f"{mechanisms}")
        if err != ERR_NONE:
            raise SaslAuthError(
                f"SASL handshake failed ({err}); server offers {mechanisms}")
        token = b"\x00" + username.encode() + b"\x00" + password.encode()
        self._send_frame(token)   # raw token frame (pre-KIP-152)
        if self._recv_frame() != b"":
            raise SaslAuthError("SASL PLAIN authentication failed")

    # ------------------------------------------------------------ metadata
    def _metadata(self, topics: Optional[List[str]] = None) -> dict:
        w = _Writer()
        if topics is None:
            w.i32(-1)
        else:
            w.array(topics, lambda wr, t: wr.string(t))
        # lint-ok: P3 metadata reports existence per topic: unknown
        # topics carry ERR_UNKNOWN_TOPIC in their row and are simply
        # left out of the leaders map — absence IS the answer, not an
        # error to raise
        r = self._request(METADATA, 1, bytes(w.buf))

        def broker(rd):
            return (rd.i32(), rd.string(), rd.i32(), rd.string())

        def partition(rd):
            err, pid, leader = rd.i16(), rd.i32(), rd.i32()
            rd.array(lambda x: x.i32())  # replicas
            rd.array(lambda x: x.i32())  # isr
            return (err, pid, leader)

        def topic(rd):
            err = rd.i16()
            name = rd.string()
            rd.i8()  # is_internal
            parts = rd.array(partition)
            return (err, name, parts)

        brokers = r.array(broker)
        r.i32()  # controller id
        tops = r.array(topic)
        meta = {"brokers": brokers, "topics": {}, "leaders": {}}
        for err, name, parts in tops:
            if err == ERR_NONE:
                meta["topics"][name] = len(parts)
                self._meta[name] = len(parts)
                for perr, pid, leader in parts:
                    if perr == ERR_NONE:
                        # per-partition leader NODE ID (cluster servers
                        # publish the real owner; classic servers say 0)
                        meta["leaders"][(name, pid)] = leader
        return meta

    def cluster_metadata(self, topics: Optional[List[str]] = None) -> dict:
        """Raw metadata: {"brokers": [(node, host, port, rack)],
        "topics": {name: n_partitions},
        "leaders": {(topic, partition): node}} — what a routing client
        (iotml.cluster.ClusterClient) caches and refreshes on
        NOT_LEADER_FOR_PARTITION."""
        return self._metadata(topics)

    def find_coordinator(self, group: str) -> Tuple[int, str, int]:
        """(node_id, host, port) of the group coordinator — in a cluster
        the one broker holding membership + offset state for `group`."""
        w = _Writer()
        w.string(group)
        r = self._request(FIND_COORDINATOR, 0, bytes(w.buf))
        err = r.i16()
        node, host, port = r.i32(), r.string(), r.i32()
        if err != ERR_NONE:
            raise RuntimeError(f"find_coordinator({group}): error {err}")
        return node, host or "", port

    def topics(self) -> List[str]:
        return sorted(self._metadata()["topics"])

    def topic(self, name: str) -> TopicSpec:
        n = self._meta.get(name) or self._metadata([name])["topics"].get(name)
        if n is None:
            raise KeyError(name)
        return TopicSpec(name, n)

    def create_topic(self, name: str, partitions: int = 1,
                     retention_messages: Optional[int] = None,
                     retention_bytes: Optional[int] = None,
                     retention_ms: Optional[int] = None,
                     cleanup_policy: Optional[str] = None) -> TopicSpec:
        w = _Writer()
        # retention and cleanup.policy ride CreateTopics v0's standard
        # config entries — retention.bytes / retention.ms /
        # cleanup.policy are Kafka's own names; retention.messages is
        # the emulator-family extension
        cfgs = [(k, str(v)) for k, v in
                (("retention.messages", retention_messages),
                 ("retention.bytes", retention_bytes),
                 ("retention.ms", retention_ms),
                 ("cleanup.policy", cleanup_policy)) if v is not None]

        def one(wr, _):
            wr.string(name).i32(partitions).i16(1)
            wr.i32(0)  # replica assignment: none
            wr.array(cfgs, lambda cw, kv: cw.string(kv[0]).string(kv[1]))

        w.array([None], one)
        w.i32(10_000)  # timeout ms
        # retry-ok: not auto-retried; a lost CreateTopics surfaces
        # ConnectionError and re-issuing is safe (TOPIC_EXISTS handled below)
        r = self._request(CREATE_TOPICS, 0, bytes(w.buf))
        errs = r.array(lambda rd: (rd.string(), rd.i16()))
        existed = False
        for _, err in errs:
            if err == ERR_TOPIC_EXISTS:
                existed = True
            elif err == ERR_INVALID_CONFIG:
                # mirrors the in-process broker's validation contract
                raise ValueError(
                    f"create_topic({name}): broker rejected the config "
                    f"(negative retention?)")
            elif err != ERR_NONE:
                raise RuntimeError(f"create_topic({name}) failed: error {err}")
        if existed:
            # real partition count may differ from the request; trust metadata
            self._meta.pop(name, None)
            return self.topic(name)
        self._meta[name] = partitions
        return TopicSpec(name, partitions)

    # ------------------------------------------------------------- produce
    def _partition_count_or_default(self, topic: str) -> int:
        n = self._meta.get(topic)
        if n is None:
            n = self._metadata([topic])["topics"].get(topic, 1)
        return n

    def produce_many(self, topic: str, entries, partition=None,
                     acks: Optional[int] = None,
                     timeout_ms: int = 10_000) -> int:
        """entries: [(key, value, timestamp_ms[, headers])] → offset of the
        last one.  Record headers (the trace-context carrier on the
        in-process broker) are DROPPED here: MessageSet v1 has no header
        slot, so traces end at a wire-broker boundary by design.

        ``acks`` (default: the client's configured default, -1): -1
        acks at the quorum high-water mark on replicated topics
        (leader-only on unreplicated ones — Kafka RF-1), 1 acks at the
        leader append, 0 is fire-and-forget (the response is immediate
        and carries no delivery guarantee).  A quorum that cannot form
        raises NotEnoughReplicasError (nothing appended); a quorum that
        does not catch up within ``timeout_ms`` raises
        ProduceTimedOutError (appended, unacked — redeliver)."""
        by_part: Dict[int, list] = {}
        for key, value, ts, *_hdrs in entries:
            p = self._partition_for(topic, key) if partition is None else partition
            by_part.setdefault(p, []).append((0, key, value, ts))
        last = -1
        w = _Writer()
        w.i16(self._acks if acks is None else int(acks))
        w.i32(int(timeout_ms))

        def part_entry(wr, item):
            p, ents = item
            wr.i32(p).bytes_(encode_message_set(ents))

        def topic_entry(wr, _):
            wr.string(topic).array(sorted(by_part.items()), part_entry)

        w.array([None], topic_entry)
        # retry-ok: produce is NOT auto-retried (double-append risk);
        # ConnectionError reaches the producer, which owns redelivery
        r = self._request(PRODUCE, 2, bytes(w.buf))

        def part_resp(rd):
            p, err, base = rd.i32(), rd.i16(), rd.i64()
            rd.i64()  # log append time
            return (p, err, base)

        tops = r.array(lambda rd: (rd.string(), rd.array(part_resp)))
        for _, parts in tops:
            for p, err, base in parts:
                if err == ERR_FENCED_LEADER_EPOCH:
                    # stale party detected (this client OR a resurrected
                    # old leader): nothing was appended — re-resolve and
                    # hand redelivery back to the caller
                    raise self._fenced(f"produce to {topic}:{p}")
                if err == ERR_NOT_LEADER_FOR_PARTITION:
                    # sharded cluster: this broker no longer owns the
                    # partition — nothing appended THERE; the routing
                    # client refreshes its map and redelivers
                    raise NotLeaderForPartitionError(topic, p)
                if err == ERR_NOT_ENOUGH_REPLICAS:
                    raise NotEnoughReplicasError(
                        f"produce to {topic}:{p} refused: ISR below "
                        f"min_isr (or no ISR configured for acks=all); "
                        f"nothing appended — redeliver when the quorum "
                        f"re-forms")
                if err == ERR_REQUEST_TIMED_OUT:
                    raise ProduceTimedOutError(
                        f"produce to {topic}:{p} appended but the "
                        f"quorum HWM did not reach it in time; unacked "
                        f"— the caller redelivers (at-least-once)")
                if err == ERR_INVALID_REQUIRED_ACKS:
                    raise ValueError(
                        f"produce to {topic}:{p} refused: required_acks "
                        f"must be -1, 0 or 1; nothing appended")
                if err == ERR_UNKNOWN_TOPIC:
                    raise KeyError(topic)
                if err == ERR_TOPIC_AUTHORIZATION_FAILED:
                    raise PermissionError(
                        f"produce to {topic}:{p} refused: the topic is "
                        f"restricted to its owning engine "
                        f"(Broker.restrict_topic); nothing appended")
                if err != ERR_NONE:
                    raise RuntimeError(f"produce to {topic}:{p} failed: {err}")
                last = max(last, base + len(by_part[p]) - 1)
        return last

    def produce_raw(self, topic: str, partition: int,
                    frames: bytes, acks: Optional[int] = None,
                    timeout_ms: int = 10_000) -> int:
        """RAW_PRODUCE over the wire: ship a pre-framed batch the broker
        appends segment-verbatim (CRC-validated whole, offsets stamped
        server-side).  Returns the batch's base offset.

        Raises NotImplementedError against a server without the
        extension (producers pin back to classic produce — the
        UNSUPPORTED_VERSION fallback), CorruptMessageError when the
        server rejected the whole batch (nothing appended; re-frame and
        redeliver), NotLeaderForPartitionError on a sharded bounce, and
        ConnectionError on transport death — NOT auto-retried, the
        caller owns redelivery exactly like produce."""
        w = _Writer()
        w.string(topic).i32(partition).bytes_(frames)
        # trailing-optional required_acks + timeout (ISSUE 14): the
        # RAW_PRODUCE mirror of classic produce's acks field.  Old
        # servers never read past the frames blob; absent fields mean
        # the client default (-1, like classic produce).
        w.i16(self._acks if acks is None else int(acks))
        w.i32(int(timeout_ms))
        # retry-ok: RAW_PRODUCE is NOT auto-retried (double-append risk,
        # same stance as produce); ConnectionError reaches the producer
        r = self._request(RAW_PRODUCE, 0, bytes(w.buf))
        err = r.i16()
        if err == ERR_UNSUPPORTED_VERSION:
            raise NotImplementedError(
                "server lacks the RAW_PRODUCE extension")
        base = r.i64()
        r.i32()  # count
        if err == ERR_CORRUPT_MESSAGE:
            raise CorruptMessageError(topic, partition, int(base))
        if err == ERR_FENCED_LEADER_EPOCH:
            raise self._fenced(f"raw produce to {topic}:{partition}")
        if err == ERR_NOT_LEADER_FOR_PARTITION:
            raise NotLeaderForPartitionError(topic, partition)
        if err == ERR_NOT_ENOUGH_REPLICAS:
            raise NotEnoughReplicasError(
                f"raw produce to {topic}:{partition} refused: ISR "
                f"below min_isr; nothing appended — redeliver when the "
                f"quorum re-forms")
        if err == ERR_REQUEST_TIMED_OUT:
            raise ProduceTimedOutError(
                f"raw produce to {topic}:{partition} appended but "
                f"unacked within the timeout — the caller redelivers")
        if err == ERR_INVALID_REQUIRED_ACKS:
            raise ValueError(
                f"raw produce to {topic}:{partition} refused: "
                f"required_acks must be -1, 0 or 1; nothing appended")
        if err == ERR_UNKNOWN_TOPIC:
            raise KeyError(topic)
        if err == ERR_TOPIC_AUTHORIZATION_FAILED:
            raise PermissionError(
                f"raw produce to {topic}:{partition} refused: the "
                f"topic is restricted to its owning engine "
                f"(Broker.restrict_topic); nothing appended")
        if err != ERR_NONE:
            raise RuntimeError(
                f"raw produce to {topic}:{partition} failed: {err}")
        return base

    # --------------------------------------------------------------- fetch
    def fetch(self, topic: str, partition: int, offset: int,
              max_messages: int = 1024) -> List[Message]:
        w = _Writer()
        # replica id (-1 = consumer; >= 0 = a follower's mirror fetch,
        # observed by the leader's ISR tracker), max_wait 0ms, min_bytes 1
        w.i32(self._replica_id).i32(0).i32(1)

        def part(wr, _):
            wr.i32(partition).i64(offset).i32(4 << 20)

        w.array([None], lambda wr, _: (wr.string(topic),
                                       wr.array([None], part)))
        r = self._request(FETCH, 2, bytes(w.buf))
        r.i32()  # throttle

        out: List[Message] = []
        tops = r.array(lambda rd: (rd.string(), rd.array(
            lambda p: (p.i32(), p.i16(), p.i64(), p.bytes_()))))
        for tname, parts in tops:
            for pid, err, hwm, record_set in parts:
                if err == ERR_OFFSET_OUT_OF_RANGE:
                    # the server's log head was trimmed past this offset
                    # (retention/realignment).  Surfaced, not swallowed:
                    # the old `continue` made trimmed history look like
                    # an empty poll.  `hwm` rides the response as the
                    # earliest retained offset for this error.
                    raise OffsetOutOfRangeError(tname or topic, pid,
                                                offset, max(hwm, 0))
                if err == ERR_UNKNOWN_TOPIC:
                    raise KeyError(topic)
                if err == ERR_NOT_LEADER_FOR_PARTITION:
                    raise NotLeaderForPartitionError(tname or topic, pid)
                if err != ERR_NONE:
                    raise RuntimeError(f"fetch {topic}:{pid} failed: {err}")
                # the hwm already rides every fetch response: cache it so
                # consumer-lag needs no extra round trip (last_hwm)
                self._hwm[(tname or topic, pid)] = int(hwm)
                for off, key, value, ts in decode_message_set(record_set or b""):
                    if off >= offset and len(out) < max_messages:
                        # a null VALUE is a tombstone (compacted-topic
                        # delete marker): surfaced as None, not coerced
                        # to b"" — consumers of changelogs must be able
                        # to tell "deleted" from "empty"
                        out.append(Message(tname, pid, off, value,
                                           key, ts))
        return out

    def last_hwm(self, topic: str, partition: int) -> Optional[int]:
        """The newest high-water mark seen for (topic, partition) in a
        fetch response, None before the first classic fetch — the
        zero-round-trip consumer-lag source (StreamConsumer.record_lag
        falls back to end_offset when absent)."""
        return self._hwm.get((topic, partition))

    def fetch_raw(self, topic: str, partition: int, offset: int,
                  max_bytes: int = 1 << 20):
        """Raw-batch fetch over the wire: the broker's store-format
        frame bytes, verbatim, as one `RawFrameBatch` — the consumer's
        columnar decoder does ALL record work on one buffer (zero
        per-record objects client-side, zero MessageSet re-encode
        server-side for durable brokers).  Returns None at/after the
        log end or against a server without the RAW_FETCH extension
        (callers fall back to classic fetch)."""
        from ..ops.framing import RawFrameBatch

        w = _Writer()
        w.string(topic).i32(partition).i64(offset).i32(max_bytes)
        # trailing-optional replica id (ISSUE 14): a follower's raw
        # mirror fetch identifies itself so the leader's ISR tracker
        # observes the position and serves past the quorum HWM.  Old
        # servers never read past max_bytes.
        w.i32(self._replica_id)
        r = self._request(RAW_FETCH, 0, bytes(w.buf))
        err = r.i16()
        if err == ERR_UNSUPPORTED_VERSION:
            # pre-extension (or relay) server: the response carries no
            # further fields.  Raised — not None — so consumers DISABLE
            # the columnar path instead of mistaking it for log end.
            raise NotImplementedError(
                "server lacks the RAW_FETCH extension")
        aux = r.i64()  # start offset; earliest-retained for error 1
        blob = r.bytes_()
        # trailing-optional hwm (ISSUE 13 satellite): newer servers
        # append the partition high-water mark after the blob so the
        # COLUMNAR path feeds consumer-lag with zero extra round trips,
        # exactly like classic fetch.  Optional both directions: an
        # older server simply ends the response here, an older client
        # never reads past the blob.
        if err == ERR_NONE and r.pos + 8 <= len(r.buf):
            hwm = r.i64()
            if hwm >= 0:  # -1 = the server could not answer cheaply
                self._hwm[(topic, partition)] = hwm
        if not blob and err == ERR_NONE:
            return None  # log end
        if err == ERR_OFFSET_OUT_OF_RANGE:
            raise OffsetOutOfRangeError(topic, partition, offset,
                                        max(aux, 0))
        if err == ERR_UNKNOWN_TOPIC:
            raise KeyError(topic)
        if err == ERR_NOT_LEADER_FOR_PARTITION:
            raise NotLeaderForPartitionError(topic, partition)
        if err != ERR_NONE:
            raise RuntimeError(f"raw fetch {topic}:{partition}: {err}")
        if blob is None:
            return None
        return RawFrameBatch(topic, partition, int(aux), blob)

    # ------------------------------------------------------------- offsets
    def _list_offset(self, topic: str, partition: int, timestamp: int) -> int:
        w = _Writer()
        w.i32(-1)

        def part(wr, _):
            wr.i32(partition).i64(timestamp)

        w.array([None], lambda wr, _: (wr.string(topic),
                                       wr.array([None], part)))
        r = self._request(LIST_OFFSETS, 1, bytes(w.buf))
        tops = r.array(lambda rd: (rd.string(), rd.array(
            lambda p: (p.i32(), p.i16(), p.i64(), p.i64()))))
        for _, parts in tops:
            for pid, err, ts, off in parts:
                if err == ERR_NOT_LEADER_FOR_PARTITION:
                    raise NotLeaderForPartitionError(topic, pid)
                if err == ERR_UNKNOWN_TOPIC:
                    raise KeyError(topic)
                if err != ERR_NONE:
                    raise RuntimeError(f"list_offsets {topic}:{pid}: {err}")
                return off
        raise RuntimeError("empty ListOffsets response")

    def end_offset(self, topic: str, partition: int = 0) -> int:
        return self._list_offset(topic, partition, -1)

    def begin_offset(self, topic: str, partition: int = 0) -> int:
        return self._list_offset(topic, partition, -2)

    def offset_for_timestamp(self, topic: str, partition: int,
                             timestamp_ms: int) -> int:
        """Earliest offset with record timestamp >= `timestamp_ms` —
        ListOffsets by timestamp, the Broker replay-API duck-type."""
        return self._list_offset(topic, partition, max(int(timestamp_ms), 0))

    # ------------------------------------------------- consumer-group API
    def commit(self, group: str, topic: str, partition: int, next_offset: int):
        """Simple-consumer commit: the generation=-1, unfenced special case
        of `commit_fenced`."""
        if not self.commit_fenced(group, -1, "",
                                  [(topic, partition, next_offset)]):
            raise RuntimeError(f"offset commit {topic}:{partition} fenced")

    def committed(self, group: str, topic: str, partition: int) -> Optional[int]:
        w = _Writer()
        w.string(group)

        def part(wr, _):
            wr.i32(partition)

        w.array([None], lambda wr, _: (wr.string(topic),
                                       wr.array([None], part)))
        r = self._request(OFFSET_FETCH, 1, bytes(w.buf))
        tops = r.array(lambda rd: (rd.string(), rd.array(
            lambda p: (p.i32(), p.i64(), p.string(), p.i16()))))
        for _, parts in tops:
            for pid, off, _meta, err in parts:
                if err == ERR_NOT_COORDINATOR:
                    raise CoordinatorMovedError(
                        f"offset fetch {topic}:{pid}: broker is not the "
                        f"coordinator")
                if err != ERR_NONE:
                    raise RuntimeError(f"offset fetch {topic}:{pid}: {err}")
                return None if off < 0 else off
        return None

    def committed_many(self, group: str, pairs
                       ) -> Dict[Tuple[str, int], int]:
        """Committed offsets for [(topic, partition), ...] in ONE
        OffsetFetch round-trip (the per-partition committed() loop cost a
        wire request each — at replica-mirror rates that was hundreds of
        idle requests/s against the leader).  Pairs with no committed
        offset are omitted from the result."""
        by_topic: Dict[str, List[int]] = {}
        for t, p in pairs:
            by_topic.setdefault(t, []).append(p)
        w = _Writer()
        w.string(group)
        w.array(sorted(by_topic.items()), lambda wr, tp: (
            wr.string(tp[0]),
            wr.array(sorted(tp[1]), lambda pw, p: pw.i32(p))))
        r = self._request(OFFSET_FETCH, 1, bytes(w.buf))
        tops = r.array(lambda rd: (rd.string(), rd.array(
            lambda p: (p.i32(), p.i64(), p.string(), p.i16()))))
        out: Dict[Tuple[str, int], int] = {}
        for tname, parts in tops:
            for pid, off, _meta, err in parts:
                if err == ERR_NOT_COORDINATOR:
                    raise CoordinatorMovedError(
                        f"offset fetch {tname}:{pid}: broker is not the "
                        f"coordinator")
                if err != ERR_NONE:
                    raise RuntimeError(f"offset fetch {tname}:{pid}: {err}")
                if off >= 0:
                    out[(tname, pid)] = off
        return out

    def commit_many(self, group: str, topic: str, entries) -> None:
        """Commit [(partition, next_offset), ...] of one topic in ONE
        OffsetCommit request (StreamConsumer.commit's fast path) —
        delegates to the fenced path with the simple-consumer generation.
        Mirrors commit(): raises if the server fences the request, so a
        future server-side semantics change cannot silently drop offsets
        (today the server never fences generation -1)."""
        if not self.commit_fenced(group, -1, "",
                                  [(topic, p, off) for p, off in entries]):
            raise RuntimeError(f"batched offset commit {topic} fenced")

    def commit_fenced(self, group: str, generation: int, member_id: str,
                      positions) -> bool:
        """Generation-fenced OffsetCommit (v2 carries generation+member).

        Offset commits are per-partition in Kafka, so three outcomes:
        every partition rejected with ILLEGAL_GENERATION → the member is
        fenced, nothing written, returns False; every partition accepted →
        True; a *mix* → the accepted partitions ARE committed but the rest
        were refused (the member named partitions outside its assignment) —
        that is a caller bug, surfaced as RuntimeError naming them."""
        by_topic: dict = {}
        for t, p, off in positions:
            by_topic.setdefault(t, []).append((p, off))
        w = _Writer()
        w.string(group).i32(generation).string(member_id).i64(-1)
        w.array(sorted(by_topic.items()), lambda wr, tp: (
            wr.string(tp[0]),
            wr.array(tp[1], lambda pw, p: pw.i32(p[0]).i64(p[1])
                     .string(None))))
        # retry-ok: offset commits are NOT auto-retried (a stale commit
        # replayed after a rebalance could fence-bypass); callers re-commit
        # from their own cursors on ConnectionError
        r = self._request(OFFSET_COMMIT, 2, bytes(w.buf))
        tops = r.array(lambda rd: (rd.string(), rd.array(
            lambda p: (p.i32(), p.i16()))))
        results = [(t, pid, err) for t, parts in tops for pid, err in parts]
        errs = {err for _, _, err in results}
        if errs == {ERR_NONE}:
            return True
        if errs == {ERR_ILLEGAL_GENERATION}:
            return False  # fenced: nothing was written
        if errs == {ERR_NOT_COORDINATOR}:
            # the group's coordinator moved (cluster failover): nothing
            # written here — re-find the coordinator and re-commit
            raise CoordinatorMovedError(
                f"offset commit {sorted(by_topic)}: broker is not the "
                f"coordinator")
        if errs == {ERR_FENCED_LEADER_EPOCH}:
            # leadership-epoch fence (distinct from the generation fence
            # above: this is the whole SERVER relationship being stale,
            # not one group member) — nothing written, caller re-commits
            # from its own cursors against the re-resolved leader
            raise self._fenced(f"offset commit {sorted(by_topic)}")
        bad = [(t, pid) for t, pid, err in results if err != ERR_NONE]
        raise RuntimeError(
            f"partial offset commit: partitions {bad} refused (outside this "
            f"member's assignment?); the rest were committed")

    # ------------------------------------------- group membership (wire)
    def join_group(self, group: str, topics, member_id: str = "",
                   session_timeout_ms: int = 10_000):
        """JoinGroup v0 with the standard consumer subscription metadata.
        Returns (generation, member_id, leader_id, members) where `members`
        is [(member_id, [topics])] — non-empty only for the elected leader
        (real brokers hand the leader everyone's subscriptions so it can
        compute the assignment client-side)."""
        meta = _Writer()
        meta.i16(0)
        meta.array(list(topics), lambda wr, t: wr.string(t))
        meta.bytes_(b"")
        w = _Writer()
        w.string(group).i32(session_timeout_ms).string(member_id)
        w.string("consumer")
        w.array([("range", bytes(meta.buf))],
                lambda wr, p: (wr.string(p[0]), wr.bytes_(p[1])))
        # retry-ok: join mutates membership (may create a member id); the
        # coordinator adapter's join loop retries with its member id, so a
        # lost response never leaks a zombie member past session timeout
        r = self._request(JOIN_GROUP, 0, bytes(w.buf))
        err = r.i16()
        if err == ERR_NOT_COORDINATOR:
            raise CoordinatorMovedError(
                f"join group {group}: broker is not the coordinator")
        if err != ERR_NONE:
            raise RuntimeError(f"join group {group}: error {err}")
        generation = r.i32()
        r.string()  # protocol
        leader = r.string()
        mid = r.string()
        members = []
        for other_id, blob in r.array(lambda rd: (rd.string(), rd.bytes_())):
            sub = []
            if blob:
                mr = _Reader(blob)
                try:
                    mr.i16()
                    sub = mr.array(lambda rd: rd.string())
                except struct.error:
                    sub = []
            members.append((other_id, sub))
        return generation, mid, leader, members

    def sync_group(self, group: str, generation: int, member_id: str,
                   assignments: Optional[dict] = None):
        """SyncGroup v0 → [(topic, partition), ...] assignment.

        `assignments` (leader only): {member_id: [(topic, [partitions])]}
        serialized in the standard ConsumerProtocolAssignment format — real
        brokers store-and-forward it to each member (our server computes
        assignment itself and ignores it, same response either way)."""
        w = _Writer()
        w.string(group).i32(generation).string(member_id)

        def one(wr, item):
            other_id, tps = item
            aw = _Writer()
            aw.i16(0)
            aw.array(sorted(tps), lambda xw, tp: (
                xw.string(tp[0]),
                aw_array_parts(xw, tp[1])))
            aw.bytes_(b"")
            wr.string(other_id).bytes_(bytes(aw.buf))

        def aw_array_parts(xw, parts):
            xw.array(sorted(parts), lambda pw, p: pw.i32(p))

        w.array(sorted((assignments or {}).items()), one)
        # retry-ok: sync is generation-fenced server-side; callers rejoin
        # on ConnectionError rather than replay a possibly-stale sync
        r = self._request(SYNC_GROUP, 0, bytes(w.buf))
        err = r.i16()
        blob = r.bytes_() or b""
        if err == ERR_NOT_COORDINATOR:
            raise CoordinatorMovedError(
                f"sync group {group}: broker is not the coordinator")
        if err == ERR_UNKNOWN_MEMBER_ID:
            raise RuntimeError(
                f"sync group {group}: member {member_id!r} unknown to "
                f"the coordinator — rejoin the group")
        if err == ERR_ILLEGAL_GENERATION:
            raise RuntimeError(
                f"sync group {group}: generation {generation} fenced by "
                f"a newer rebalance — rejoin the group")
        if err != ERR_NONE:
            raise RuntimeError(f"sync group {group}: error {err}")
        if not blob:
            return []  # coordinator had nothing for us (yet)
        ar = _Reader(blob)
        ar.i16()  # version
        pairs = []
        for topic, parts in ar.array(lambda rd: (rd.string(),
                                                 rd.array(lambda p: p.i32()))):
            pairs.extend((topic, p) for p in parts)
        return pairs

    def heartbeat_group(self, group: str, generation: int,
                        member_id: str) -> bool:
        w = _Writer()
        w.string(group).i32(generation).string(member_id)
        r = self._request(HEARTBEAT, 0, bytes(w.buf))
        err = r.i16()
        if err == ERR_NOT_COORDINATOR:
            raise CoordinatorMovedError(
                f"heartbeat {group}: broker is not the coordinator")
        if err in (ERR_UNKNOWN_MEMBER_ID, ERR_REBALANCE_IN_PROGRESS):
            # both mean "this generation is over": the caller rejoins —
            # same False signal either way, not worth distinct raises
            return False
        return err == ERR_NONE

    def leave_group(self, group: str, member_id: str) -> None:
        w = _Writer()
        w.string(group).string(member_id)
        # retry-ok: a lost leave is self-healing (session timeout expires
        # the member); not worth retrying against a possibly-new leader
        err = self._request(LEAVE_GROUP, 0, bytes(w.buf)).i16()
        if err == ERR_NOT_COORDINATOR:
            # surfaced typed so the cluster router's _coordinated wrapper
            # re-finds the coordinator instead of silently dropping the
            # leave (the session would only expire by timeout)
            raise CoordinatorMovedError(
                f"leave group {group}: broker is not the coordinator")

    # ----------------------------------------------------- cluster admin
    def cluster_admin(self, command: str, args: Optional[dict] = None,
                      ) -> dict:
        """CLUSTER_ADMIN extension: drive a live controller's elastic
        reassignment (`add-broker` / `drain-broker` / `status`) from
        another process.  Returns the controller's JSON report; raises
        NotImplementedError against a broker with no controller
        attached, RuntimeError with the controller's error text
        otherwise."""
        import json as _json

        w = _Writer()
        w.string(command)
        w.bytes_(_json.dumps(args or {}).encode())
        # retry-ok: admin verbs MUTATE cluster membership (a replayed
        # add-broker boots a second node); a ConnectionError surfaces
        # and the operator re-checks `status` before re-issuing
        r = self._request(CLUSTER_ADMIN, 0, bytes(w.buf))
        err = r.i16()
        if err == ERR_UNSUPPORTED_VERSION:
            raise NotImplementedError(
                "broker has no cluster controller attached "
                "(CLUSTER_ADMIN unsupported)")
        blob = r.bytes_() or b"{}"
        doc = _json.loads(blob.decode() or "{}")
        if err == ERR_UNKNOWN_SERVER:
            # the verb itself raised controller-side; the response body
            # carries the operator-facing error text
            raise RuntimeError(
                f"cluster admin {command!r} failed: "
                f"{doc.get('error', 'unknown server error')}")
        if err != ERR_NONE:
            raise RuntimeError(
                f"cluster admin {command!r} failed: "
                f"{doc.get('error', f'error {err}')}")
        return doc

    # --------------------------------------------------- api versions
    def api_versions(self) -> Dict[int, Tuple[int, int]]:
        """ApiVersions v0 → {api_key: (min_version, max_version)} — the
        server's supported-api table, the wire-level capability probe
        (a client can ask before using the raw columnar apis)."""
        r = self._request(API_VERSIONS, 0, b"")
        err = r.i16()
        ranges = r.array(lambda rd: (rd.i16(), rd.i16(), rd.i16()))
        if err != ERR_NONE:
            raise RuntimeError(f"api_versions failed: error {err}")
        return {k: (lo, hi) for k, lo, hi in ranges}

    def close(self) -> None:
        # _sock is None when the last reconnect attempt found no
        # reachable server (_connect_any clears it before trying) — a
        # replica losing its leader hits exactly this at stop()
        if self._sock is not None:
            self._sock.close()


class RemoteGroupCoordinator:
    """GroupCoordinator-shaped adapter over the wire protocol.

    Gives `stream.group.GroupConsumer` elastic membership against a broker
    in ANOTHER process: join/heartbeat/leave/fenced_commit ride JoinGroup/
    SyncGroup/Heartbeat/LeaveGroup/OffsetCommit requests, with membership
    state living broker-side — the missing piece that makes the reference's
    scalable-Deployment story (SURVEY §2.7) work across processes, exactly
    as Kafka's own coordinator does."""

    def __init__(self, client: "KafkaWireBroker", group_id: str,
                 session_timeout_ms: int = 10_000):
        self.broker = client
        self.group_id = group_id
        self.session_timeout_ms = session_timeout_ms

    def join(self, topics, member_id=None):
        mid = member_id or ""
        last_err = None
        for _ in range(5):  # a peer joining between Join and Sync bumps the
            generation, mid, leader, members = self.broker.join_group(
                self.group_id, topics, mid,  # generation: rejoin
                session_timeout_ms=self.session_timeout_ms)
            assignments = None
            if mid == leader and members:
                # elected leader: compute the range assignment client-side
                # and submit it in SyncGroup — the standard protocol flow a
                # real broker requires (ours computes server-side and gets
                # the same answer)
                assignments = self._leader_assign(members)
            try:
                assignment = self.broker.sync_group(
                    self.group_id, generation, mid, assignments)
                return mid, generation, assignment
            except RuntimeError as e:
                last_err = e
        raise last_err

    def _leader_assign(self, members):
        """RangeAssignor over the members' subscriptions, as
        {member_id: [(topic, [partitions])]}."""
        from .group import range_assign

        topic_parts: dict = {}
        for _mid, topics in members:
            for t in topics:
                if t not in topic_parts:
                    try:
                        topic_parts[t] = self.broker.topic(t).partitions
                    except KeyError:
                        continue  # subscribe-before-create: nothing yet
        flat = range_assign([m for m, _ in members], topic_parts)
        subscribed = {m: set(ts) for m, ts in members}
        out = {}
        for m, tps in flat.items():
            by_topic: dict = {}
            for t, p in tps:
                if t in subscribed.get(m, ()):
                    by_topic.setdefault(t, []).append(p)
            out[m] = sorted(by_topic.items())
        return out

    def heartbeat(self, member_id: str, generation: int) -> bool:
        return self.broker.heartbeat_group(self.group_id, generation,
                                           member_id)

    def fenced_commit(self, member_id: str, generation: int,
                      positions) -> bool:
        if not positions:
            # nothing to write, but the fencing signal must still be real:
            # a heartbeat verifies membership at this generation (the local
            # coordinator checks the same thing under its lock)
            return self.heartbeat(member_id, generation)
        return self.broker.commit_fenced(self.group_id, generation,
                                         member_id, positions)

    def leave(self, member_id: str) -> None:
        self.broker.leave_group(self.group_id, member_id)


# ------------------------------------------------------------------ server
class _KafkaConn(socketserver.BaseRequestHandler):
    """One client connection to the wire server."""

    def setup(self):
        with self.server._conn_lock:      # type: ignore[attr-defined]
            self.server._live_conns.add(self.request)

    def finish(self):
        with self.server._conn_lock:      # type: ignore[attr-defined]
            self.server._live_conns.discard(self.request)

    def _recv_exact(self, n: int) -> bytes:
        return recv_exact(self.request, n)

    def handle(self):
        broker: Broker = self.server.broker  # type: ignore[attr-defined]
        creds = self.server.credentials      # type: ignore[attr-defined]
        authed = creds is None
        sasl_pending = False
        try:
            while True:
                (size,) = struct.unpack(">i", self._recv_exact(4))
                frame = self._recv_exact(size)
                if sasl_pending:
                    # raw PLAIN token: [authzid] \0 user \0 password
                    parts = frame.split(b"\x00")
                    ok = len(parts) == 3 and \
                        (parts[1].decode(), parts[2].decode()) == creds
                    if not ok:
                        return  # auth failure: drop connection
                    authed, sasl_pending = True, False
                    self.request.sendall(struct.pack(">i", 0))
                    continue
                r = _Reader(frame)
                api_key, api_version, corr = r.i16(), r.i16(), r.i32()
                # the client id's trailing @e<N> tag carries the client's
                # leadership epoch (absent for standard/legacy clients)
                _cid, client_epoch = parse_client_epoch(r.string())
                w = _Writer()
                w.i32(corr)
                lo_hi = _SUPPORTED.get(api_key)
                if lo_hi is None or not lo_hi[0] <= api_version <= lo_hi[1]:
                    w.i16(ERR_UNSUPPORTED_VERSION)
                elif api_key == SASL_HANDSHAKE:
                    mech = r.string()
                    if mech == "PLAIN":
                        w.i16(ERR_NONE)
                        sasl_pending = not authed
                    else:
                        w.i16(ERR_SASL_AUTH_FAILED)
                    w.array(["PLAIN"], lambda wr, m: wr.string(m))
                elif not authed:
                    return  # protocol requests before auth: drop
                elif api_key == API_VERSIONS:
                    w.i16(ERR_NONE)
                    w.array(sorted(_SUPPORTED.items()),
                            lambda wr, kv: wr.i16(kv[0]).i16(kv[1][0])
                            .i16(kv[1][1]))
                else:
                    self._dispatch(broker, api_key, r, w,
                                   client_epoch=client_epoch)
                resp = bytes(w.buf)
                self.request.sendall(struct.pack(">i", len(resp)) + resp)
        except (ConnectionError, OSError, struct.error):
            pass

    @staticmethod
    def _valid_part(broker: Broker, topic: str, pid: int) -> bool:
        """Guard every broker access: an out-of-range partition must come
        back as Kafka error 3, not an IndexError that kills the connection."""
        return topic in broker.topics() and \
            0 <= pid < broker.topic(topic).partitions

    @staticmethod
    def _mark_raw_batch(frames: bytes, stage: str, topic: str,
                        pid: int, at_or_after=None) -> None:
        """Record the broker-process hop of a wire-carried batch trace
        (ISSUE 13): a sampled RAW batch carries its context in the
        first frame's headers — decode it and mark `stage`, so a
        cross-process reconstruction shows the MQTT→bridge→shard→
        consumer path through THIS broker.  One bounded first-frame
        parse, only under tracing; any malformed bytes are simply not a
        trace (the produce/fetch path itself validates separately).
        ``at_or_after`` gates re-served batch heads on the fetch side
        exactly like StreamConsumer._extract_batch_trace."""
        from ..ops.framing import first_frame_headers

        try:
            hdrs = first_frame_headers(frames, at_or_after=at_or_after)
        except (ValueError, struct.error):
            return
        ctx = _tracing.from_headers(hdrs)
        if ctx is not None:
            _tracing.mark_batch(ctx, stage, topic, pid)

    def _epoch_mismatch(self, client_epoch: Optional[int]) -> bool:
        """True when the fencing epochs disagree.  A stamped epoch below
        the server's means the CLIENT slept through a failover; above it
        means THIS SERVER is a resurrected old leader — either way the
        log-mutating request must be refused, or the log splits.
        Unstamped (legacy/standard-Kafka) clients pass unfenced."""
        server_epoch = self.server.epoch     # type: ignore[attr-defined]
        return client_epoch is not None and client_epoch != server_epoch

    @staticmethod
    def _produce_error_resp(w: _Writer, tops, err: int) -> None:
        """Serialize a classic PRODUCE response answering `err` for
        every partition of every topic — the one writer behind the
        retiring / invalid-acks / epoch-fence early returns (a future
        response-shape change must land in exactly one place)."""
        resp = [(tname, [(pid, err, -1) for pid, _ in parts])
                for tname, parts in tops]
        w.array(resp, lambda wr, t: (wr.string(t[0]), wr.array(
            t[1], lambda pw, p: pw.i32(p[0]).i16(p[1]).i64(p[2])
            .i64(-1))))
        w.i32(0)  # throttle

    def _not_coordinator(self) -> bool:
        """True when this broker is part of a cluster whose group
        coordinator is pinned to a DIFFERENT node: group membership and
        offset state must live in exactly one place, so every other
        broker answers NOT_COORDINATOR (16) and the client re-finds."""
        cluster = self.server.cluster        # type: ignore[attr-defined]
        return cluster is not None and \
            cluster.coordinator()[0] != cluster.node_id

    # ------------------------------------------------------------ handlers
    def _dispatch(self, broker: Broker, api_key: int, r: _Reader, w: _Writer,
                  client_epoch: Optional[int] = None):
        cluster = self.server.cluster          # type: ignore[attr-defined]
        if api_key == METADATA:
            n = r.i32()
            names = [r.string() for _ in range(max(n, 0))] if n >= 0 else None
            if names is None or n == 0:
                names = broker.topics()
            if cluster is not None:
                # cluster mode: the broker list is the WHOLE cluster and
                # every partition names its owning node — the map routing
                # clients cache (refreshed on NOT_LEADER_FOR_PARTITION)
                rows = list(cluster.brokers())
                my_id = cluster.node_id
            else:
                host, port = self.server.server_address[:2]  # type: ignore
                rows = [(0, host, port)]
                my_id = 0
            w.array(rows, lambda wr, b: wr.i32(b[0]).string(b[1])
                    .i32(b[2]).string(None))
            w.i32(my_id if cluster is None else rows[0][0])  # controller id

            def topic_entry(wr, name):
                known = name in broker.topics()
                wr.i16(ERR_NONE if known else ERR_UNKNOWN_TOPIC)
                wr.string(name).i8(0)
                parts = range(broker.topic(name).partitions) if known else []

                def part_entry(pw, p):
                    leader = my_id if cluster is None else \
                        cluster.leader_node(name, p)
                    pw.i16(ERR_NONE).i32(p).i32(-1 if leader is None
                                                else leader)
                    pw.array([leader if leader is not None else 0],
                             lambda x, v: x.i32(v))  # replicas
                    pw.array([leader if leader is not None else 0],
                             lambda x, v: x.i32(v))  # isr

                wr.array(list(parts), part_entry)

            w.array(names, topic_entry)
        elif api_key == PRODUCE:
            # required_acks is PARSED AND HONORED (ISSUE 14; it was
            # read-and-discarded before): 1 acks at the leader append,
            # -1 (acks=all) acks only once the batch is below the
            # quorum high-water mark, 0 answers immediately with no
            # delivery guarantee (errors masked — fire-and-forget).
            acks = r.i16()
            timeout_ms = r.i32()

            def part(rd):
                return (rd.i32(), rd.bytes_())

            tops = r.array(lambda rd: (rd.string(), rd.array(part)))
            if self.server.retiring:       # type: ignore[attr-defined]
                # reassignment step-down: leadership moved — answer
                # NOT_LEADER so every producer (epoch-stamped or
                # legacy) re-routes; nothing may land in a retired log
                self._produce_error_resp(w, tops,
                                         ERR_NOT_LEADER_FOR_PARTITION)
                return
            if acks not in (-1, 0, 1):
                self._produce_error_resp(w, tops,
                                         ERR_INVALID_REQUIRED_ACKS)
                return
            if self._epoch_mismatch(client_epoch):
                # fence BEFORE touching the broker: a stale-epoch produce
                # must append nothing anywhere
                self._produce_error_resp(w, tops,
                                         ERR_FENCED_LEADER_EPOCH)
                return
            repl = getattr(broker, "replication", None)
            resp = []
            for tname, parts in tops:
                presp = []
                for pid, record_set in parts:
                    entries = decode_message_set(record_set or b"")
                    if tname not in broker.topics() and cluster is None:
                        # cluster topics are provisioned cluster-wide by
                        # the controller/client fan-out; a single-broker
                        # auto-create here would fork the topic spec
                        broker.create_topic(tname, partitions=max(pid + 1, 1))
                    if not self._valid_part(broker, tname, pid):
                        presp.append((pid, ERR_UNKNOWN_TOPIC, -1))
                        continue
                    quorum = acks == -1 and repl is not None
                    if quorum:
                        # acks=all durability checks BEFORE any append:
                        # a topic with no ISR configured on a quorum-
                        # enabled broker is an explicit error, and an
                        # ISR below min_isr refuses (nothing appended —
                        # redelivery is safe).  A broker with NO
                        # replication state keeps Kafka's RF-1 shape:
                        # ISR = {leader}, acks=all == acks=1.
                        if not repl.covers(tname) or \
                                repl.isr_size(tname, pid) < repl.min_isr:
                            presp.append(
                                (pid, ERR_NOT_ENOUGH_REPLICAS, -1))
                            continue
                    try:
                        # bulk append under one broker lock — the
                        # per-message produce loop was a per-record cost
                        # in the server's hottest handler.  Null values
                        # pass through intact: a produced tombstone must
                        # land in the log as a tombstone, or compaction
                        # could never delete a key written over the wire.
                        # The returned LAST offset anchors both the
                        # response base and the quorum target: a
                        # re-read of end_offset could include a
                        # concurrent producer's later batch and make
                        # this request wait on (or time out over)
                        # records that are not its own.
                        last = broker.produce_many(
                            tname, [(key, value, ts)
                                    for _, key, value, ts in entries],
                            partition=pid)
                        base = last - len(entries) + 1 if entries \
                            else broker.end_offset(tname, pid)
                    except NotLeaderForPartitionError:
                        # sharded broker, unowned partition: Kafka error
                        # 6 — the client refreshes metadata and re-routes
                        presp.append(
                            (pid, ERR_NOT_LEADER_FOR_PARTITION, -1))
                        continue
                    except PermissionError:
                        # engine-owned topic (Broker.restrict_topic): an
                        # external client may not write the AVRO leg —
                        # the exclusivity trusted_passthrough relies on
                        presp.append(
                            (pid, ERR_TOPIC_AUTHORIZATION_FAILED, -1))
                        continue
                    if quorum and entries:
                        # block this handler thread until THIS batch is
                        # below the quorum HWM (followers fetch on their
                        # own connections/threads, so the wait starves
                        # nothing).  A timeout means APPENDED-UNACKED:
                        # the caller redelivers, Kafka's own contract.
                        if not repl.wait_replicated(
                                tname, pid, last + 1,
                                timeout_s=min(max(timeout_ms, 0) / 1000.0,
                                              30.0)):
                            presp.append(
                                (pid, ERR_REQUEST_TIMED_OUT, base))
                            continue
                    presp.append((pid, ERR_NONE, base))
                resp.append((tname, presp))
            if acks == 0:
                # fire-and-forget: the append already ran; the answer
                # carries no delivery information by definition (real
                # Kafka sends NO response at all for acks=0 — this
                # family's strict request/response framing keeps the
                # turn, masked)
                resp = [(tname, [(pid, ERR_NONE, -1)
                                 for pid, _err, _base in presp])
                        for tname, presp in resp]
            w.array(resp, lambda wr, t: (wr.string(t[0]), wr.array(
                t[1], lambda pw, p: pw.i32(p[0]).i16(p[1]).i64(p[2])
                .i64(-1))))
            w.i32(0)  # throttle
        elif api_key == FETCH:
            # replica id >= 0 marks a FOLLOWER's mirror fetch (Kafka's
            # own field, finally load-bearing — ISSUE 14): the leader
            # observes the fetch position into its ISR tracker and
            # serves past the quorum HWM (a follower exists to read the
            # un-replicated tail); consumers (-1) are bounded by it.
            rid = r.i32()
            r.i32()  # max wait
            r.i32()  # min bytes

            def part(rd):
                return (rd.i32(), rd.i64(), rd.i32())

            tops = r.array(lambda rd: (rd.string(), rd.array(part)))
            repl = getattr(broker, "replication", None)
            resp = []
            for tname, parts in tops:
                presp = []
                for pid, offset, max_bytes in parts:
                    if not self._valid_part(broker, tname, pid):
                        presp.append((pid, ERR_UNKNOWN_TOPIC, -1, b""))
                        continue
                    try:
                        if rid >= 0:
                            if repl is not None:
                                repl.observe_fetch(rid, tname, pid,
                                                   offset)
                            # relay brokers have no fetch_tail: they
                            # carry no replication state either, so the
                            # plain fetch is already unbounded there
                            msgs = getattr(broker, "fetch_tail",
                                           broker.fetch)(
                                tname, pid, offset, 4096)
                        else:
                            msgs = broker.fetch(tname, pid, offset, 4096)
                    except NotLeaderForPartitionError:
                        presp.append((pid, ERR_NOT_LEADER_FOR_PARTITION,
                                      -1, b""))
                        continue
                    except OffsetOutOfRangeError as e:
                        # Kafka error 1; the hwm slot carries the
                        # earliest retained offset so the client's
                        # auto-reset needs no second round trip
                        presp.append((pid, ERR_OFFSET_OUT_OF_RANGE,
                                      e.earliest, b""))
                        continue
                    hwm = broker.end_offset(tname, pid)
                    if rid < 0 and repl is not None:
                        # consumers see the QUORUM hwm (their readable
                        # frontier), not the leader log end — consumer
                        # lag measures against what they may read
                        ceil = repl.fetch_ceiling(tname, pid)
                        if ceil is not None:
                            hwm = ceil
                    ms = encode_message_set(
                        [(m.offset, m.key, m.value, m.timestamp_ms)
                         for m in msgs])[:max(max_bytes, 0) or None]
                    presp.append((pid, ERR_NONE, hwm, ms))
                resp.append((tname, presp))
            w.i32(0)  # throttle
            w.array(resp, lambda wr, t: (wr.string(t[0]), wr.array(
                t[1], lambda pw, p: pw.i32(p[0]).i16(p[1]).i64(p[2])
                .bytes_(p[3]))))
        elif api_key == RAW_FETCH:
            # emulator-family extension: one partition, the broker's raw
            # store-frame bytes verbatim — no MessageSet re-encode, no
            # per-record server work (durable brokers serve the
            # segment's own disk bytes)
            tname = r.string()
            pid = r.i32()
            offset = r.i64()
            max_bytes = r.i32()
            # trailing-optional replica id (ISSUE 14): a follower's
            # zero-copy mirror fetch — observed into the ISR, served
            # past the quorum HWM.  Old clients simply end the request
            # here and stay consumers.
            rid = r.i32() if r.pos + 4 <= len(r.buf) else -1
            repl = getattr(broker, "replication", None)
            fetch_raw = getattr(broker, "fetch_raw", None)
            valid = self._valid_part(broker, tname, pid)
            if valid and rid >= 0 and fetch_raw is not None:
                # observe only VALIDATED partitions (a replica with a
                # stale topic view must not seed a garbage part state
                # that poisons the every-partition ISR intersection)
                if repl is not None:
                    repl.observe_fetch(rid, tname, pid, offset)
                fetch_raw = getattr(broker, "fetch_raw_tail", fetch_raw)
            if not valid:
                w.i16(ERR_UNKNOWN_TOPIC).i64(-1).bytes_(None)
            elif fetch_raw is None:  # relay broker without raw reads
                w.i16(ERR_UNSUPPORTED_VERSION)
            else:
                try:
                    raw = fetch_raw(tname, pid, offset,
                                    max_bytes=max(max_bytes, 4096))
                except NotImplementedError:
                    # a RELAY broker (wire client / cluster route) whose
                    # upstream lacks the extension: same downgrade
                    # answer as a pre-extension server, so the client
                    # pins back to classic FETCH instead of dying on a
                    # severed connection
                    w.i16(ERR_UNSUPPORTED_VERSION)
                except NotLeaderForPartitionError:
                    w.i16(ERR_NOT_LEADER_FOR_PARTITION).i64(-1).bytes_(None)
                except OffsetOutOfRangeError as e:
                    w.i16(ERR_OFFSET_OUT_OF_RANGE).i64(e.earliest)
                    w.bytes_(None)
                else:
                    # cheap for local (in-memory/durable) brokers; a
                    # RELAY broker (wire client backing this server)
                    # must not pay an upstream round trip per fetch —
                    # its own fetch_raw just cached the upstream's
                    # trailing hwm, so answer from that cache (-1 =
                    # genuinely absent)
                    if hasattr(broker, "_request"):
                        lh = getattr(broker, "last_hwm", None)
                        hwm = lh(tname, pid) if lh is not None else None
                        hwm = -1 if hwm is None else hwm
                    elif rid < 0 and repl is not None and \
                            repl.fetch_ceiling(tname, pid) is not None:
                        # consumers' columnar lag measures against the
                        # quorum hwm — their readable frontier
                        hwm = repl.fetch_ceiling(tname, pid)
                    else:
                        hwm = broker.end_offset(tname, pid)
                    if raw is None:
                        w.i16(ERR_NONE).i64(offset).bytes_(b"")
                    else:
                        if _tracing.ENABLED:
                            # broker-process hop of a wire-carried batch
                            # trace: one first-frame parse per raw fetch
                            # (batch-granular), so the trace CLI sees
                            # the shard the batch crossed
                            self._mark_raw_batch(raw.data,
                                                 "wire_raw_fetch",
                                                 tname, pid,
                                                 at_or_after=offset)
                        w.i16(ERR_NONE).i64(raw.start_offset)
                        w.bytes_(raw.data)
                    # trailing-optional hwm: consumer lag for the
                    # columnar path at zero extra round trips (older
                    # clients never read past the blob)
                    w.i64(hwm)
        elif api_key == RAW_PRODUCE:
            # write-path mirror of RAW_FETCH: a pre-framed batch the
            # broker appends segment-verbatim (CRCs validated WHOLE,
            # offsets stamped into the frame heads server-side).  A
            # corrupt batch answers CORRUPT_MESSAGE with nothing
            # appended — no torn/partial appends ever reach a segment.
            tname = r.string()
            pid = r.i32()
            frames = r.bytes_() or b""
            # trailing-optional required_acks + timeout (ISSUE 14): the
            # RAW_PRODUCE mirror of classic produce's field.  Absent
            # (old clients) means -1, the classic client default.
            acks = r.i16() if r.pos + 2 <= len(r.buf) else -1
            timeout_ms = r.i32() if r.pos + 4 <= len(r.buf) else 10_000
            repl = getattr(broker, "replication", None)
            quorum = acks == -1 and repl is not None
            produce_raw = getattr(broker, "produce_raw", None)
            if self.server.retiring:       # type: ignore[attr-defined]
                # reassignment step-down, same answer as classic
                w.i16(ERR_NOT_LEADER_FOR_PARTITION).i64(-1).i32(0)
            elif self._epoch_mismatch(client_epoch):
                # fence BEFORE touching the broker, like classic produce
                w.i16(ERR_FENCED_LEADER_EPOCH).i64(-1).i32(0)
            elif produce_raw is None:
                # relay broker without raw appends: same downgrade as a
                # pre-extension server — clients pin back to classic
                w.i16(ERR_UNSUPPORTED_VERSION)
            elif acks not in (-1, 0, 1):
                w.i16(ERR_INVALID_REQUIRED_ACKS).i64(-1).i32(0)
            else:
                if tname not in broker.topics() and cluster is None:
                    broker.create_topic(tname, partitions=max(pid + 1, 1))
                if not self._valid_part(broker, tname, pid):
                    w.i16(ERR_UNKNOWN_TOPIC).i64(-1).i32(0)
                elif quorum and (not repl.covers(tname) or
                                 repl.isr_size(tname, pid) <
                                 repl.min_isr):
                    # same pre-append refusal as classic acks=all:
                    # nothing lands, redelivery is safe
                    w.i16(ERR_NOT_ENOUGH_REPLICAS).i64(-1).i32(0)
                else:
                    if _tracing.ENABLED:
                        self._mark_raw_batch(frames, "wire_raw_produce",
                                             tname, pid)
                    nframes = None
                    if quorum:
                        # the quorum wait must target THIS batch's own
                        # last offset, not an end_offset re-read that
                        # may include a concurrent producer's later
                        # batch (the same race fixed on classic
                        # produce): count the frames before the append
                        # — one validation walk, quorum path only; a
                        # corrupt batch falls through to produce_raw's
                        # own whole-batch rejection
                        from ..ops import framing as _fr

                        try:
                            nframes = _fr.validate_frame_batch(
                                frames)["count"]
                        except _fr.CorruptFrameError:
                            nframes = None
                    try:
                        base = produce_raw(tname, pid, frames)
                    except NotImplementedError:
                        w.i16(ERR_UNSUPPORTED_VERSION)
                    except CorruptMessageError as e:
                        w.i16(ERR_CORRUPT_MESSAGE).i64(e.index).i32(0)
                    except NotLeaderForPartitionError:
                        w.i16(ERR_NOT_LEADER_FOR_PARTITION).i64(-1).i32(0)
                    except PermissionError:
                        # engine-owned topic without the owner's grant
                        w.i16(ERR_TOPIC_AUTHORIZATION_FAILED).i64(-1)
                        w.i32(0)
                    else:
                        count = nframes if nframes is not None else \
                            broker.end_offset(tname, pid) - base
                        if quorum and count and not repl.wait_replicated(
                                tname, pid, base + count,
                                timeout_s=min(max(timeout_ms, 0)
                                              / 1000.0, 30.0)):
                            # appended-unacked: the producer redelivers
                            w.i16(ERR_REQUEST_TIMED_OUT).i64(base)
                            w.i32(count)
                        else:
                            w.i16(ERR_NONE).i64(base)
                            w.i32(count)
        elif api_key == LIST_OFFSETS:
            r.i32()  # replica

            def part(rd):
                return (rd.i32(), rd.i64())

            tops = r.array(lambda rd: (rd.string(), rd.array(part)))
            resp = []
            for tname, parts in tops:
                presp = []
                for pid, ts in parts:
                    try:
                        if not self._valid_part(broker, tname, pid):
                            presp.append((pid, ERR_UNKNOWN_TOPIC, -1, -1))
                        elif ts == -2:
                            presp.append((pid, ERR_NONE, -1,
                                          broker.begin_offset(tname, pid)))
                        elif ts >= 0:
                            # ListOffsets by timestamp: the replay cursor
                            # (earliest offset with record ts >= requested)
                            presp.append((pid, ERR_NONE, -1,
                                          broker.offset_for_timestamp(
                                              tname, pid, ts)))
                        else:
                            presp.append((pid, ERR_NONE, -1,
                                          broker.end_offset(tname, pid)))
                    except NotLeaderForPartitionError:
                        presp.append((pid, ERR_NOT_LEADER_FOR_PARTITION,
                                      -1, -1))
                resp.append((tname, presp))
            w.array(resp, lambda wr, t: (wr.string(t[0]), wr.array(
                t[1], lambda pw, p: pw.i32(p[0]).i16(p[1]).i64(p[2])
                .i64(p[3]))))
        elif api_key == OFFSET_COMMIT:
            group = r.string()
            generation = r.i32()
            member = r.string()
            r.i64()  # retention

            def part(rd):
                return (rd.i32(), rd.i64(), rd.string())

            tops = r.array(lambda rd: (rd.string(), rd.array(part)))
            if self._not_coordinator():
                # cluster group/offset state is pinned to ONE broker:
                # a commit accepted here would fork the offset table
                resp = [(tname, [(pid, ERR_NOT_COORDINATOR)
                                 for pid, _, _ in parts])
                        for tname, parts in tops]
            elif self._epoch_mismatch(client_epoch):
                # stale-epoch commit: writing it would let a zombie
                # fence-bypass the promoted log's offset streams
                resp = [(tname, [(pid, ERR_FENCED_LEADER_EPOCH)
                                 for pid, _, _ in parts])
                        for tname, parts in tops]
            # generation == -1: simple consumer, no fencing (the classic
            # path).  A real generation routes through the group coordinator
            # so a member fenced by a rebalance cannot clobber offsets.
            elif generation >= 0:
                coord = self.server.group_coordinator(group)
                positions = [(t, pid, off)
                             for t, parts in tops for pid, off, _ in parts]
                done = coord.fenced_commit_detailed(member, generation,
                                                    positions)
                if done is None:  # fenced: nothing written
                    resp = [(t, [(pid, ERR_ILLEGAL_GENERATION)
                                 for pid, _, _ in parts])
                            for t, parts in tops]
                else:  # per-partition: unowned partitions error out loudly
                    resp = [(t, [(pid, ERR_NONE if (t, pid) in done
                                  else ERR_ILLEGAL_GENERATION)
                                 for pid, _, _ in parts])
                            for t, parts in tops]
            else:
                for tname, parts in tops:
                    # one batched commit per topic: a durable broker
                    # fsyncs its offsets file ONCE per request, not once
                    # per partition (the client batched for a reason)
                    broker.commit_many(group, tname,
                                       [(pid, off) for pid, off, _ in parts])
                resp = [(tname, [(pid, ERR_NONE) for pid, _, _ in parts])
                        for tname, parts in tops]
            w.array(resp, lambda wr, t: (wr.string(t[0]), wr.array(
                t[1], lambda pw, p: pw.i32(p[0]).i16(p[1]))))
        elif api_key == OFFSET_FETCH:
            group = r.string()
            tops = r.array(lambda rd: (rd.string(),
                                       rd.array(lambda p: p.i32())))
            err = ERR_NOT_COORDINATOR if self._not_coordinator() \
                else ERR_NONE
            resp = []
            for tname, parts in tops:
                presp = []
                for pid in parts:
                    off = None if err else broker.committed(group, tname,
                                                            pid)
                    presp.append((pid, -1 if off is None else off))
                resp.append((tname, presp))
            w.array(resp, lambda wr, t: (wr.string(t[0]), wr.array(
                t[1], lambda pw, p: pw.i32(p[0]).i64(p[1]).string(None)
                .i16(err))))
        elif api_key == FIND_COORDINATOR:
            r.string()  # group id — ONE coordinator per cluster (pinned)
            if cluster is not None:
                node, host, port = cluster.coordinator()
                w.i16(ERR_NONE).i32(node).string(host).i32(port)
            else:
                # advertise the address the client actually connected to,
                # not the bind address (0.0.0.0 would be unconnectable)
                host = self.request.getsockname()[0]
                w.i16(ERR_NONE).i32(0).string(host).i32(self.server.port)
        elif api_key == JOIN_GROUP and self._not_coordinator():
            w.i16(ERR_NOT_COORDINATOR).i32(-1).string("").string("")
            w.string("")
            w.array([], lambda wr, x: None)
        elif api_key == SYNC_GROUP and self._not_coordinator():
            r.string()
            w.i16(ERR_NOT_COORDINATOR).bytes_(b"")
        elif api_key in (HEARTBEAT, LEAVE_GROUP) and \
                self._not_coordinator():
            w.i16(ERR_NOT_COORDINATOR)
        elif api_key == JOIN_GROUP:
            group = r.string()
            session_timeout_ms = r.i32()
            member = r.string()
            r.string()  # protocol type ("consumer")
            protocols = r.array(lambda rd: (rd.string(), rd.bytes_()))
            # subscription topics from the standard consumer protocol
            # metadata: version i16, topics array<str>, userdata bytes
            topics = []
            if protocols:
                meta = _Reader(protocols[0][1] or b"")
                try:
                    meta.i16()
                    topics = meta.array(lambda rd: rd.string())
                except struct.error:
                    topics = []
            coord = self.server.group_coordinator(
                group, session_timeout_ms / 1000.0)
            mid, gen, _assigned = coord.join(topics, member or None)
            members = coord.members()
            leader = members[0] if members else mid
            # echo a protocol the client actually offered (a client errors
            # out if told a protocol it never proposed); assignment itself
            # is computed server-side regardless (see class docstring)
            proto = protocols[0][0] if protocols else "range"
            w.i16(ERR_NONE).i32(gen).string(proto).string(leader).string(mid)
            # standard flow: the elected leader receives every member's
            # subscription metadata so it can compute the assignment
            # client-side (our SyncGroup computes server-side regardless,
            # and ignores what the leader submits — same answer)
            rows = []
            if mid == leader:
                for other_id, subs in sorted(coord.subscriptions().items()):
                    mw = _Writer()
                    mw.i16(0)
                    mw.array(list(subs), lambda wr2, t: wr2.string(t))
                    mw.bytes_(b"")
                    rows.append((other_id, bytes(mw.buf)))
            w.array(rows, lambda wr, x: (wr.string(x[0]), wr.bytes_(x[1])))
        elif api_key == SYNC_GROUP:
            group = r.string()
            generation = r.i32()
            member = r.string()
            r.array(lambda rd: (rd.string(), rd.bytes_()))  # leader's (unused)
            coord = self.server.group_coordinator(group)
            # one atomic coordinator call: check + assignment under one lock
            verdict, assigned = coord.sync(member, generation)
            if verdict == "unknown_member":
                w.i16(ERR_UNKNOWN_MEMBER_ID).bytes_(b"")
            elif verdict == "illegal_generation":
                w.i16(ERR_ILLEGAL_GENERATION).bytes_(b"")
            else:
                by_topic: dict = {}
                for t, p in assigned:
                    by_topic.setdefault(t, []).append(p)
                aw = _Writer()
                aw.i16(0)  # ConsumerProtocolAssignment version
                aw.array(sorted(by_topic.items()), lambda wr, tp: (
                    wr.string(tp[0]),
                    wr.array(sorted(tp[1]), lambda pw, p: pw.i32(p))))
                aw.bytes_(b"")  # userdata
                w.i16(ERR_NONE).bytes_(bytes(aw.buf))
        elif api_key == HEARTBEAT:
            group = r.string()
            generation = r.i32()
            member = r.string()
            coord = self.server.group_coordinator(group)
            verdict = coord.heartbeat_verdict(member, generation)
            w.i16({"ok": ERR_NONE,
                   "unknown_member": ERR_UNKNOWN_MEMBER_ID,
                   "rebalance_in_progress": ERR_REBALANCE_IN_PROGRESS}
                  [verdict])
        elif api_key == LEAVE_GROUP:
            group = r.string()
            member = r.string()
            self.server.group_coordinator(group).leave(member)
            w.i16(ERR_NONE)
        elif api_key == CLUSTER_ADMIN:
            # elastic reassignment verbs (ISSUE 14): served only when a
            # controller is attached (`server.admin`); the verbs run IN
            # this handler thread — the CLI waits for the reassignment
            # report, other connections keep serving (threading server)
            import json as _json

            command = r.string()
            blob = r.bytes_() or b"{}"
            admin = getattr(self.server, "admin", None)
            if admin is None:
                w.i16(ERR_UNSUPPORTED_VERSION)
            else:
                try:
                    doc = admin.admin_command(
                        command or "",
                        _json.loads(blob.decode() or "{}"))
                    w.i16(ERR_NONE)
                    w.bytes_(_json.dumps(doc, default=str).encode())
                except Exception as e:  # noqa: BLE001 - the operator
                    # gets the error text, the connection stays up
                    w.i16(ERR_UNKNOWN_SERVER)
                    w.bytes_(_json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())
        elif api_key == CREATE_TOPICS:
            def topic(rd):
                name = rd.string()
                parts = rd.i32()
                rd.i16()  # replication factor
                rd.array(lambda x: (x.i32(), x.array(lambda y: y.i32())))
                cfgs = rd.array(lambda x: (x.string(), x.string()))
                return (name, parts, cfgs)

            tops = r.array(topic)
            r.i32()  # timeout
            resp = []
            for name, parts, cfgs in tops:
                if name in broker.topics():
                    resp.append((name, ERR_TOPIC_EXISTS))
                else:
                    # retention configs carried the standard way (the
                    # names Kafka itself uses); unknown keys are ignored
                    # like a permissive broker's defaults path
                    try:
                        ret = {}
                        for k, v in cfgs:
                            if k == "cleanup.policy" and v is not None:
                                # create_topic validates the value
                                # (ValueError → INVALID_CONFIG below)
                                ret["cleanup_policy"] = v
                                continue
                            field = {"retention.messages":
                                     "retention_messages",
                                     "retention.bytes": "retention_bytes",
                                     "retention.ms": "retention_ms"}.get(k)
                            if field is None or v is None:
                                continue
                            value = int(v)  # non-integer → INVALID_CONFIG
                            if value == -1:
                                # Kafka's documented 'unlimited' sentinel
                                # for retention.*: explicit unlimited (0),
                                # which on a durable broker OVERRIDES the
                                # store-wide default (None would inherit)
                                value = 0
                            ret[field] = value
                        broker.create_topic(name, partitions=max(parts, 1),
                                            **ret)
                    except ValueError:
                        # unparseable or negative retention: answer
                        # INVALID_CONFIG instead of killing the connection
                        resp.append((name, ERR_INVALID_CONFIG))
                        continue
                    resp.append((name, ERR_NONE))
            w.array(resp, lambda wr, t: wr.string(t[0]).i16(t[1]))


class KafkaWireServer(socketserver.ThreadingTCPServer):
    """TCP Kafka-protocol front for the in-process Broker.

    `with KafkaWireServer(broker) as s:` serves on an ephemeral localhost
    port (`s.port`).  Pass `credentials=(user, password)` to require the
    SASL/PLAIN exchange the reference's cluster config mandates
    (gcp.yaml:29-32).
    """

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, broker: Broker, host: str = "127.0.0.1",
                 port: int = 0,
                 credentials: Optional[Tuple[str, str]] = None,
                 epoch: int = 0, cluster=None):
        super().__init__((host, port), _KafkaConn)
        self.broker = broker
        self.credentials = credentials
        self.port = self.server_address[1]
        #: cluster view (iotml.cluster duck-type: node_id, brokers(),
        #: leader_node(topic, partition), coordinator()) — None for the
        #: classic single-broker server.  With a view, Metadata carries
        #: per-partition leaders, unowned partitions answer
        #: NOT_LEADER_FOR_PARTITION, and group/offset APIs are pinned to
        #: the view's coordinator node.
        self.cluster = cluster
        #: cluster admin hook (iotml.cluster.ClusterController duck-
        #: type: admin_command(command, args) -> dict) — None answers
        #: CLUSTER_ADMIN with UNSUPPORTED_VERSION.
        self.admin = None
        #: reassignment step-down (ISSUE 14): True once leadership has
        #: moved off this server but its sockets are still draining —
        #: every write answers NOT_LEADER_FOR_PARTITION (truthful: it
        #: no longer leads) so even UNSTAMPED legacy producers re-route
        #: instead of split-writing into a retired log; reads keep
        #: serving through the grace window.
        self.retiring = False
        #: leadership fencing epoch this server believes it serves at.
        #: Promotion bumps it (FollowerReplica.promote); a restarted old
        #: leader comes back with its stale value and fences itself
        #: against epoch-stamped produce/commit traffic.
        self.epoch = int(epoch)
        self._thread: Optional[threading.Thread] = None
        self._coordinators: dict = {}
        self._coord_lock = threading.Lock()
        self._live_conns: set = set()
        self._conn_lock = threading.Lock()

    def set_epoch(self, epoch: int) -> None:
        if epoch < self.epoch:
            raise ValueError(f"epoch must be monotonic: have {self.epoch}, "
                             f"got {epoch}")
        self.epoch = int(epoch)

    def group_coordinator(self, group_id: str,
                          session_timeout_s: Optional[float] = None):
        """Broker-side GroupCoordinator for a group (created on first use).
        The session timeout is fixed by the first member that names one."""
        from .group import GroupCoordinator

        with self._coord_lock:
            coord = self._coordinators.get(group_id)
            if coord is None:
                coord = GroupCoordinator(
                    self.broker, group_id,
                    session_timeout_s=session_timeout_s or 10.0)
                self._coordinators[group_id] = coord
            return coord

    def start(self) -> "KafkaWireServer":
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self.serve_forever, daemon=True,
            name=f"iotml-kafka-wire-{self.port}"))
        self._thread.start()
        return self

    def __enter__(self) -> "KafkaWireServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()
        self.server_close()

    def kill(self) -> None:
        """Simulate abrupt broker death (failover tests / drills):
        `shutdown()` alone only stops the accept loop — established
        handler threads keep serving their sockets, which a dead process
        would not.  This severs every live client connection too, so
        clients observe exactly what a crashed leader looks like."""
        self.shutdown()
        with self._conn_lock:
            conns = list(self._live_conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self.server_close()
