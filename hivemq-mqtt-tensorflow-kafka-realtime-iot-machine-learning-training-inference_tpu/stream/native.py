"""ctypes bindings for the C++ stream engine (cpp/avro_engine.cc).

The engine is the perf twin of `ops.avro.AvroCodec`: one call decodes a
whole poll's worth of Confluent-framed Avro messages into columnar numpy
buffers (and encodes the other way).  Python stays the source of truth for
correctness (the pure codec is the test oracle; `tests/test_native.py`
cross-checks byte-for-byte); the engine is used automatically by the data
path when the shared library is present.

Build lazily on first use (`make -C iotml/cpp`, no external deps, <1s) and
fall back silently to the pure-Python codec when no toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

from ..core.schema import RecordSchema

_TYPE_CODE = {"float": 0, "double": 1, "int": 2, "long": 3, "string": 4,
              "boolean": 5}
LABEL_STRIDE = 16  # fits "true"/"false"/"" labels with headroom

_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "cpp")
_SO_PATH = os.path.join(_CPP_DIR, "build", "libiotml_stream.so")

_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _CPP_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


ENGINE_VERSION = 9  # must match iotml_engine_version() in avro_engine.cc


def _stale() -> bool:
    """A prebuilt .so from an older checkout must be rebuilt: `make` only
    triggers on mtime, so also compare against source files explicitly."""
    try:
        so_m = os.path.getmtime(_SO_PATH)
        for name in os.listdir(_CPP_DIR):
            if name.endswith((".cc", ".h")) or name == "Makefile":
                if os.path.getmtime(os.path.join(_CPP_DIR, name)) > so_m:
                    return True
    except OSError:
        return True
    return False


def load() -> Optional[ctypes.CDLL]:
    """The engine library, building it on first call; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if (not os.path.exists(_SO_PATH) or _stale()) and not _build() \
            and not os.path.exists(_SO_PATH):
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
        # version gate FIRST: touching a symbol a stale engine lacks would
        # raise AttributeError before the check meant to reject it
        lib.iotml_engine_version.restype = ctypes.c_int64
        if lib.iotml_engine_version() < ENGINE_VERSION:
            # stale binary and the rebuild failed (or produced an old ABI):
            # treat as unavailable rather than risk missing symbols
            _lib = None
            return None
        lib.iotml_decode_batch.restype = ctypes.c_int64
        lib.iotml_decode_batch_nulls.restype = ctypes.c_int64
        lib.iotml_decode_batch_strict.restype = ctypes.c_int64
        lib.iotml_encode_batch.restype = ctypes.c_int64
        lib.iotml_json_decode_batch.restype = ctypes.c_int64
        lib.iotml_encode_batch_nulls.restype = ctypes.c_int64
        lib.iotml_format_rows_f32.restype = ctypes.c_int64
        lib.iotml_format_rows_f64.restype = ctypes.c_int64
        lib.iotml_frames_decode_columnar.restype = ctypes.c_int64
        # watermark-carrying decode (ABI 9): same walk, event-time
        # min/max out-params — the columnar plane's zero-cost watermark
        lib.iotml_frames_decode_columnar_ts.restype = ctypes.c_int64
        # write-path frame codec (ABI 8, frame_engine.cc)
        lib.iotml_frames_encode_columnar.restype = ctypes.c_int64
        lib.iotml_frames_encode_values.restype = ctypes.c_int64
        lib.iotml_frames_restamp.restype = ctypes.c_int64
        lib.iotml_frames_validate.restype = ctypes.c_int64
        _lib = lib
    except (OSError, AttributeError):
        _lib = None
    return _lib


def available() -> bool:
    return load() is not None


class NativeCodec:
    """Schema-compiled batch codec over the C++ engine."""

    def __init__(self, schema: RecordSchema):
        self.schema = schema
        self.types = np.array([_TYPE_CODE[f.avro_type] for f in schema.fields],
                              np.int8)
        self.nullable = np.array([1 if f.nullable else 0 for f in schema.fields],
                                 np.uint8)
        self.n_fields = len(schema.fields)
        self.n_strings = int((self.types == 4).sum())
        self.n_numeric = self.n_fields - self.n_strings
        # schema-constant inputs for the JSON batch parser: uppercase
        # column names (built once, not per poll batch on the hot path)
        names = [f.name.upper().encode() for f in schema.fields]
        self._json_names_blob = b"".join(names)
        self._json_name_offsets = np.zeros((len(names) + 1,), np.int64)
        np.cumsum([len(b) for b in names], out=self._json_name_offsets[1:])
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native stream engine unavailable")

    # ------------------------------------------------------------- decode
    def _decode_impl(self, messages: List[bytes], strip: int,
                     stride: int, want_nulls: bool, strict: bool = False):
        n = len(messages)
        if n == 0:
            empty = (np.zeros((0, self.n_numeric)),
                     np.zeros((0, self.n_strings), f"S{stride}"))
            return empty + ((np.zeros((0, self.n_fields), np.uint8),)
                            if want_nulls else ())
        blob = b"".join(messages)
        offsets = np.zeros((n + 1,), np.int64)
        np.cumsum([len(m) for m in messages], out=offsets[1:])
        numeric = np.empty((n, self.n_numeric), np.float64)
        labels = np.zeros((n, max(self.n_strings, 1)), f"S{stride}")
        args = [
            ctypes.c_char_p(blob),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(n),
            self.types.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            self.nullable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(self.n_fields),
            ctypes.c_int64(strip),
            numeric.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            labels.ctypes.data_as(ctypes.c_char_p),
            ctypes.c_int64(stride),
        ]
        if want_nulls:
            nulls = np.zeros((n, self.n_fields), np.uint8)
            args.append(nulls.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            rc = self._lib.iotml_decode_batch_nulls(*args)
        elif strict:
            rc = self._lib.iotml_decode_batch_strict(*args)
        else:
            rc = self._lib.iotml_decode_batch(*args)
        if rc != n:
            raise ValueError(f"malformed Avro message at row {-rc - 1}")
        out = (numeric, labels[:, : self.n_strings])
        return out + ((nulls,) if want_nulls else ())

    def decode_batch(self, messages: List[bytes], strip: int = 0,
                     stride: int = LABEL_STRIDE, strict: bool = False
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """→ (numeric [n, n_numeric] float64, labels [n, n_strings]).

        Numeric columns are the schema's non-string fields in order — for
        the car schemas that is exactly the 18-sensor matrix.

        strict=True is the pass-through validation mode: it additionally
        rejects (ValueError) records the Python codec would reject
        (invalid UTF-8 strings, union branch outside {0,1}) or would
        canonicalize on re-encode (trailing bytes, non-minimal varints) —
        i.e. success guarantees forwarding the ORIGINAL bytes equals
        decode→re-encode, the fast-path parity contract."""
        return self._decode_impl(messages, strip, stride, want_nulls=False,
                                 strict=strict)

    def decode_batch_nulls(self, messages: List[bytes], strip: int = 0,
                           stride: int = LABEL_STRIDE):
        """decode_batch + per-field null bitmap [n, n_fields] (uint8).

        The columnar outputs cannot represent a null union distinctly
        (numeric null → 0.0, string null → ""); exact-semantics callers
        check the bitmap and fall back when any null is present.  The
        ENGINE_VERSION gate in load() guarantees the symbol exists."""
        return self._decode_impl(messages, strip, stride, want_nulls=True)

    # --------------------------------------------------------------- json
    def json_decode_batch(self, messages: List[bytes],
                          stride: int = LABEL_STRIDE):
        """Batch-parse flat JSON objects into the same columnar layout as
        decode_batch: → (numeric [n, n_numeric] float64, labels
        [n, n_strings] S-stride, nulls [n, n_fields] uint8, fallback [n]
        uint8).

        Missing columns and explicit JSON nulls on nullable columns set
        the null bitmap (the fleet's producer-named payloads make the
        KSQL-mangled columns permanently null — the hot case).  Rows the
        native parser cannot reproduce exactly (escapes, nested values,
        type mismatches, ints beyond 2^53, null on a non-nullable column)
        are flagged in `fallback` with undefined contents — the caller
        re-decodes those through json.loads.  Keys match schema column
        names case-insensitively (ASCII upper), like the Python leg's
        `{k.upper(): v}`."""
        n = len(messages)
        if n == 0:
            return (np.zeros((0, self.n_numeric)),
                    np.zeros((0, self.n_strings), f"S{stride}"),
                    np.zeros((0, self.n_fields), np.uint8),
                    np.zeros((0,), np.uint8))
        blob = b"".join(messages)
        offsets = np.zeros((n + 1,), np.int64)
        np.cumsum([len(m) for m in messages], out=offsets[1:])
        names_blob = self._json_names_blob
        name_offsets = self._json_name_offsets
        numeric = np.empty((n, self.n_numeric), np.float64)
        labels = np.zeros((n, max(self.n_strings, 1)), f"S{stride}")
        nulls = np.zeros((n, self.n_fields), np.uint8)
        fallback = np.zeros((n,), np.uint8)
        rc = self._lib.iotml_json_decode_batch(
            ctypes.c_char_p(blob),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(n),
            ctypes.c_char_p(names_blob),
            name_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self.types.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            self.nullable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(self.n_fields),
            numeric.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.c_int64(self.n_numeric),
            labels.ctypes.data_as(ctypes.c_char_p),
            ctypes.c_int64(self.n_strings),
            ctypes.c_int64(stride),
            nulls.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            fallback.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if rc < 0:
            raise ValueError("json batch decode rejected arguments")
        return numeric, labels[:, : self.n_strings], nulls, fallback

    # ------------------------------------------------------------- frames
    def frame_decoder(self, pinned_id_limit: Optional[int] = None
                      ) -> "FrameDecoder":
        """The store-frame columnar decoder compiled for this schema —
        the zero-copy pipeline's single decode entry point."""
        return FrameDecoder(self, pinned_id_limit=pinned_id_limit)

    # ------------------------------------------------------------- encode
    def encode_batch(self, numeric: np.ndarray, labels: Optional[np.ndarray],
                     schema_id: int = -1, stride: int = LABEL_STRIDE,
                     nulls: Optional[np.ndarray] = None) -> List[bytes]:
        """Columnar rows → list of (optionally framed) Avro messages.

        `nulls` ([n, n_fields] uint8) encodes branch 0 of the nullable
        union where set — the column slot's value is ignored for those
        fields.  A null flagged on a non-nullable field raises (no valid
        encoding exists)."""
        numeric = np.ascontiguousarray(numeric, np.float64)
        n = numeric.shape[0]
        if labels is None:
            labels = np.zeros((n, self.n_strings), f"S{stride}")
        labels = np.ascontiguousarray(labels.astype(f"S{stride}"))
        cap = n * (5 + self.n_fields * 20 + self.n_strings * stride) + 64
        out = np.empty((cap,), np.uint8)
        offsets = np.zeros((n + 1,), np.int64)
        args = [
            numeric.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            labels.ctypes.data_as(ctypes.c_char_p),
            ctypes.c_int64(stride),
            ctypes.c_int64(n),
            self.types.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            self.nullable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(self.n_fields),
            ctypes.c_int64(schema_id),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(cap),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ]
        if nulls is not None:
            nulls = np.ascontiguousarray(nulls, np.uint8)
            args.append(nulls.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            total = self._lib.iotml_encode_batch_nulls(*args)
        else:
            total = self._lib.iotml_encode_batch(*args)
        if total < 0:
            raise ValueError("encode rejected (overflow or impossible null)")
        raw = out.tobytes()
        return [raw[offsets[i]:offsets[i + 1]] for i in range(n)]

    def encode_frames(self, numeric: np.ndarray,
                      labels: Optional[np.ndarray],
                      timestamps: Optional[np.ndarray] = None,
                      keys=None, schema_id: int = 1,
                      nulls: Optional[np.ndarray] = None,
                      base_offset: int = 0,
                      stride: int = LABEL_STRIDE) -> bytes:
        """Columnar rows → ONE contiguous ready-to-append raw frame
        batch: Confluent-framed Avro values wrapped in the store's
        CRC32C frame, offsets stamped ``base_offset + i`` — the fused
        produce leg (a record is framed ONCE at conversion and never
        re-serialised; `Broker.produce_raw` appends these bytes
        segment-verbatim after restamping).  Byte parity with the
        python codec + store frame oracle is pinned by tests.

        `keys`: optional list of per-row key bytes (None entries = null
        key), or an ``S``-dtype array (all non-null) — the S-array form
        is passed as ONE fixed-stride block, zero per-record objects."""
        numeric = np.ascontiguousarray(numeric, np.float64)
        n = numeric.shape[0]
        if labels is None:
            labels = np.zeros((n, self.n_strings), f"S{stride}")
        labels = np.ascontiguousarray(labels.astype(f"S{stride}"))
        ts = np.zeros((n,), np.int64) if timestamps is None else \
            np.ascontiguousarray(timestamps, np.int64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        kargs = (None, None, ctypes.c_int64(0), None)
        key_bytes = 0
        if isinstance(keys, np.ndarray):
            keys = np.ascontiguousarray(keys)
            kargs = (keys.ctypes.data_as(u8p), None,
                     ctypes.c_int64(keys.dtype.itemsize), None)
            key_bytes = keys.nbytes
        elif keys is not None:
            kblob = b"".join(k or b"" for k in keys)
            koff = np.zeros((n + 1,), np.int64)
            np.cumsum([len(k or b"") for k in keys], out=koff[1:])
            knull = np.asarray([1 if k is None else 0 for k in keys],
                               np.uint8)
            kargs = (ctypes.c_char_p(kblob), koff.ctypes.data_as(i64p),
                     ctypes.c_int64(0), knull.ctypes.data_as(u8p))
            key_bytes = len(kblob)
        # worst case per row: frame head + value (5 + 20/field + strings)
        cap = n * (64 + 5 + self.n_fields * 20
                   + self.n_strings * stride) + key_bytes + 64
        out = ctypes.create_string_buffer(cap)
        nargs = None if nulls is None else np.ascontiguousarray(
            nulls, np.uint8).ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        rc = self._lib.iotml_frames_encode_columnar(
            numeric.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            labels.ctypes.data_as(ctypes.c_char_p),
            ctypes.c_int64(stride), ctypes.c_int64(n),
            self.types.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            self.nullable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(self.n_fields), ctypes.c_int64(schema_id),
            nargs, *kargs,
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(int(base_offset)),
            ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(cap))
        if rc < 0:
            raise ValueError(
                "frame encode rejected (overflow or impossible null)")
        return out.raw[:rc]


#: flag bits reported by the frame decoder (frame_engine.cc FrameFlags)
FRAMES_STOP_TORN = 1     # torn/corrupt frame parked the scan (recovery)
FRAMES_STOP_SCHEMA = 2   # Confluent writer id != the pinned reader id

#: default bytes per row for message keys in columnar decode (matches
#: NativeKafkaBroker.KEY_STRIDE: MQTT-topic car keys fit with room)
KEY_STRIDE = 64


class FrameDecoder:
    """Columnar decoder over raw store-frame batches (frame_engine.cc).

    ONE decode entry point for the zero-copy data plane: live consume
    (`StreamConsumer.poll_into`) and timestamp-replay backfill both land
    here, over the same `[len|crc|attrs|offset|ts|key|value|headers]`
    frame bytes the segmented log persists and the wire's RAW_FETCH
    ships — so the two paths cannot drift.  Decodes into CALLER-OWNED
    preallocated float32/label/key buffers (`data.pipeline.DecodeRing`
    slots): zero per-record Python objects, zero per-chunk buffer churn.

    `pinned_id_limit` is the exclusive upper bound on positionally-safe
    Confluent writer ids (default: `stream.registry.RESERVED_ID_BASE`,
    the band where evolved writer schemas live): an evolved writer's
    frame — or a non-Confluent payload — stops the scan with
    `FRAMES_STOP_SCHEMA` and the caller resolves that chunk by name in
    Python instead of mis-reading it positionally.
    """

    def __init__(self, codec: NativeCodec,
                 pinned_id_limit: Optional[int] = None):
        from .registry import RESERVED_ID_BASE

        self.codec = codec
        self.pinned_id_limit = RESERVED_ID_BASE \
            if pinned_id_limit is None else int(pinned_id_limit)
        self._lib = codec._lib
        #: event-time bounds (ms) of the frames CONSUMED by the last
        #: decode_into call — decoded rows and skipped tombstones alike;
        #: -1 when that call consumed nothing.  The batch-granular
        #: watermark source (ISSUE 13): the frame head already carries
        #: every record's timestamp, so min/max costs nothing extra.
        self.last_ts_min = -1
        self.last_ts_max = -1

    @property
    def n_numeric(self) -> int:
        return self.codec.n_numeric

    @property
    def n_strings(self) -> int:
        return self.codec.n_strings

    def decode_into(self, buf, start_offset: int, out_numeric: np.ndarray,
                    out_labels: np.ndarray,
                    out_keys: Optional[np.ndarray] = None,
                    cap_rows: Optional[int] = None
                    ) -> Tuple[int, int, int, int]:
        """Decode raw frame bytes into the caller's column buffers.

        Args:
          buf: contiguous frame bytes (bytes/memoryview/bytearray) — a
            segment byte range, a RAW_FETCH payload, or the emulator's
            re-framed batch; may start below `start_offset` (skipped)
            and end mid-frame (ends the batch).
          start_offset: frames below this log offset are skipped.
          out_numeric: [cap, n_numeric] float32 C-contiguous.
          out_labels: [cap, n_strings] S-stride C-contiguous.
          out_keys: optional [cap] S-stride (message keys, truncated at
            stride-1 like the fused native path).
        Returns (rows, next_offset, flags, skipped_tombstones).
        """
        codec = self.codec
        cap = out_numeric.shape[0] if cap_rows is None \
            else min(int(cap_rows), out_numeric.shape[0])
        if out_labels.shape[0] < cap or \
                (out_keys is not None and out_keys.shape[0] < cap):
            raise ValueError("label/key buffers shorter than cap_rows")
        if isinstance(buf, (bytearray, memoryview)):
            buf = bytes(buf)  # borderline callers; the hot paths hand bytes
        c_buf = ctypes.cast(ctypes.c_char_p(buf),
                            ctypes.POINTER(ctypes.c_uint8))  # zero-copy
        next_off = ctypes.c_int64(start_offset)
        flags = ctypes.c_int64(0)
        skipped = ctypes.c_int64(0)
        ts_min = ctypes.c_int64(-1)
        ts_max = ctypes.c_int64(-1)
        label_stride = out_labels.dtype.itemsize
        rows = self._lib.iotml_frames_decode_columnar_ts(
            c_buf,
            ctypes.c_int64(len(buf)), ctypes.c_int64(int(start_offset)),
            codec.types.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            codec.nullable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(codec.n_fields),
            ctypes.c_int64(self.pinned_id_limit),
            out_numeric.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out_labels.ctypes.data_as(ctypes.c_char_p),
            ctypes.c_int64(label_stride),
            out_keys.ctypes.data_as(ctypes.c_char_p)
            if out_keys is not None else None,
            ctypes.c_int64(out_keys.dtype.itemsize
                           if out_keys is not None else 0),
            ctypes.c_int64(cap), ctypes.byref(next_off),
            ctypes.byref(flags), ctypes.byref(skipped),
            ctypes.byref(ts_min), ctypes.byref(ts_max))
        if rows < 0:
            raise ValueError("frame decoder rejected arguments")
        self.last_ts_min = int(ts_min.value)
        self.last_ts_max = int(ts_max.value)
        return int(rows), int(next_off.value), int(flags.value), \
            int(skipped.value)
