"""ctypes bindings for the C++ Kafka wire client (cpp/kafka_client.cc).

`NativeKafkaBroker` is the native twin of `kafka_wire.KafkaWireBroker`:
same `Broker` duck-type (produce / fetch / end_offset / commit / ...), but
every wire byte is handled in C++ — the role librdkafka played for the
reference's `tensorflow_io.kafka` ops (reference cardata-v3.py:46-47).

Beyond the duck-type it exposes the fused hot path `fetch_decode()`:
fetch + Confluent framing strip + columnar Avro decode in a single native
call, returning `(numeric [n, F], labels [n, S], next_offset)` ready for
`normalizer.np` + `jax.device_put` — the KafkaDataset-equivalent with zero
per-message Python objects.  `StreamConsumer.poll_decoded` and
`SensorBatches` use it automatically when the broker supports it.

The Python client (`kafka_wire.py`) is the correctness oracle;
`tests/test_native_kafka.py` cross-checks the two against the same wire
server.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .broker import (CorruptMessageError, Message, OffsetOutOfRangeError,
                     SchemaIdMismatchError, TopicSpec)
from .kafka_wire import NotLeaderForPartitionError, ProducePartitionMixin
from .native import LABEL_STRIDE, NativeCodec, load

_ERR_NAMES = {1: "OFFSET_OUT_OF_RANGE", 3: "UNKNOWN_TOPIC_OR_PARTITION",
              6: "NOT_LEADER_FOR_PARTITION",
              16: "NOT_COORDINATOR",
              35: "UNSUPPORTED_VERSION", 36: "TOPIC_ALREADY_EXISTS",
              58: "SASL_AUTHENTICATION_FAILED"}


class KafkaProtocolError(RuntimeError):
    def __init__(self, rc: int, what: str):
        code = -rc - 1000
        name = _ERR_NAMES.get(code, str(code))
        super().__init__(f"{what}: kafka error {name}" if rc <= -1000
                         else f"{what}: transport error")
        self.code = code if rc <= -1000 else None


def _check(rc: int, what: str) -> int:
    if rc < 0:
        raise KafkaProtocolError(rc, what)
    return rc


_i64p = ctypes.POINTER(ctypes.c_int64)
_u8p = ctypes.POINTER(ctypes.c_uint8)


def _sig(lib) -> None:
    """Full argtypes for every entry point — without them ctypes passes
    Python ints as 32-bit c_int, truncating handle pointers and int64s."""
    c = ctypes
    lib.iotml_kafka_connect.restype = c.c_void_p
    lib.iotml_kafka_connect.argtypes = [
        c.c_char_p, c.c_int32, c.c_char_p, c.c_char_p, c.c_char_p, c.c_double]
    lib.iotml_kafka_close.restype = None
    lib.iotml_kafka_close.argtypes = [c.c_void_p]
    sigs = {
        "metadata": [c.c_void_p, c.c_char_p],
        "create_topic": [c.c_void_p, c.c_char_p, c.c_int32],
        # + optional cleanup.policy config entry (NULL = none)
        "create_topic_cfg": [c.c_void_p, c.c_char_p, c.c_int32, c.c_char_p],
        "list_offset": [c.c_void_p, c.c_char_p, c.c_int32, c.c_int64],
        "produce": [c.c_void_p, c.c_char_p, c.c_int32, c.c_char_p, _i64p,
                    c.c_char_p, _i64p, _u8p, _i64p, c.c_int64],
        # tombstone-capable produce: value_null flags ride after key_null
        "produce_nulls": [c.c_void_p, c.c_char_p, c.c_int32, c.c_char_p,
                          _i64p, c.c_char_p, _i64p, _u8p, _u8p, _i64p,
                          c.c_int64],
        "produce_raw": [c.c_void_p, c.c_char_p, c.c_int32, _u8p, c.c_int64],
        "fetch": [c.c_void_p, c.c_char_p, c.c_int32, c.c_int64, c.c_int64],
        "staged_bytes": [c.c_void_p, _i64p, _i64p],
        "staged_value_nulls": [c.c_void_p, _u8p],
        "high_watermark": [c.c_void_p],
        "take": [c.c_void_p, c.c_char_p, _i64p, c.c_char_p, _i64p, _u8p,
                 _i64p, _i64p],
        "fetch_decode": [c.c_void_p, c.c_char_p, c.c_int32, c.c_int64,
                         c.POINTER(c.c_int8), _u8p, c.c_int64, c.c_int64,
                         c.POINTER(c.c_double), c.c_char_p, c.c_int64,
                         c.c_int64, _i64p],
        "fetch_decode_keys": [c.c_void_p, c.c_char_p, c.c_int32, c.c_int64,
                              c.POINTER(c.c_int8), _u8p, c.c_int64,
                              c.c_int64, c.POINTER(c.c_double), c.c_char_p,
                              c.c_int64, c.c_char_p, c.c_int64, c.c_int64,
                              _i64p],
        "commit": [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int32, c.c_int64],
        "commit_many": [c.c_void_p, c.c_char_p, c.c_char_p,
                        c.POINTER(c.c_int32), _i64p, c.c_int64],
        "committed": [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int32],
    }
    for name, argtypes in sigs.items():
        fn = getattr(lib, f"iotml_kafka_{name}")
        fn.restype = ctypes.c_int64
        fn.argtypes = argtypes
    lib.iotml_kafka_set_pinned_id_limit.restype = None
    lib.iotml_kafka_set_pinned_id_limit.argtypes = [c.c_void_p, c.c_int64]


class NativeKafkaBroker(ProducePartitionMixin):
    """Kafka-protocol client over the C++ engine, Broker duck-typed."""

    def __init__(self, servers: str, client_id: str = "iotml-native",
                 sasl_username: Optional[str] = None,
                 sasl_password: Optional[str] = None,
                 timeout_s: float = 30.0,
                 key_stride: Optional[int] = None,
                 pinned_id_limit: Optional[int] = None):
        #: bytes per row reserved for message keys in fetch_decode_keys;
        #: raise it where per-entity consumers join on keys longer than
        #: the MQTT-topic defaults (a truncated key aliases two cars).
        #: None → the class default KEY_STRIDE (single source of truth)
        if key_stride is not None:
            self.KEY_STRIDE = int(key_stride)
        #: rows whose key filled the stride (possibly truncated by the
        #: engine — the engine writes at most stride-1 bytes)
        self.keys_maybe_truncated = 0
        lib = load()
        if lib is None:
            raise RuntimeError("native stream engine unavailable")
        _sig(lib)
        self._lib = lib
        # bootstrap list: first reachable server wins (standard
        # bootstrap.servers semantics, shared parser with KafkaWireBroker)
        from ..utils.net import parse_bootstrap

        self._h = None
        for host, port in parse_bootstrap(servers):
            self._h = lib.iotml_kafka_connect(
                host.encode(), port, client_id.encode(),
                sasl_username.encode() if sasl_username is not None else None,
                sasl_password.encode() if sasl_password is not None else None,
                ctypes.c_double(timeout_s))
            if self._h:
                break
        if not self._h:
            raise ConnectionError(
                f"native kafka connect to {servers} failed"
                + (" (SASL)" if sasl_username else ""))
        # Runtime guard on the fused strip=5 decode (ON by default):
        # writer-schema ids at/above the reserved band
        # (stream.registry.RESERVED_ID_BASE) mark EVOLVED schemas a
        # positional v1 decode would silently mis-read — fetch_decode
        # stops before such a frame and raises SchemaIdMismatchError so
        # the consumer resolves that chunk by name in Python.  Pass
        # pinned_id_limit=-1 to restore the legacy blind strip.
        if pinned_id_limit is None:
            from .registry import RESERVED_ID_BASE

            pinned_id_limit = RESERVED_ID_BASE
        self.pinned_id_limit = int(pinned_id_limit)
        lib.iotml_kafka_set_pinned_id_limit(self._h, self.pinned_id_limit)
        self._meta: Dict[str, int] = {}
        self._rr: Dict[str, int] = {}
        # One socket + one C-side staged buffer per handle: serialize every
        # native call, as the Python twin (kafka_wire.KafkaWireBroker) does.
        # RLock because create_topic/produce_many re-enter via topic().
        self._lock = threading.RLock()

    # ------------------------------------------------------------ metadata
    def topic(self, name: str) -> TopicSpec:
        with self._lock:
            n = self._meta.get(name)
            if not n:
                n = _check(self._lib.iotml_kafka_metadata(self._h, name.encode()),
                           f"metadata({name})")
                if n == 0:
                    raise KeyError(name)
                self._meta[name] = n
            return TopicSpec(name, n)

    def refresh_topic(self, name: str) -> Optional[int]:
        """Drop the cached partition count and re-query broker metadata.

        `topic()` caches positive lookups forever (the fused fetch hot path
        must not pay a metadata round-trip per poll), so partition growth is
        only visible through an explicit refresh — the group coordinator
        calls this on its rate-limited metadata sweep (metadata.max.age.ms
        analogue).  Returns the fresh count, or None while the topic does
        not exist (yet)."""
        with self._lock:
            self._meta.pop(name, None)
            n = _check(self._lib.iotml_kafka_metadata(self._h, name.encode()),
                       f"metadata({name})")
            if n == 0:
                return None
            self._meta[name] = n
            return n

    def create_topic(self, name: str, partitions: int = 1,
                     retention_messages: Optional[int] = None,
                     cleanup_policy: Optional[str] = None) -> TopicSpec:
        with self._lock:
            existed = _check(self._lib.iotml_kafka_create_topic_cfg(
                self._h, name.encode(), partitions,
                cleanup_policy.encode() if cleanup_policy else None),
                f"create_topic({name})")
            if existed:
                # the topic's real partition count may differ from the request —
                # refresh from metadata so the partitioner never routes out of
                # range
                self._meta.pop(name, None)
                return self.topic(name)
            self._meta[name] = partitions
            return TopicSpec(name, partitions)

    # ------------------------------------------------------------- produce
    def _partition_count_or_default(self, topic: str) -> int:
        try:
            return self.topic(topic).partitions
        except KeyError:
            return 1

    def produce_many(self, topic: str, entries, partition=None) -> int:
        """entries: [(key, value, timestamp_ms[, headers])] → offset of
        the last one.  Trailing record headers (trace context on the
        in-process broker) are dropped — the native log has no header
        column; traces end at the native-engine boundary by design."""
        with self._lock:
            by_part: Dict[int, list] = {}
            for key, value, ts, *_hdrs in entries:
                p = self._partition_for(topic, key) if partition is None \
                    else partition
                by_part.setdefault(p, []).append((key, value, ts))
            last = -1
            for p, ents in sorted(by_part.items()):
                # shared columnar layout (kafka_wire.columnar_kvt): one
                # definition of the (values, offsets, key-null) C ABI for
                # both native produce paths
                from .kafka_wire import columnar_kvt

                # tombstones (value None): framed through the null-aware
                # entry point so the delete marker crosses the wire as a
                # null value, never a spoofed empty payload
                vnull = None
                if any(v is None for _k, v, _t in ents):
                    vnull = np.asarray(
                        [1 if v is None else 0 for _k, v, _t in ents],
                        np.uint8)
                    ents = [(k, v if v is not None else b"", t)
                            for k, v, t in ents]
                values, voff, keys, koff, knull, ts = columnar_kvt(ents)
                if keys is None:
                    kargs = (None, None, None)
                else:
                    kargs = (ctypes.c_char_p(keys),
                             koff.ctypes.data_as(_i64p),
                             knull.ctypes.data_as(_u8p))
                if vnull is not None:
                    rc = self._lib.iotml_kafka_produce_nulls(
                        self._h, topic.encode(), p,
                        ctypes.c_char_p(values),
                        voff.ctypes.data_as(_i64p), *kargs,
                        vnull.ctypes.data_as(_u8p),
                        ts.ctypes.data_as(_i64p), len(ents))
                else:
                    rc = self._lib.iotml_kafka_produce(
                        self._h, topic.encode(), p, ctypes.c_char_p(values),
                        voff.ctypes.data_as(_i64p), *kargs,
                        ts.ctypes.data_as(_i64p), len(ents))
                if rc == -1006:
                    raise NotLeaderForPartitionError(topic, p)
                base = _check(rc, f"produce({topic}:{p})")
                last = max(last, base + len(ents) - 1)
            return last

    def produce_raw(self, topic: str, partition: int,
                    frames: bytes) -> int:
        """RAW_PRODUCE through the C++ client: the pre-framed batch
        bytes go straight onto the socket (no MessageSet re-encode, no
        per-record work).  Same error surface as the Python wire client:
        NotImplementedError on an extension-less server (pin back to
        classic), CorruptMessageError on whole-batch rejection,
        NotLeaderForPartitionError on a sharded bounce."""
        with self._lock:
            rc = self._lib.iotml_kafka_produce_raw(
                self._h, topic.encode(), partition,
                ctypes.cast(ctypes.c_char_p(frames), _u8p),
                ctypes.c_int64(len(frames)))
            if rc == -1035:
                raise NotImplementedError(
                    "server lacks the RAW_PRODUCE extension")
            if rc == -1002:
                raise CorruptMessageError(topic, partition, -1)
            if rc == -1006:
                raise NotLeaderForPartitionError(topic, partition)
            return _check(rc, f"produce_raw({topic}:{partition})")

    # --------------------------------------------------------------- fetch
    def _raise_out_of_range(self, rc: int, topic: str, partition: int,
                            offset: int) -> None:
        """proto error 1 (rc -1001): the broker trimmed past `offset`.
        The iotml wire server carries the earliest retained offset in
        the hwm slot for this error (real brokers send -1; consumers
        re-query begin_offset on 0), staged by the native client."""
        if rc == -1001:
            earliest = max(
                int(self._lib.iotml_kafka_high_watermark(self._h)), 0)
            raise OffsetOutOfRangeError(topic, partition, offset, earliest)
        if rc == -1006:
            # NOT_LEADER_FOR_PARTITION (cluster shard routing): same
            # typed signal as the Python wire client, so routing clients
            # treat both transports identically
            raise NotLeaderForPartitionError(topic, partition)

    def fetch(self, topic: str, partition: int, offset: int,
              max_messages: int = 1024) -> List[Message]:
        with self._lock:
            rc = self._lib.iotml_kafka_fetch(self._h, topic.encode(), partition,
                                             ctypes.c_int64(offset),
                                             ctypes.c_int64(max_messages))
            if rc == -1003:
                raise KeyError(topic)
            self._raise_out_of_range(rc, topic, partition, offset)
            n = _check(rc, f"fetch({topic}:{partition}@{offset})")
            if n == 0:
                return []
            vb, kb = ctypes.c_int64(), ctypes.c_int64()
            self._lib.iotml_kafka_staged_bytes(self._h, ctypes.byref(vb),
                                               ctypes.byref(kb))
            values = ctypes.create_string_buffer(max(vb.value, 1))
            keys = ctypes.create_string_buffer(max(kb.value, 1))
            voff = np.zeros((n + 1,), np.int64)
            koff = np.zeros((n + 1,), np.int64)
            knull = np.zeros((n,), np.uint8)
            vnull = np.zeros((n,), np.uint8)
            moff = np.zeros((n,), np.int64)
            ts = np.zeros((n,), np.int64)
            # value-null flags staged BEFORE take (take clears staging):
            # tombstones surface as Message.value None, never b""
            self._lib.iotml_kafka_staged_value_nulls(
                self._h, vnull.ctypes.data_as(_u8p))
            self._lib.iotml_kafka_take(
                self._h, values, voff.ctypes.data_as(_i64p), keys,
                koff.ctypes.data_as(_i64p), knull.ctypes.data_as(_u8p),
                moff.ctypes.data_as(_i64p), ts.ctypes.data_as(_i64p))
            vraw = values.raw
            kraw = keys.raw
            out = []
            for i in range(n):
                key = None if knull[i] else kraw[koff[i]:koff[i + 1]]
                value = None if vnull[i] else vraw[voff[i]:voff[i + 1]]
                out.append(Message(topic, partition, int(moff[i]),
                                   value, key, int(ts[i])))
            return out

    def fetch_decode(self, topic: str, partition: int, offset: int,
                     codec: NativeCodec, strip: int = 5,
                     max_rows: int = 4096
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
        """Fused native poll → (numeric [n, F] float64, labels [n, S] bytes,
        next_offset).  n == 0 means no data at `offset`."""
        with self._lock:
            numeric = np.empty((max_rows, codec.n_numeric), np.float64)
            labels = np.zeros((max_rows, max(codec.n_strings, 1)),
                              f"S{LABEL_STRIDE}")
            next_off = ctypes.c_int64(offset)
            rc = self._lib.iotml_kafka_fetch_decode(
                self._h, topic.encode(), partition, ctypes.c_int64(offset),
                codec.types.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
                codec.nullable.ctypes.data_as(_u8p),
                ctypes.c_int64(codec.n_fields), ctypes.c_int64(strip),
                numeric.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                labels.ctypes.data_as(ctypes.c_char_p),
                ctypes.c_int64(LABEL_STRIDE), ctypes.c_int64(max_rows),
                ctypes.byref(next_off))
            if rc == -1999:
                raise SchemaIdMismatchError(topic, partition, offset)
            if rc <= -2000:
                raise ValueError(f"malformed Avro message at row {-(rc + 2000) - 1}")
            if rc == -1003:
                raise KeyError(topic)
            self._raise_out_of_range(rc, topic, partition, offset)
            n = _check(rc, f"fetch_decode({topic}:{partition}@{offset})")
            return (numeric[:n], labels[:n, : codec.n_strings],
                    int(next_off.value))

    #: default bytes per row for message keys in fetch_decode_keys
    #: (MQTT-topic keys like "vehicles/sensor/data/electric-vehicle-00042"
    #: fit with room; longer keys truncate at stride-1, zero-padded —
    #: pass key_stride= at construction to widen)
    KEY_STRIDE = 64

    def fetch_decode_keys(self, topic: str, partition: int, offset: int,
                          codec: NativeCodec, strip: int = 5,
                          max_rows: int = 4096
                          ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     int]:
        """`fetch_decode` + per-message keys: (numeric [n, F], labels
        [n, S], keys [n] S{KEY_STRIDE} bytes, next_offset).  The key is
        the record's routing identity (car id via the MQTT-topic key) —
        what per-entity consumers (car-health detection) join on."""
        with self._lock:
            numeric = np.empty((max_rows, codec.n_numeric), np.float64)
            labels = np.zeros((max_rows, max(codec.n_strings, 1)),
                              f"S{LABEL_STRIDE}")
            keys = np.zeros((max_rows,), f"S{self.KEY_STRIDE}")
            next_off = ctypes.c_int64(offset)
            rc = self._lib.iotml_kafka_fetch_decode_keys(
                self._h, topic.encode(), partition, ctypes.c_int64(offset),
                codec.types.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
                codec.nullable.ctypes.data_as(_u8p),
                ctypes.c_int64(codec.n_fields), ctypes.c_int64(strip),
                numeric.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                labels.ctypes.data_as(ctypes.c_char_p),
                ctypes.c_int64(LABEL_STRIDE),
                keys.ctypes.data_as(ctypes.c_char_p),
                ctypes.c_int64(self.KEY_STRIDE),
                ctypes.c_int64(max_rows), ctypes.byref(next_off))
            if rc == -1999:
                raise SchemaIdMismatchError(topic, partition, offset)
            if rc <= -2000:
                raise ValueError(
                    f"malformed Avro message at row {-(rc + 2000) - 1}")
            if rc == -1003:
                raise KeyError(topic)
            self._raise_out_of_range(rc, topic, partition, offset)
            n = _check(rc, f"fetch_decode_keys({topic}:{partition}@{offset})")
            # A key that fills the stride was possibly truncated by the
            # engine (it writes at most stride-1 bytes): two distinct car
            # keys sharing a stride-1-byte prefix would alias into one
            # detector entity — surface that instead of staying silent.
            nt = int(np.sum(np.char.str_len(keys[:n])
                            >= self.KEY_STRIDE - 1))
            if nt:
                if not self.keys_maybe_truncated:
                    import warnings

                    warnings.warn(
                        f"{nt} message key(s) filled KEY_STRIDE-1="
                        f"{self.KEY_STRIDE - 1} bytes and may be truncated"
                        " (keys sharing that prefix alias); construct"
                        " NativeKafkaBroker with a larger key_stride=",
                        RuntimeWarning, stacklevel=2)
                self.keys_maybe_truncated += nt
            return (numeric[:n], labels[:n, : codec.n_strings], keys[:n],
                    int(next_off.value))

    # ------------------------------------------------------------- offsets
    def end_offset(self, topic: str, partition: int = 0) -> int:
        with self._lock:
            return _check(self._lib.iotml_kafka_list_offset(
                self._h, topic.encode(), partition, ctypes.c_int64(-1)),
                f"end_offset({topic}:{partition})")

    def begin_offset(self, topic: str, partition: int = 0) -> int:
        with self._lock:
            return _check(self._lib.iotml_kafka_list_offset(
                self._h, topic.encode(), partition, ctypes.c_int64(-2)),
                f"begin_offset({topic}:{partition})")

    # ------------------------------------------------- consumer-group API
    def commit(self, group: str, topic: str, partition: int,
               next_offset: int) -> None:
        with self._lock:
            _check(self._lib.iotml_kafka_commit(
                self._h, group.encode(), topic.encode(), partition,
                ctypes.c_int64(next_offset)), f"commit({group},{topic})")

    def commit_many(self, group: str, topic: str, entries) -> None:
        """Commit [(partition, next_offset), ...] of one topic in ONE wire
        request (the per-partition loop cost a round trip each)."""
        entries = list(entries)
        if not entries:
            return
        with self._lock:
            parts = np.asarray([p for p, _ in entries], np.int32)
            offs = np.asarray([o for _, o in entries], np.int64)
            _check(self._lib.iotml_kafka_commit_many(
                self._h, group.encode(), topic.encode(),
                parts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                offs.ctypes.data_as(_i64p), len(entries)),
                f"commit_many({group},{topic})")

    def committed(self, group: str, topic: str,
                  partition: int) -> Optional[int]:
        with self._lock:
            off = self._lib.iotml_kafka_committed(
                self._h, group.encode(), topic.encode(), partition)
            if off < -1:  # -1 itself means "no committed offset"
                raise KafkaProtocolError(off, f"committed({group},{topic})")
            return None if off == -1 else off

    def committed_many(self, group: str, pairs):
        """Committed offsets for [(topic, partition), ...]; pairs with
        no committed offset are omitted (Broker/wire-client contract).
        The native library has no batched OffsetFetch entry point, so
        this loops — callers get the uniform duck-type either way."""
        out = {}
        for t, p in pairs:
            off = self.committed(group, t, p)
            if off is not None:
                out[(t, p)] = off
        return out

    def close(self) -> None:
        with self._lock:
            if getattr(self, "_h", None):
                self._lib.iotml_kafka_close(self._h)
                self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
