"""Ordered, indexed producer — the KafkaOutputSequence equivalent.

The reference writes predictions back with ``kafka_io.KafkaOutputSequence``
(cardata-v3.py:238-252): results are assigned an absolute *index* as batches
complete, and ``flush()`` publishes them in index order, so the output topic
preserves input-stream order even when batches finish out of order.  That
ordering contract is what lets downstream consumers join predictions back to
source offsets, so we keep it exactly: ``setitem(index, message)`` + ordered
``flush()``, with gap detection instead of silent misalignment.
"""

from __future__ import annotations

from typing import Dict, Optional

from .broker import Broker


class OutputSequence:
    """Buffer of (index → message) flushed to a topic in index order."""

    def __init__(self, broker: Broker, topic: str,
                 partition: Optional[int] = None):
        self.broker = broker
        self.topic = topic
        self.partition = partition
        self._buf: Dict[int, bytes] = {}

    def setitem(self, index: int, message):
        if isinstance(message, str):
            message = message.encode()
        if index in self._buf:
            raise ValueError(f"duplicate output index {index}")
        self._buf[index] = message

    def __setitem__(self, index: int, message):
        self.setitem(index, message)

    def flush(self, allow_gaps: bool = False) -> int:
        """Publish buffered messages in ascending index order.

        Returns the number of messages flushed.  With allow_gaps=False
        (default) a missing index raises — an out-of-order scorer bug should
        fail loudly, not ship misaligned predictions.
        """
        if not self._buf:
            return 0
        idxs = sorted(self._buf)
        if not allow_gaps:
            lo, hi = idxs[0], idxs[-1]
            if hi - lo + 1 != len(idxs):
                missing = set(range(lo, hi + 1)) - set(idxs)
                raise ValueError(f"output sequence has gaps at {sorted(missing)[:8]}...")
        produce_many = getattr(self.broker, "produce_many", None)
        if produce_many is not None:
            # one batched call: over the Kafka wire a per-message produce
            # is a round trip each — a drain's flush would cost thousands
            # of them.  Order within the batch is preserved by contract.
            produce_many(self.topic, [(None, self._buf[i], 0) for i in idxs],
                         partition=self.partition)
        else:
            for i in idxs:
                self.broker.produce(self.topic, self._buf[i],
                                    partition=self.partition)
        n = len(idxs)
        self._buf.clear()
        return n
