"""Producers: the ordered KafkaOutputSequence equivalent and the
zero-copy RAW batch producer.

The reference writes predictions back with ``kafka_io.KafkaOutputSequence``
(cardata-v3.py:238-252): results are assigned an absolute *index* as batches
complete, and ``flush()`` publishes them in index order, so the output topic
preserves input-stream order even when batches finish out of order.  That
ordering contract is what lets downstream consumers join predictions back to
source offsets, so we keep it exactly: ``setitem(index, message)`` + ordered
``flush()``, with gap detection instead of silent misalignment.

``RawBatchProducer`` (ISSUE 12) is the write-path twin of the consume
side's FrameDecoder: a converted chunk is framed ONCE (natively, at
conversion) and the resulting raw frame batch ships over RAW_PRODUCE to
be appended segment-verbatim — with the documented fallback ladder
(IOTML_RAW_PRODUCE auto|on|off; an UNSUPPORTED_VERSION server pins the
producer back to classic PRODUCE permanently, exactly like the consume
side's RAW_FETCH pin-back).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..obs import tracing
from ..obs.metrics import default_registry as _metrics
from .broker import Broker

#: write-plane telemetry — the produce-leg breakdown the e2e bench
#: publishes (convert+frame seconds live with the encoder; these cover
#: the append/ship leg)
raw_produce_records = _metrics.counter(
    "iotml_raw_produce_records_total",
    "records shipped as pre-framed RAW_PRODUCE batches")
raw_produce_fallbacks = _metrics.counter(
    "iotml_raw_produce_fallbacks_total",
    "producers pinned back to classic PRODUCE (UNSUPPORTED_VERSION)")
raw_produce_append_seconds = _metrics.histogram(
    "iotml_raw_produce_append_seconds",
    "RAW_PRODUCE ship+append latency per batch (the produce leg's "
    "append half)")
raw_produce_convert_seconds = _metrics.histogram(
    "iotml_raw_produce_convert_seconds",
    "convert+frame latency per raw batch (the produce leg's native "
    "JSON→Avro→frame half, observed by the fused converters)")


class OutputSequence:
    """Buffer of (index → message) flushed to a topic in index order."""

    def __init__(self, broker: Broker, topic: str,
                 partition: Optional[int] = None):
        self.broker = broker
        self.topic = topic
        self.partition = partition
        self._buf: Dict[int, bytes] = {}

    def setitem(self, index: int, message):
        if isinstance(message, str):
            message = message.encode()
        if index in self._buf:
            raise ValueError(f"duplicate output index {index}")
        self._buf[index] = message

    def __setitem__(self, index: int, message):
        self.setitem(index, message)

    def flush(self, allow_gaps: bool = False) -> int:
        """Publish buffered messages in ascending index order.

        Returns the number of messages flushed.  With allow_gaps=False
        (default) a missing index raises — an out-of-order scorer bug should
        fail loudly, not ship misaligned predictions.
        """
        if not self._buf:
            return 0
        idxs = sorted(self._buf)
        if not allow_gaps:
            lo, hi = idxs[0], idxs[-1]
            if hi - lo + 1 != len(idxs):
                missing = set(range(lo, hi + 1)) - set(idxs)
                raise ValueError(f"output sequence has gaps at {sorted(missing)[:8]}...")
        produce_many = getattr(self.broker, "produce_many", None)
        if produce_many is not None:
            # one batched call: over the Kafka wire a per-message produce
            # is a round trip each — a drain's flush would cost thousands
            # of them.  Order within the batch is preserved by contract.
            produce_many(self.topic, [(None, self._buf[i], 0) for i in idxs],
                         partition=self.partition)
        else:
            for i in idxs:
                self.broker.produce(self.topic, self._buf[i],
                                    partition=self.partition)
        n = len(idxs)
        self._buf.clear()
        return n


class RawBatchProducer:
    """Ship pre-framed raw batches to one topic, with the classic
    fallback ladder.

    The producer OWNS the plane decision per ``IOTML_RAW_PRODUCE``:

    - ``auto`` (default): try ``produce_raw``; the first
      NotImplementedError (extension-less server / relay) pins this
      producer back to classic ``produce_many`` permanently — the same
      one-way downgrade the consume side applies to RAW_FETCH.
    - ``on``: raw required — an extension-less server raises (the CI
      parity gate's mode: a silent fallback must fail, not degrade).
    - ``off``: classic everywhere (debug escape hatch).

    Redelivery stays caller-owned (RAW_PRODUCE is NOT idempotent);
    CorruptMessageError means nothing was appended — re-frame and
    resend.  Batches above IOTML_PRODUCE_BATCH_BYTES are the CALLER's
    job to split (frames only split at frame boundaries, which the
    encoder owns); `produce_frames` ships one pre-split batch.
    """

    def __init__(self, broker, topic: str, mode: Optional[str] = None):
        from ..data.pipeline import raw_produce_mode

        self.broker = broker
        self.topic = topic
        self.mode = raw_produce_mode() if mode is None else mode
        # plane state: None = undecided (auto), True = raw, False = classic
        self._raw: Optional[bool] = {"on": True, "off": False,
                                     "auto": None}[self.mode]
        self.raw_batches = 0
        self.classic_records = 0

    @property
    def engaged(self) -> Optional[bool]:
        """True = raw plane active, False = pinned classic, None = not
        yet decided (auto, before the first batch)."""
        return self._raw

    def produce_frames(self, partition: int, frames: bytes,
                       count: int, entries=None) -> int:
        """Ship one pre-framed batch to `partition`; returns the batch's
        base offset.  `entries` ([(key, value, ts[, headers])]) is the
        classic-fallback form of the same records — REQUIRED in auto
        mode (the downgrade re-ships the exact records); omit it only
        under mode='on', where fallback is an error by contract."""
        import time

        if self._raw is False:
            return self._classic(partition, entries)
        produce_raw = getattr(self.broker, "produce_raw", None)
        if produce_raw is None:
            self._pin_classic()
            return self._classic(partition, entries)
        ctx = None
        if tracing.ENABLED:
            # wire-trace leg (ISSUE 13): a SAMPLED batch carries one
            # trace context in its first frame's headers — the frame
            # field survives RAW_PRODUCE, the segment, replica mirrors
            # and RAW_FETCH verbatim, so the batch's journey is
            # reconstructable across processes.  Cost: one record
            # re-encode per sampled batch, zero on unsampled ones.
            ctx = tracing.start("raw_produce")
            if ctx is not None:
                from ..ops.framing import stamp_first_frame

                frames = stamp_first_frame(
                    frames, ((tracing.HEADER_KEY, ctx),))
        try:
            t0 = time.perf_counter()
            base = produce_raw(self.topic, partition, frames)
            raw_produce_append_seconds.observe(time.perf_counter() - t0)
        except NotImplementedError:
            self._pin_classic()
            return self._classic(partition, entries)
        if ctx is not None:
            tracing.mark_batch(ctx, "raw_produce_append", self.topic,
                               partition, base, base + count - 1, count)
        self._raw = True
        self.raw_batches += 1
        raw_produce_records.inc(count)
        return base

    def _pin_classic(self) -> None:
        if self.mode == "on":
            raise NotImplementedError(
                f"IOTML_RAW_PRODUCE=on but the broker for "
                f"{self.topic!r} lacks the RAW_PRODUCE extension")
        if self._raw is not False:
            self._raw = False
            raw_produce_fallbacks.inc()

    def _classic(self, partition: int, entries) -> int:
        if entries is None:
            raise NotImplementedError(
                f"RAW_PRODUCE unavailable for {self.topic!r} and no "
                f"classic-fallback entries were provided")
        if callable(entries):
            entries = entries()  # built lazily: the fallback form costs
            # a per-record encode, paid only when actually downgrading
        last = self.broker.produce_many(self.topic, entries,
                                        partition=partition)
        self.classic_records += len(entries)
        return last - len(entries) + 1
