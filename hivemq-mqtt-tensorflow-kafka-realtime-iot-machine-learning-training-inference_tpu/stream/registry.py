"""Schema registry — Confluent Schema Registry semantics, in-process.

The reference depends on a running Schema Registry twice: KSQL's AVRO
streams register their value schemas implicitly, and the offline fixture
registers `cardata-v1.avsc` by hand with a REST POST to
`/subjects/<subject>-value/versions` (reference
`testdata/Test-Load-csv/register_schema.py:20-31`).  The 5-byte wire
framing every consumer strips (`ops.framing`) exists *because* ids live in
this registry.

This module keeps the same contract: subjects hold versioned schemas,
registration is idempotent by schema fingerprint (re-posting an identical
schema returns the existing id — Confluent behavior), ids are global and
monotonically increasing, and lookups work by id, by subject version, or by
latest.  `TopicNameStrategy` naming (`<topic>-value`) is provided so code
written against the real registry maps 1:1.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
from typing import Dict, List, Optional, Tuple

from ..core.schema import Field, RecordSchema


def parse_avsc(avsc: str) -> RecordSchema:
    """Build a RecordSchema from Avro schema JSON (inverse of
    RecordSchema.avro_json). Handles ["null", T] unions as nullable fields
    — the shape of the reference's KSQL-derived schema
    (AUTOENCODER.../cardata-v1.avsc:5-158)."""
    doc = json.loads(avsc)
    if doc.get("type") != "record":
        raise ValueError(f"only record schemas supported, got {doc.get('type')}")
    fields = []
    for f in doc.get("fields", []):
        t = f["type"]
        nullable = False
        if isinstance(t, list):
            non_null = [x for x in t if x != "null"]
            if len(non_null) != 1 or not isinstance(non_null[0], str):
                raise ValueError(f"unsupported union type {t!r} in {f['name']}")
            nullable = "null" in t
            t = non_null[0]
        if not isinstance(t, str):
            raise ValueError(f"unsupported complex type in field {f['name']}")
        fields.append(Field(name=f["name"], avro_type=t, nullable=nullable,
                            doc=f.get("doc", "")))
    # a trailing string field named like a label is the anomaly label in
    # both reference schema variants (failure_occurred / FAILURE_OCCURRED)
    label = next((f.name for f in fields
                  if f.name.lower() == "failure_occurred"), None)
    return RecordSchema(name=doc.get("name", "record"),
                        namespace=doc.get("namespace", ""),
                        fields=tuple(fields), label_field=label)


def fingerprint(avsc: str) -> str:
    """Canonical-ish fingerprint: whitespace-normalized schema JSON SHA256."""
    canon = json.dumps(json.loads(avsc), sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def subject_for_topic(topic: str, is_key: bool = False) -> str:
    """Confluent TopicNameStrategy: '<topic>-value' / '<topic>-key'."""
    return f"{topic}-{'key' if is_key else 'value'}"


@dataclasses.dataclass(frozen=True)
class RegisteredSchema:
    schema_id: int
    subject: str
    version: int
    avsc: str

    @property
    def record_schema(self) -> RecordSchema:
        return parse_avsc(self.avsc)


#: ids at/above this are reserved for framework-pinned writer schemas
#: (the schema-evolution band, `core.schema.WRITER_SCHEMAS` — e.g.
#: car-schema v2 at 1002): the registry never allocates into it, so an
#: evolved-schema frame id can never collide with a subject this
#: registry assigned
RESERVED_ID_BASE = 1000


class SchemaRegistry:
    """Subjects → versioned schemas with global ids (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next_id = 1
        self._by_id: Dict[int, RegisteredSchema] = {}
        self._subjects: Dict[str, List[RegisteredSchema]] = {}
        self._fp_to_id: Dict[str, int] = {}

    # -------------------------------------------------------------- write
    def register(self, subject: str, avsc: str) -> int:
        """POST /subjects/<subject>/versions equivalent; returns the id.

        Idempotent: an identical schema (by fingerprint) reuses its global
        id; registering it under a new subject adds a version entry there.
        """
        json.loads(avsc)  # syntax check up front, like the REST API's 422
        fp = fingerprint(avsc)
        with self._lock:
            sid = self._fp_to_id.get(fp)
            versions = self._subjects.setdefault(subject, [])
            if sid is not None:
                for rs in versions:
                    if rs.schema_id == sid:
                        return sid
            else:
                sid = self._next_id
                if sid >= RESERVED_ID_BASE:
                    raise RuntimeError(
                        f"schema id space exhausted at the reserved "
                        f"band ({RESERVED_ID_BASE}): this registry "
                        f"allocated {sid - 1} distinct schemas")
                self._next_id += 1
                self._fp_to_id[fp] = sid
            rs = RegisteredSchema(schema_id=sid, subject=subject,
                                  version=len(versions) + 1, avsc=avsc)
            versions.append(rs)
            self._by_id.setdefault(sid, rs)
            return sid

    def register_record_schema(self, topic: str, schema: RecordSchema) -> int:
        return self.register(subject_for_topic(topic), schema.avro_json())

    # --------------------------------------------------------------- read
    def by_id(self, schema_id: int) -> RegisteredSchema:
        """GET /schemas/ids/<id> equivalent."""
        with self._lock:
            try:
                return self._by_id[schema_id]
            except KeyError:
                raise KeyError(f"schema id {schema_id} not registered") from None

    def latest(self, subject: str) -> RegisteredSchema:
        """GET /subjects/<subject>/versions/latest equivalent."""
        with self._lock:
            versions = self._subjects.get(subject)
            if not versions:
                raise KeyError(f"subject {subject!r} not found")
            return versions[-1]

    def version(self, subject: str, version: int) -> RegisteredSchema:
        with self._lock:
            versions = self._subjects.get(subject, [])
            for rs in versions:
                if rs.version == version:
                    return rs
            raise KeyError(f"{subject!r} has no version {version}")

    def subjects(self) -> List[str]:
        with self._lock:
            return sorted(self._subjects)

    def check(self, subject: str, avsc: str) -> Optional[int]:
        """Is this exact schema already registered under subject? → id/None
        (the REST API's POST /subjects/<subject> lookup)."""
        fp = fingerprint(avsc)
        with self._lock:
            sid = self._fp_to_id.get(fp)
            if sid is None:
                return None
            if any(rs.schema_id == sid for rs in self._subjects.get(subject, [])):
                return sid
            return None
