"""Confluent Schema-Registry REST API over `SchemaRegistry`.

The reference registers schemas by POSTing Avro JSON to the registry's
REST endpoint (`testdata/Test-Load-csv/register_schema.py:20-31`:
`POST /subjects/{subject}/versions` with body `{"schema": "<avsc>"}`), and
its consumers resolve Confluent-framed schema ids via
`GET /schemas/ids/{id}`.  This server speaks that wire surface over the
in-process registry, byte-compatible with Confluent clients:

  POST /subjects/{subject}/versions   {"schema": avsc}  → {"id": n}
  POST /subjects/{subject}            {"schema": avsc}  → registered version
  GET  /subjects                                        → ["s", ...]
  GET  /subjects/{subject}/versions                     → [1, 2, ...]
  GET  /subjects/{subject}/versions/latest|{n}          → full entry
  GET  /schemas/ids/{id}                                → {"schema": avsc}
  GET  /config                                          → compatibility
"""

from __future__ import annotations

import json

from ..utils.rest import RestError, RestServer
from .registry import RegisteredSchema, SchemaRegistry


def _entry(rs: RegisteredSchema) -> dict:
    return {"subject": rs.subject, "version": rs.version,
            "id": rs.schema_id, "schema": rs.avsc}


class SchemaRegistryServer(RestServer):
    """REST front-end for one `SchemaRegistry`."""

    def __init__(self, registry: SchemaRegistry, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__(host, port, name="iotml-schema-registry")
        self.registry = registry
        sub = r"([^/]+)"
        self.route("GET", r"/subjects", self._subjects)
        self.route("POST", rf"/subjects/{sub}/versions", self._register)
        self.route("POST", rf"/subjects/{sub}", self._check)
        self.route("GET", rf"/subjects/{sub}/versions", self._versions)
        self.route("GET", rf"/subjects/{sub}/versions/latest", self._latest)
        self.route("GET", rf"/subjects/{sub}/versions/(\d+)", self._version)
        self.route("GET", r"/schemas/ids/(\d+)", self._by_id)
        self.route("GET", r"/config", lambda m, b: (
            200, {"compatibilityLevel": "BACKWARD"}))

    # ------------------------------------------------------------- routes
    def _subjects(self, m, body):
        return 200, self.registry.subjects()

    def _register(self, m, body):
        avsc = body.get("schema")
        if not avsc:
            raise RestError(422, "missing 'schema' field")
        try:
            sid = self.registry.register(m.group(1), avsc)
        except ValueError as e:
            # Confluent's 42201: invalid Avro schema
            raise RestError(422, f"invalid schema: {e}")
        return 200, {"id": sid}

    def _check(self, m, body):
        avsc = body.get("schema")
        if not avsc:
            raise RestError(422, "missing 'schema' field")
        try:
            sid = self.registry.check(m.group(1), avsc)
        except ValueError as e:
            raise RestError(422, f"invalid schema: {e}")
        if sid is None:
            # Confluent's 40403: schema not found under subject
            raise RestError(404, "schema not found")
        for rs in self._all_versions(m.group(1)):
            if rs.schema_id == sid:
                return 200, _entry(rs)
        raise RestError(404, "schema not found")

    def _all_versions(self, subject):
        try:
            n = self.registry.latest(subject).version
        except KeyError:
            return []
        return [self.registry.version(subject, v) for v in range(1, n + 1)]

    def _versions(self, m, body):
        versions = self._all_versions(m.group(1))
        if not versions:
            raise RestError(404, f"subject {m.group(1)!r} not found")
        return 200, [rs.version for rs in versions]

    def _latest(self, m, body):
        try:
            return 200, _entry(self.registry.latest(m.group(1)))
        except KeyError as e:
            raise RestError(404, str(e))

    def _version(self, m, body):
        try:
            return 200, _entry(self.registry.version(m.group(1),
                                                     int(m.group(2))))
        except KeyError as e:
            raise RestError(404, str(e))

    def _by_id(self, m, body):
        try:
            rs = self.registry.by_id(int(m.group(1)))
        except KeyError as e:
            raise RestError(404, str(e))
        return 200, {"schema": rs.avsc}
