"""Follower replication + leader failover for the wire broker.

The reference provisions replicated infrastructure: RF-3 Kafka topics on
a 3-broker cluster (reference 01_installConfluentPlatform.sh:180-183,
gcp.yaml:46-54) and a 5-node HiveMQ cluster (hivemq-crd.yaml:10) — its
pipeline survives a broker death.  This module is the TPU rebuild's
minimum equivalent for the stream plane:

- `FollowerReplica`: a second wire-server process/object that
  continuously pulls a leader's topics (messages, offsets preserved
  one-to-one, consumer-group commit table included) into its own local
  log and serves the same Kafka wire protocol.  Async pull replication —
  Kafka `acks=1` semantics: an unreplicated tail at the moment of leader
  death is lost (the loss window is `lag()`, observable).
- Failover lives in the CLIENT: `KafkaWireBroker` keeps its full
  bootstrap list, and a request hitting a dead socket reconnects to the
  next reachable server and retries once (kafka_wire.py `_request`).  A
  consumer built with `bootstrap="leader,follower"` that loses the
  leader mid-drain resumes fetching from the follower at the SAME
  offsets; committed offsets are mirrored, so a crash-restart
  (`from_committed`) also lands correctly.

What this deliberately does not do (scoped against the reference's
managed clusters, see ARCHITECTURE.md): no ISR/acks=all produce path
(a produce acked by the leader alone can be lost with it), no automatic
leader election (the bootstrap order IS the priority list), and no
replica for the MQTT session plane (HiveMQ clustering replicates live
session state; the rebuild's MQTT front is stateless-per-connection by
design, and a reconnecting fleet re-establishes sessions against the
surviving front).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..chaos import faults as chaos
from ..obs import metrics as obs_metrics
from ..utils.backoff import ExpBackoff
from .broker import Broker, OffsetOutOfRangeError
from .kafka_wire import KafkaWireBroker, KafkaWireServer

#: wire-server epoch of an UNPROMOTED follower: no stamped epoch can
#: equal it, so every fenced client is refused until promote() installs
#: a real leadership epoch.
FOLLOWER_EPOCH = -1


class FollowerReplica:
    """Pull-replicate a leader's topics into a local wire-served log.

    Args:
      leader: bootstrap string of the leader (host:port).
      topics: topic names to mirror (None = every topic the leader
        lists, re-polled each sync round so late-created topics join).
      groups: consumer groups whose committed offsets are mirrored.
      host/port: where this follower's own wire server listens.
      poll_interval_s: sleep between sync rounds once caught up.
      commit_interval_s: idle cadence of commit-table mirroring in the
        background loop.  Rounds that copied messages always mirror
        (commits land together with the data they fence); fully
        caught-up rounds re-poll the group tables at most this often —
        without it, every idle round issued offset fetches at
        poll_interval_s rates (~hundreds of requests/s of steady idle
        load on the leader for a 10-partition topic, ADVICE.md round-5).
      sasl: optional (user, password) for the leader connection; the
        follower's own server stays open (fixture semantics).
    """

    def __init__(self, leader: str, topics: Optional[List[str]] = None,
                 groups: Tuple[str, ...] = (), host: str = "127.0.0.1",
                 port: int = 0, poll_interval_s: float = 0.05,
                 fetch_batch: int = 2000,
                 retention_messages: Optional[int] = None,
                 sasl: Optional[tuple] = None,
                 commit_interval_s: float = 1.0,
                 store_dir: Optional[str] = None, store_policy=None,
                 partition_filter=None, local: Optional[Broker] = None,
                 compacted_topics: Tuple[str, ...] = (),
                 replica_id: Optional[int] = None, topology=None):
        #: local log bound per mirrored topic.  The wire protocol does
        #: not carry the leader's retention config, so a follower of a
        #: retention-bounded leader must be given its own bound here or
        #: it accumulates the whole stream forever.
        self._retention = retention_messages
        #: partition_filter(topic, partition) -> bool: mirror only the
        #: partitions it accepts (None = all).  A SHARD follower in a
        #: partitioned cluster (iotml.cluster) mirrors exactly its shard
        #: — fetching unowned partitions from a sharded leader would only
        #: bounce off NOT_LEADER_FOR_PARTITION anyway.
        self._owns = partition_filter or (lambda _t, _p: True)
        # store_dir: mount the follower's log durably (iotml.store) —
        # a restarted follower resumes replication from its retained
        # end instead of re-copying the leader's whole history.
        # `local` injects a pre-built broker instead (a cluster shard
        # follower passes a ShardBroker so unowned partitions stay
        # unmounted and refuse to serve).
        if local is not None and store_dir is not None:
            raise ValueError("pass either local= or store_dir=, not both")
        self.local = local if local is not None else \
            Broker(store_dir=store_dir, store_policy=store_policy)
        # epoch -1 = "not a leader": an epoch-stamped produce/commit
        # reaching this follower BEFORE promotion is fenced (the
        # pre-promotion half of split-log protection — a failed-over
        # client must not write to a log that replication still owns);
        # unstamped legacy clients keep the fixture-open semantics
        self.server = KafkaWireServer(self.local, host=host, port=port,
                                      epoch=FOLLOWER_EPOCH)
        user, pw = sasl if sasl is not None else (None, None)
        #: replica_id (ISSUE 14): >= 0 stamps this follower's identity
        #: into its FETCH/RAW_FETCH requests so a quorum leader's ISR
        #: tracker observes the fetch positions — membership, eviction
        #: and the quorum high-water mark all derive from them.  None
        #: keeps the legacy anonymous mirror (no ISR participation).
        self.replica_id = replica_id
        #: topology (supervise.Topology duck-type): when given, the
        #: leader connection re-resolves the CURRENT leader address on
        #: every reconnect — a follower survives its leader being
        #: reassigned (add-broker/drain-broker) by simply following the
        #: published cell, cursor intact (offsets are identical across
        #: the pair by contract).
        self._leader = KafkaWireBroker(
            leader, client_id="iotml-replica", topology=topology,
            sasl_username=user, sasl_password=pw,
            replica_id=-1 if replica_id is None else int(replica_id))
        self._topics = topics
        #: topics mirrored with COMPACTED semantics: fetched batches may
        #: carry offset holes (compaction punched out shadowed records),
        #: so a gap is replayed offset-preserving via produce_at instead
        #: of triggering the trimmed-history realignment.  Detected from
        #: the leader's TopicSpec when it carries cleanup_policy (an
        #: in-process leader); the wire Metadata has no config slot, so
        #: wire followers name them here (operator knowledge, exactly
        #: like the retention bound above).
        self._compacted = set(compacted_topics)
        self._groups = list(groups)
        self._interval = poll_interval_s
        self._commit_interval = commit_interval_s
        self._last_commit_sync = float("-inf")  # monotonic domain
        self._last_lag_probe = float("-inf")    # lag-gauge cadence
        self._batch = fetch_batch
        self._stop = threading.Event()
        # pause/resume barrier: pause() parks the background loop
        # BETWEEN rounds (ack'd via _paused), so tests and promote() can
        # drive sync_once()/kill the leader with no concurrent round in
        # flight — the supervised barrier that replaced the old
        # sleep-and-hope race (tests/test_replica.py)
        self._pause = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._parts: Dict[str, int] = {}
        self.sync_errors: list = []
        self.rounds = 0
        self.promoted = False
        #: zero-copy mirror plane (ISSUE 12): RAW_FETCH batches append
        #: verbatim after CRC validation (offsets already stamped).
        #: None = undecided; the first UNSUPPORTED_VERSION from the
        #: leader pins the follower back to the classic per-record leg
        #: permanently (same one-way downgrade as every raw-plane
        #: client).  IOTML_RAW_PRODUCE=off starts pinned classic.
        self._raw_mirror: Optional[bool] = None
        try:
            from ..data.pipeline import raw_produce_mode

            if raw_produce_mode() == "off":
                self._raw_mirror = False
        except ValueError:
            self._raw_mirror = False
        self.raw_mirrored = 0  # records copied over the raw leg

    # -------------------------------------------------------- lifecycle
    @property
    def port(self) -> int:
        return self.server.port

    def start(self) -> "FollowerReplica":
        from ..supervise.registry import register_thread

        self.server.start()
        self._thread = register_thread(threading.Thread(
            target=self._run, daemon=True,
            name=f"iotml-replica-sync-{self.port}"))
        self._thread.start()
        return self

    def pause(self, timeout_s: float = 10.0) -> bool:
        """Park the background sync loop at the round barrier; returns
        once the in-flight round (if any) has finished.  No-op (True)
        when the loop isn't running — synchronous drivers (the chaos
        runner) are their own barrier."""
        self._pause.set()
        if self._thread is None or not self._thread.is_alive():
            return True
        return self._paused.wait(timeout=timeout_s)

    def resume(self) -> None:
        self._pause.clear()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.server.shutdown()
        self.server.server_close()
        try:
            self._leader.close()
        except OSError:
            pass
        self.local.close()  # durable backend releases its fds (no-op else)

    def __enter__(self) -> "FollowerReplica":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------ replication
    def _run(self) -> None:
        # bounded exponential backoff with jitter for reconnect attempts
        # against a dead/dying leader: the fixed `interval * 4` retry
        # busy-spun through a long outage (a chaos blackout scenario
        # turns that into thousands of doomed reconnects), and unjittered
        # retries from a follower fleet re-thundering-herd the leader the
        # instant it returns
        base = max(self._interval * 2, 0.01)  # poll_interval_s=0 is a
        # legal busy-poll; the reconnect path still must not busy-spin
        backoff = ExpBackoff(base_s=base, cap_s=max(2.0, base))
        while not self._stop.is_set():
            if self._pause.is_set():
                # barrier: acknowledge, then park between rounds until
                # resumed or stopped (promote() stops while parked)
                self._paused.set()
                while self._pause.is_set() and not self._stop.is_set():
                    time.sleep(0.005)
                self._paused.clear()
                continue
            try:
                # cadence-throttled mirroring: sync_once(None) lets the
                # round decide — mirror when it copied messages, or when
                # commit_interval_s has elapsed since the last mirror
                moved = self.sync_once(mirror_commits=None)
            except Exception as e:  # noqa: BLE001 - leader may be dying;
                # the follower's job is to keep serving what it has
                self.sync_errors.append(f"{type(e).__name__}: {e}")
                obs_metrics.replica_sync_errors.inc()
                time.sleep(backoff.next_delay())
                continue
            backoff.reset()
            self.rounds += 1
            obs_metrics.replica_sync_rounds.inc()
            # live loss-window gauge at the commit-mirror cadence:
            # lag() costs one ListOffsets per partition, so the poll
            # loop must not pay it per round (idle rounds at
            # poll_interval_s rates), but dashboards need it without
            # anyone calling lag() by hand
            now = time.monotonic()
            if now - self._last_lag_probe >= self._commit_interval:
                self._last_lag_probe = now
                try:
                    self.lag()  # updates iotml_replica_lag_records
                except (OSError, RuntimeError, KeyError):
                    pass  # leader dying: the sync error path owns this
            if not moved:
                time.sleep(self._interval)

    def sync_once(self, mirror_commits: Optional[bool] = True) -> int:
        """One replication round; returns messages copied.  Public so
        tests (and a caught-up barrier) can drive it synchronously —
        direct calls mirror the commit tables unconditionally
        (deterministic); the background loop passes None to apply the
        commit_interval_s cadence instead."""
        act = chaos.point("replica.sync")
        if act is not None and act.kind == "skip":
            return 0  # injected pause: this round replicates nothing
        names = self._topics if self._topics is not None \
            else self._leader.topics()
        copied = 0
        for t in names:
            spec = self._leader.topic(t)
            compacted = t in self._compacted or \
                getattr(spec, "cleanup_policy", "delete") == "compact"
            if t not in self._parts:
                if t not in self.local.topics():
                    self.local.create_topic(
                        t, partitions=spec.partitions,
                        retention_messages=self._retention,
                        cleanup_policy="compact" if compacted
                        else "delete")
                    # late-start bootstrap: align each empty partition to
                    # the leader's earliest retained offset so copied
                    # messages land at IDENTICAL offsets
                    for p in range(spec.partitions):
                        if not self._owns(t, p):
                            continue
                        begin = self._leader.begin_offset(t, p)
                        if begin > 0:
                            self.local.align_base_offset(t, p, begin)
                self._parts[t] = spec.partitions
            for p in range(self._parts[t]):
                if not self._owns(t, p):
                    continue
                while not self._stop.is_set():
                    local_end = self.local.end_offset(t, p)
                    if self._raw_mirror is not False:
                        n, verdict = self._sync_raw(t, p, local_end,
                                                    compacted)
                        copied += n
                        if verdict == "continue":
                            continue
                        if verdict == "break":
                            break
                        # "classic": per-record leg takes this batch
                    try:
                        msgs = self._leader.fetch(t, p, local_end,
                                                  max_messages=self._batch)
                    except OffsetOutOfRangeError as e:
                        # the leader's retention outran replication and
                        # now SAYS so (wire error 1) instead of clamping:
                        # realign to its earliest retained offset
                        begin = max(e.earliest,
                                    self._leader.begin_offset(t, p))
                        if begin <= local_end:
                            break  # raced a concurrent trim; next round
                        self.sync_errors.append(
                            f"trimmed past cursor {t}:{p} "
                            f"{local_end}->{begin}; realigned")
                        self.local.reset_partition(t, p, begin)
                        continue
                    if not msgs:
                        break
                    if compacted:
                        # offset holes here are COMPACTION artifacts,
                        # not trim loss: mirror offset-preserving so the
                        # follower's log carries identical offsets (and
                        # identical holes).  produce_at refuses holes on
                        # an in-memory local (its list is dense) — that
                        # surfaces as a sync error below, never as a
                        # silently renumbered log.
                        try:
                            for m in msgs:
                                self.local.produce_at(
                                    t, p, m.offset, m.value, key=m.key,
                                    timestamp_ms=m.timestamp_ms,
                                    headers=m.headers)
                        except ValueError as e:
                            self.sync_errors.append(
                                f"compacted {t}:{p}: {e}")
                            break
                        copied += len(msgs)
                        continue
                    if msgs[0].offset != local_end:
                        # leader trimmed past our cursor (retention
                        # outran replication): REALIGN — appending at the
                        # local end would shift every later offset and
                        # silently break the offsets-identical contract
                        self.sync_errors.append(
                            f"trimmed past cursor {t}:{p} "
                            f"{local_end}->{msgs[0].offset}; realigned")
                        self.local.reset_partition(t, p, msgs[0].offset)
                    for m in msgs:
                        # headers mirrored too (None over the wire — the
                        # protocol has no header slot; one-to-one for an
                        # in-process leader)
                        self.local.produce(t, m.value, key=m.key,
                                           partition=p,
                                           timestamp_ms=m.timestamp_ms,
                                           headers=m.headers)
                    copied += len(msgs)
        if copied:
            obs_metrics.replica_copied.inc(copied)
        if mirror_commits is None:
            mirror_commits = bool(copied) or (
                time.monotonic() - self._last_commit_sync
                >= self._commit_interval)
        if mirror_commits and self._groups:
            # ONE OffsetFetch round-trip per group covering every
            # mirrored (topic, partition) — not a wire request each
            # commit mirroring is NOT partition-filtered: a coordinator
            # shard's follower inherits the coordinator role on
            # promotion, so it needs the committed offsets of EVERY
            # partition, not just the shard's own (the offsets table is
            # one compacted file either way)
            pairs = [(t, p) for t in list(self._parts)
                     for p in range(self._parts[t])]
            for g in self._groups:
                for (t, p), off in self._leader.committed_many(
                        g, pairs).items():
                    self.local.commit(g, t, p, off)
            self._last_commit_sync = time.monotonic()
        return copied

    def _sync_raw(self, t: str, p: int, local_end: int,
                  compacted: bool):
        """One zero-copy mirror round: RAW_FETCH the leader's frame
        batch, CRC-validate it, and append the in-range bytes VERBATIM
        (offsets already stamped by the leader — identical offsets are
        the failover contract, now also identical bytes).  Returns
        ``(records_copied, verdict)`` with verdict one of ``continue``
        (made progress / realigned — poll again), ``break`` (caught
        up), ``classic`` (this batch takes the per-record leg; a
        NotImplementedError pins the whole follower back)."""
        from ..data.pipeline import raw_batch_bytes
        from ..ops import framing as _fr

        try:
            raw = self._leader.fetch_raw(t, p, local_end,
                                         max_bytes=raw_batch_bytes())
        except NotImplementedError:
            # pre-extension leader: one-way downgrade, like consumers
            self._raw_mirror = False
            return 0, "classic"
        except OffsetOutOfRangeError as e:
            begin = max(e.earliest, self._leader.begin_offset(t, p))
            if begin <= local_end:
                return 0, "break"  # raced a concurrent trim; next round
            self.sync_errors.append(
                f"trimmed past cursor {t}:{p} "
                f"{local_end}->{begin}; realigned")
            self.local.reset_partition(t, p, begin)
            return 0, "continue"
        if raw is None:
            return 0, "break"
        try:
            v = _fr.validate_frame_batch(raw.data,
                                         start_offset=local_end)
        except _fr.CorruptFrameError as e:
            # a corrupt mid-batch frame from the leader: let the
            # classic leg (whose fetch re-reads decoded records) decide
            self.sync_errors.append(f"raw mirror {t}:{p}: {e}")
            return 0, "classic"
        if v["count"] == 0:
            # a NON-empty batch with no complete in-range frame: either
            # torn at the cursor (a record larger than the raw-batch
            # byte cap) or pure alignment slack — the classic
            # per-record leg takes this batch, so an oversized record
            # can never park the mirror forever (the write-side twin of
            # the consume path's torn-at-cursor probe)
            return 0, ("classic" if raw.data else "break")
        if not compacted and v["first"] != local_end:
            # leader trimmed past our cursor (retention outran
            # replication): REALIGN — the PR 6 semantics, unchanged
            self.sync_errors.append(
                f"trimmed past cursor {t}:{p} "
                f"{local_end}->{v['first']}; realigned")
            self.local.reset_partition(t, p, v["first"])
        if compacted and not getattr(self.local, "durable", False) and \
                (v["first"] != local_end or not v["contiguous"]):
            # compaction holes need a durable local (a dense in-memory
            # list cannot hold them): per-record leg, same surface as
            # produce_at's refusal
            return 0, "classic"
        blob = raw.data[v["start_pos"]:v["end_pos"]]
        try:
            self.local.produce_raw_at(t, p, blob)
        except ValueError as e:
            self.sync_errors.append(f"raw mirror {t}:{p}: {e}")
            return 0, "classic"
        self._raw_mirror = True
        self.raw_mirrored += v["count"]
        return v["count"], "continue"

    def lag(self) -> Dict[str, int]:
        """Per-topic messages the leader has that this follower doesn't —
        the loss window if the leader died right now.  Also exported
        live as `iotml_replica_lag_records{topic=...}` (the background
        loop probes at the commit-mirror cadence)."""
        out: Dict[str, int] = {}
        for t, n in self._parts.items():
            out[t] = sum(
                max(0, self._leader.end_offset(t, p)
                    - self.local.end_offset(t, p))
                for p in range(n) if self._owns(t, p))
            obs_metrics.replica_lag.set(out[t], topic=t)
        return out

    # ---------------------------------------------------------- failover
    def promote(self, epoch: int) -> str:
        """Convert this follower into the SERVING LEADER at `epoch`.

        The sequence is fencing-first: (1) barrier — park and stop the
        sync loop so no round is mid-copy while the log changes owner;
        (2) drop the leader client (the old leader is dead or about to
        be fenced); (3) stamp the new epoch into this follower's wire
        server, so epoch-stamped clients are accepted here and a
        resurrected old leader (still at the previous epoch) rejects
        them — split-log protection in both directions.  Returns the
        serving address for the topology publish.

        What stays scoped out (vs the reference's managed clusters):
        re-admitting the old leader as a follower of the new one is an
        operator action; this method only changes who serves."""
        if self.promoted:
            raise RuntimeError("already promoted")
        self._stop.set()
        # close the leader client BEFORE waiting on the loop: a sync
        # round stalled in recv against a wedged (not-dead) leader
        # would otherwise hold the join below open for the full socket
        # timeout; closing makes the round fail fast into the stop check
        try:
            self._leader.close()
        except OSError:
            pass
        self.resume()  # release a parked loop so it can observe _stop
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # REFUSE to serve: a still-running round could append
                # stale leader records after post-failover produces,
                # interleaving old and new writes in the promoted log
                raise RuntimeError(
                    "sync loop did not stop within 10s; refusing to "
                    "promote over a possibly mid-copy log")
        self.server.set_epoch(epoch)
        self.promoted = True
        obs_metrics.failover_epoch.set(epoch)
        for t in self._parts:
            obs_metrics.replica_lag.set(0, topic=t)  # no leader: no lag
        host = self.server.server_address[0]
        return f"{host}:{self.port}"

    def caught_up(self, timeout_s: float = 10.0) -> bool:
        """Block until every mirrored topic's lag is zero (or timeout)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                if all(v == 0 for v in self.lag().values()) and self._parts:
                    return True
            except (OSError, RuntimeError, KeyError):
                pass
            time.sleep(0.05)
        return False
