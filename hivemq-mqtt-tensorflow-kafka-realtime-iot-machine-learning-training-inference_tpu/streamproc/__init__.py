from .tasks import JsonToAvro, RekeyByCar, TumblingCounter, StreamTask  # noqa: F401
