from .tasks import JsonToAvro, RekeyByCar, TumblingCounter, StreamTask  # noqa: F401
from .sql import (SqlEngine, SqlError, REFERENCE_PIPELINE_DDL,  # noqa: F401
                  install_reference_pipeline)
from .server import KsqlServer  # noqa: F401
