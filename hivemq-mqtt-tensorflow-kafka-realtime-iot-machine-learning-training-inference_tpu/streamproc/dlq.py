"""Dead-letter queue for poisoned stream records.

The KSQL-equivalent tasks used to ``continue``-drop undecodable
messages (bad UTF-8, malformed CSV, broken Avro framing, invalid JSON)
— correct for pipeline liveness, but the record vanished without a
trace.  Kafka Connect's answer is the dead-letter-queue topic
(``errors.deadletterqueue.topic.name``); this is the same design for
the in-process engine: every drop site routes the poisoned record to
``<source-topic>_DLQ`` as a JSON envelope carrying everything an
operator needs to replay or diagnose it —

    {"source": topic, "partition": p, "offset": o, "error": "...",
     "task": "JsonToAvro", "trace": "0123abcd…" | null,
     "raw_b64": base64(value), "key_b64": base64(key) | null}

— counted under ``iotml_dlq_total{source=...}`` and browsable with
``python -m iotml.obs dlq``.  Routing failures degrade to the old
drop-and-count behavior: the DLQ must never become a new way for a
poisoned record to halt the pipeline.
"""

from __future__ import annotations

import base64
import json
from typing import Optional

from ..obs import metrics as obs_metrics
from ..obs import tracing

DLQ_SUFFIX = "_DLQ"


def dlq_topic(source_topic: str) -> str:
    return source_topic + DLQ_SUFFIX


def envelope(message, error: str, task: Optional[str] = None) -> bytes:
    """The JSON dead-letter envelope for one poisoned record."""
    ctx = tracing.from_headers(message.headers) if message.headers else None
    doc = {
        "source": message.topic,
        "partition": message.partition,
        "offset": message.offset,
        "error": error,
        "task": task,
        "trace": f"{ctx.trace_id:016x}" if ctx is not None else None,
        "raw_b64": base64.b64encode(message.value or b"").decode(),
        "key_b64": (base64.b64encode(message.key).decode()
                    if message.key is not None else None),
    }
    return json.dumps(doc, sort_keys=True).encode()


def decode_envelope(value: bytes) -> dict:
    """Envelope bytes → dict with `raw` (decoded bytes) added — the
    ``python -m iotml.obs dlq`` peek path.  Raises ValueError for
    anything that isn't an envelope-shaped JSON object (a DLQ topic is
    an open topic; arbitrary bytes may land on it)."""
    doc = json.loads(value)
    if not isinstance(doc, dict):
        raise ValueError(f"DLQ envelope must be a JSON object, got "
                         f"{type(doc).__name__}")
    doc["raw"] = base64.b64decode(doc.get("raw_b64") or "")
    return doc


def route(broker, message, error: str, task: Optional[str] = None) -> bool:
    """Send one poisoned record to its source topic's DLQ.

    Returns True when the dead letter landed; False when routing itself
    failed (counted separately — the caller drops the record exactly as
    it did before DLQs existed, keeping the pipeline alive)."""
    topic = dlq_topic(message.topic)
    try:
        if topic not in broker.topics():
            broker.create_topic(topic)
        broker.produce(topic, envelope(message, error, task=task),
                       key=message.key)
    except Exception:  # noqa: BLE001 - a broken DLQ path must degrade
        # to the pre-DLQ drop, never halt the stream
        obs_metrics.dlq_route_errors.inc()
        return False
    obs_metrics.dlq_total.inc(source=message.topic)
    return True
