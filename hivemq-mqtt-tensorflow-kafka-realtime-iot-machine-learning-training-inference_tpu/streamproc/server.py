"""KSQL-equivalent REST API over `SqlEngine`.

The reference drives its stream preprocessing entirely through the KSQL
server's REST endpoint (`curl -X POST http://ksql:8088/ksql -d '{"ksql":
"CREATE STREAM ..."}'` — reference
`infrastructure/confluent/01_installConfluentPlatform.sh:229-258`), and its
docs verify pipelines with `POST /query` push queries.  This server exposes
the same surface over the native engine:

  POST /ksql         {"ksql": "<stmts>"}  → JSON array, one entry/statement
  POST /query        {"ksql": "SELECT ...|PRINT ..."} → ND-JSON rows
  GET  /info         server metadata
  GET  /healthcheck  {"isHealthy": true}

A background pump thread advances all persistent queries (KSQL's
continuous-query runtime); the interval is the streaming micro-batch cadence.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .sql import SqlEngine, SqlError


class KsqlServer:
    """Threaded HTTP front-end + continuous-query pump for one SqlEngine."""

    def __init__(self, engine: SqlEngine, host: str = "127.0.0.1",
                 port: int = 0, pump_interval_s: float = 0.05):
        self.engine = engine
        self._lock = threading.Lock()  # engine is not thread-safe per se
        self.pump_interval_s = pump_interval_s
        self._stop = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None

        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, obj, content_type="application/json"):
                body = (obj if isinstance(obj, bytes)
                        else json.dumps(obj, default=str).encode())
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b"{}"
                try:
                    return json.loads(raw or b"{}")
                except ValueError:
                    return {}

            def do_GET(self):
                if self.path == "/info":
                    self._reply(200, {"KsqlServerInfo": {
                        "version": "iotml-sql-1.0",
                        "kafkaClusterId": "iotml-broker",
                        "ksqlServiceId": "iotml-ksql"}})
                elif self.path == "/healthcheck":
                    self._reply(200, {"isHealthy": True})
                else:
                    self._reply(404, {"message": "not found"})

            def do_POST(self):
                req = self._body()
                sql = req.get("ksql", req.get("sql", ""))
                if self.path == "/ksql":
                    try:
                        with server._lock:
                            results = server.engine.execute(sql)
                        self._reply(200, results)
                    except SqlError as e:
                        self._reply(400, {"@type": "statement_error",
                                          "message": str(e),
                                          "statementText": sql})
                    except Exception as e:  # engine bug: 500, keep serving
                        self._reply(500, {"@type": "server_error",
                                          "message": f"{type(e).__name__}: {e}",
                                          "statementText": sql})
                elif self.path == "/query":
                    try:
                        with server._lock:
                            results = server.engine.execute(sql)
                        lines = []
                        for res in results:
                            if "rows" in res and "header" in res:
                                lines.append(json.dumps(
                                    {"header": res["header"]}, default=str))
                                lines.extend(json.dumps({"row": r}, default=str)
                                             for r in res["rows"])
                            elif "rows" in res:  # PRINT
                                lines.extend(json.dumps(r, default=str)
                                             for r in res["rows"])
                            else:
                                lines.append(json.dumps(res, default=str))
                        body = ("\n".join(lines) + "\n").encode()
                        self._reply(200, body,
                                    content_type="application/x-ndjson")
                    except SqlError as e:
                        self._reply(400, {"@type": "statement_error",
                                          "message": str(e)})
                    except Exception as e:  # engine bug: 500, keep serving
                        self._reply(500, {"@type": "server_error",
                                          "message": f"{type(e).__name__}: {e}"})
                else:
                    self._reply(404, {"message": "not found"})

            def log_message(self, *a):  # quiet
                pass

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self.httpd.server_address

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        self._pump_thread = threading.Thread(target=self._pump_loop, daemon=True)
        self._pump_thread.start()
        return self

    def _pump_loop(self):
        while not self._stop.wait(self.pump_interval_s):
            try:
                with self._lock:
                    self.engine.pump()
            except Exception:
                # A failing query must not kill the continuous-query runtime
                # for every other query; poisoned rows are dropped upstream,
                # so anything landing here is transient or a bug — keep
                # pumping either way (KSQL keeps its query runtime alive and
                # surfaces errors per-query).
                pass

    def pump_now(self) -> int:
        """Synchronously advance continuous queries (deterministic tests)."""
        with self._lock:
            return self.engine.pump()

    def stop(self):
        self._stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2)
        self.httpd.shutdown()
        self.httpd.server_close()
