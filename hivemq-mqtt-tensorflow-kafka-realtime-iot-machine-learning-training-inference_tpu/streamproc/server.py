"""KSQL-equivalent REST API over `SqlEngine`.

The reference drives its stream preprocessing entirely through the KSQL
server's REST endpoint (`curl -X POST http://ksql:8088/ksql -d '{"ksql":
"CREATE STREAM ..."}'` — reference
`infrastructure/confluent/01_installConfluentPlatform.sh:229-258`), and its
docs verify pipelines with `POST /query` push queries.  This server exposes
the same surface over the native engine:

  POST /ksql         {"ksql": "<stmts>"}  → JSON array, one entry/statement
  POST /query        {"ksql": "SELECT ...|PRINT ..."} → ND-JSON rows
  GET  /info         server metadata
  GET  /healthcheck  {"isHealthy": true}

A background pump thread advances all persistent queries (KSQL's
continuous-query runtime); the interval is the streaming micro-batch cadence.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from ..utils.rest import RestError, RestServer
from .sql import SqlEngine, SqlError


class KsqlServer(RestServer):
    """REST front-end + continuous-query pump for one SqlEngine."""

    def __init__(self, engine: SqlEngine, host: str = "127.0.0.1",
                 port: int = 0, pump_interval_s: float = 0.05):
        super().__init__(host, port, name="iotml-ksql")
        self.engine = engine
        self._lock = threading.Lock()  # engine is not thread-safe per se
        self.pump_interval_s = pump_interval_s
        self._stop = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None

        self.route("GET", r"/info", lambda m, b: (200, {"KsqlServerInfo": {
            "version": "iotml-sql-1.0", "kafkaClusterId": "iotml-broker",
            "ksqlServiceId": "iotml-ksql"}}))
        self.route("GET", r"/healthcheck",
                   lambda m, b: (200, {"isHealthy": True}))
        self.route("POST", r"/ksql", self._ksql)
        self.route("POST", r"/query", self._query)

    @staticmethod
    def _sql_of(body) -> str:
        if isinstance(body, dict):
            return body.get("ksql", body.get("sql", ""))
        if isinstance(body, str):  # bare SQL string body
            return body
        raise RestError(400, "body must be a JSON object with a 'ksql' field")

    def _ksql(self, m, body):
        sql = self._sql_of(body)
        try:
            with self._lock:
                return 200, self.engine.execute(sql)
        except SqlError as e:
            return 400, {"@type": "statement_error", "message": str(e),
                         "statementText": sql}

    def _query(self, m, body):
        sql = self._sql_of(body)
        try:
            with self._lock:
                results = self.engine.execute(sql)
        except SqlError as e:
            return 400, {"@type": "statement_error", "message": str(e)}
        lines = []
        for res in results:
            if "rows" in res and "header" in res:
                lines.append(json.dumps({"header": res["header"]},
                                        default=str))
                lines.extend(json.dumps({"row": r}, default=str)
                             for r in res["rows"])
            elif "rows" in res:  # PRINT
                lines.extend(json.dumps(r, default=str) for r in res["rows"])
            else:
                lines.append(json.dumps(res, default=str))
        body_bytes = ("\n".join(lines) + "\n").encode()
        return 200, body_bytes, "application/x-ndjson"

    # --------------------------------------------------------- lifecycle
    def start(self):
        from ..supervise.registry import register_thread

        super().start()
        self._pump_thread = register_thread(threading.Thread(
            target=self._pump_loop, daemon=True, name="iotml-ksql-pump"))
        self._pump_thread.start()
        return self

    def _pump_loop(self):
        while not self._stop.wait(self.pump_interval_s):
            try:
                with self._lock:
                    self.engine.pump()
            except Exception:
                # A failing query must not kill the continuous-query runtime
                # for every other query; poisoned rows are dropped upstream,
                # so anything landing here is transient or a bug — keep
                # pumping either way (KSQL keeps its query runtime alive and
                # surfaces errors per-query).
                pass

    def pump_now(self) -> int:
        """Synchronously advance continuous queries (deterministic tests)."""
        with self._lock:
            return self.engine.pump()

    def stop(self):
        self._stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2)
        super().stop()
