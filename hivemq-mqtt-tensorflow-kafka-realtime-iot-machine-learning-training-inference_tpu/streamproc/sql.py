"""KSQL-equivalent SQL dialect over the stream engine.

The reference's stream-preprocessing layer is *driven by SQL text* posted to
the KSQL REST API (reference `infrastructure/confluent/01_installConfluentPlatform.sh:229-258`
issues CREATE STREAM / CSAS / CTAS / TERMINATE / DROP statements, and the
docs use `PRINT 'sensor-data' FROM BEGINNING` and `SHOW STREAMS` for
verification, reference `infrastructure/confluent/README.md:99`).  This
module implements that dialect natively over the in-process/wire broker:

  CREATE STREAM s (col TYPE, ...) WITH (KAFKA_TOPIC='t', VALUE_FORMAT='JSON'|'AVRO'|'DELIMITED', KEY='col', PARTITIONS=n);
  CREATE STREAM s2 [WITH (...)] AS SELECT ... FROM s [WHERE e] [PARTITION BY c];
  CREATE TABLE  t  [WITH (...)] AS SELECT c, COUNT(*) AS n FROM s WINDOW TUMBLING (SIZE 5 MINUTES) GROUP BY c;
  SELECT ... FROM s [WHERE e] [LIMIT n];          -- transient (pull) query
  PRINT 'topic' [FROM BEGINNING] [LIMIT n];
  SHOW STREAMS | TABLES | QUERIES | TOPICS;
  DESCRIBE name;
  TERMINATE query_id; | TERMINATE ALL;
  DROP STREAM|TABLE [IF EXISTS] name;

Persistent queries (CSAS/CTAS) run as offset-cursored `StreamTask`s — call
`SqlEngine.pump()` (or run the REST server's pump thread) to advance them,
mirroring KSQL's continuous queries.  Avro output is Confluent-framed with
a real schema id from the attached `SchemaRegistry`, so downstream consumers
(the ML ingest layer) read it exactly as they read reference topics.
"""

from __future__ import annotations

import contextlib
import json
import re
from collections import Counter
from struct import error as struct_error
from typing import Callable, Dict, List, Optional, Tuple

from ..core.schema import WRITER_SCHEMAS, Field, RecordSchema
from ..ops.avro import (AvroCodec, needs_resolution, resolve_record,
                        zigzag_encode)
from ..ops.framing import frame, unframe
from ..stream.broker import Broker, Message, OffsetOutOfRangeError
from ..stream.registry import SchemaRegistry, subject_for_topic
from .tasks import StreamTask

# ---------------------------------------------------------------- tokenizer

_TOKEN_RE = re.compile(
    r"\s*(?:"
    r"('(?:[^']|'')*')"                      # single-quoted string
    r"|([A-Za-z_][A-Za-z0-9_]*)"             # identifier / keyword
    r"|(\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+|\d+(?:[eE][+-]?\d+)?)"  # number
    r"|(<>|<=|>=|!=|[(),*+\-/<>=;])"         # operator / punctuation
    r")"
)

# value formats the engine can encode/decode; enforced for base-stream DDL
# and CSAS/CTAS alike so an unsupported format 4xxes at CREATE time
_SUPPORTED_VALUE_FORMATS = ("JSON", "AVRO", "DELIMITED")

_KSQL_TO_AVRO = {
    "STRING": "string", "VARCHAR": "string",
    "DOUBLE": "double", "FLOAT": "double",
    "INTEGER": "int", "INT": "int",
    "BIGINT": "long", "BOOLEAN": "boolean",
}
_AVRO_TO_KSQL = {"string": "STRING", "double": "DOUBLE", "int": "INTEGER",
                 "long": "BIGINT", "boolean": "BOOLEAN", "float": "DOUBLE"}


class SqlError(ValueError):
    """Statement failed to parse or execute (KSQL's 4xx error body)."""


def tokenize(text: str) -> List[str]:
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                break
            raise SqlError(f"cannot tokenize at: {text[pos:pos+30]!r}")
        pos = m.end()
        tok = m.group(0).strip()
        if tok:
            out.append(tok)
    return out


def split_statements(text: str) -> List[str]:
    """Split on ';' outside single-quoted strings."""
    out, cur, in_q = [], [], False
    for ch in text:
        if ch == "'":
            in_q = not in_q
        if ch == ";" and not in_q:
            stmt = "".join(cur).strip()
            if stmt:
                out.append(stmt)
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return out


class _Toks:
    """Cursor over a token list with case-insensitive keyword matching."""

    def __init__(self, toks: List[str]):
        self.toks = toks
        self.i = 0

    def peek(self, ahead: int = 0) -> Optional[str]:
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> str:
        if self.i >= len(self.toks):
            raise SqlError("unexpected end of statement")
        tok = self.toks[self.i]
        self.i += 1
        return tok

    def accept(self, *kw: str) -> bool:
        """Consume the next len(kw) tokens if they match (case-insensitive)."""
        for k, off in zip(kw, range(len(kw))):
            t = self.peek(off)
            if t is None or t.upper() != k:
                return False
        self.i += len(kw)
        return True

    def expect(self, *kw: str):
        if not self.accept(*kw):
            raise SqlError(f"expected {' '.join(kw)} near "
                           f"{' '.join(self.toks[self.i:self.i+4])!r}")

    def ident(self) -> str:
        tok = self.next()
        if not re.match(r"^[A-Za-z_][A-Za-z0-9_]*$", tok):
            raise SqlError(f"expected identifier, got {tok!r}")
        return tok.upper()

    def string(self) -> str:
        tok = self.next()
        if not (tok.startswith("'") and tok.endswith("'")):
            raise SqlError(f"expected string literal, got {tok!r}")
        return tok[1:-1].replace("''", "'")

    def done(self) -> bool:
        return self.i >= len(self.toks)


# ------------------------------------------------------------- expressions

_SCALARS: Dict[str, Callable] = {
    "ABS": abs,
    "ROUND": round,
    "FLOOR": lambda v: float(int(v // 1)),
    "CEIL": lambda v: float(-(-v // 1)),
    "UCASE": lambda s: str(s).upper(),
    "LCASE": lambda s: str(s).lower(),
    "LEN": lambda s: len(str(s)),
}

_AGGS = ("COUNT", "SUM", "MIN", "MAX", "AVG")


def _parse_expr(t: _Toks) -> Callable[[dict], object]:
    """Recursive-descent expression → closure(record)->value.

    Records are dicts keyed by upper-case column name plus the KSQL
    pseudo-columns ROWKEY (str) and ROWTIME (epoch ms).
    """
    return _parse_or(t)


def _parse_or(t: _Toks):
    left = _parse_and(t)
    while t.accept("OR"):
        right = _parse_and(t)
        left = (lambda l, r: lambda rec: bool(l(rec)) or bool(r(rec)))(left, right)
    return left


def _parse_and(t: _Toks):
    left = _parse_not(t)
    while t.accept("AND"):
        right = _parse_not(t)
        left = (lambda l, r: lambda rec: bool(l(rec)) and bool(r(rec)))(left, right)
    return left


def _parse_not(t: _Toks):
    if t.accept("NOT"):
        inner = _parse_not(t)
        return lambda rec: not bool(inner(rec))
    return _parse_cmp(t)


def _parse_cmp(t: _Toks):
    left = _parse_add(t)
    op = t.peek()
    if op in ("=", "!=", "<>", "<", "<=", ">", ">="):
        t.next()
        right = _parse_add(t)
        fns = {
            "=": lambda a, b: a == b, "!=": lambda a, b: a != b,
            "<>": lambda a, b: a != b, "<": lambda a, b: a < b,
            "<=": lambda a, b: a <= b, ">": lambda a, b: a > b,
            ">=": lambda a, b: a >= b,
        }
        f = fns[op]
        return (lambda l, r: lambda rec: f(l(rec), r(rec)))(left, right)
    if t.accept("IS", "NOT", "NULL"):
        return (lambda l: lambda rec: l(rec) is not None)(left)
    if t.accept("IS", "NULL"):
        return (lambda l: lambda rec: l(rec) is None)(left)
    return left


def _parse_add(t: _Toks):
    left = _parse_mul(t)
    while t.peek() in ("+", "-"):
        op = t.next()
        right = _parse_mul(t)
        if op == "+":
            left = (lambda l, r: lambda rec: l(rec) + r(rec))(left, right)
        else:
            left = (lambda l, r: lambda rec: l(rec) - r(rec))(left, right)
    return left


def _parse_mul(t: _Toks):
    left = _parse_unary(t)
    while t.peek() in ("*", "/"):
        # `*` only acts as multiplication when followed by an operand —
        # in select lists it is the wildcard and never reaches here.
        op = t.next()
        right = _parse_unary(t)
        if op == "*":
            left = (lambda l, r: lambda rec: l(rec) * r(rec))(left, right)
        else:
            left = (lambda l, r: lambda rec: l(rec) / r(rec))(left, right)
    return left


def _parse_unary(t: _Toks):
    if t.peek() == "-":
        t.next()
        inner = _parse_unary(t)
        return lambda rec: -inner(rec)
    return _parse_primary(t)


def _parse_primary(t: _Toks):
    tok = t.peek()
    if tok is None:
        raise SqlError("unexpected end of expression")
    if tok == "(":
        t.next()
        inner = _parse_expr(t)
        t.expect(")")
        return inner
    if tok.startswith("'"):
        s = t.string()
        return lambda rec: s
    if re.match(r"^[\d.]", tok):
        t.next()
        num = float(tok)
        if num.is_integer() and "." not in tok and "e" not in tok.lower():
            num = int(num)
        return lambda rec: num
    up = tok.upper()
    if up in ("TRUE", "FALSE"):
        t.next()
        val = up == "TRUE"
        return lambda rec: val
    if up == "NULL":
        t.next()
        return lambda rec: None
    if up in _SCALARS and t.peek(1) == "(":
        t.next()
        t.expect("(")
        inner = _parse_expr(t)
        t.expect(")")
        f = _SCALARS[up]
        return (lambda g: lambda rec: None if g(rec) is None else f(g(rec)))(inner)
    # column reference
    name = t.ident()
    return lambda rec: rec.get(name)


# ------------------------------------------------------------- select AST


class SelectItem:
    """One projection: expression + output alias (+ aggregate marker)."""

    def __init__(self, alias: str, fn: Callable = None,
                 agg: Optional[str] = None, agg_arg: Optional[Callable] = None,
                 source_col: Optional[str] = None, star: bool = False):
        self.alias = alias
        self.fn = fn
        self.agg = agg          # COUNT/SUM/MIN/MAX/AVG or None
        self.agg_arg = agg_arg  # argument closure for SUM/MIN/MAX/AVG
        self.source_col = source_col  # set when the expr is a bare column ref
        self.star = star


class SelectStmt:
    def __init__(self):
        self.items: List[SelectItem] = []
        self.source: str = ""
        self.where: Optional[Callable] = None
        self.window_ms: Optional[int] = None
        self.group_by: Optional[str] = None
        self.partition_by: Optional[str] = None
        self.limit: Optional[int] = None
        self.emit_changes: bool = False

    @property
    def is_aggregate(self) -> bool:
        return any(it.agg for it in self.items) or self.group_by is not None


def _parse_select_item(t: _Toks) -> SelectItem:
    tok = t.peek()
    if tok == "*":
        t.next()
        return SelectItem(alias="*", star=True)
    up = tok.upper() if tok else ""
    if up in _AGGS and t.peek(1) == "(":
        t.next()
        t.expect("(")
        if up == "COUNT" and t.peek() == "*":
            t.next()
            arg = None
        else:
            arg = _parse_expr(t)
        t.expect(")")
        alias = f"KSQL_{up}"
        if t.accept("AS"):
            alias = t.ident()
        return SelectItem(alias=alias, agg=up, agg_arg=arg)
    # remember position to detect bare column refs (for schema inference)
    start = t.i
    fn = _parse_expr(t)
    consumed = t.toks[start:t.i]
    source_col = consumed[0].upper() if len(consumed) == 1 and re.match(
        r"^[A-Za-z_][A-Za-z0-9_]*$", consumed[0]) else None
    alias = source_col or "EXPR"
    if t.accept("AS"):
        alias = t.ident()
    return SelectItem(alias=alias, fn=fn, source_col=source_col)


_WINDOW_UNITS = {"MILLISECONDS": 1, "SECONDS": 1000, "SECOND": 1000,
                 "MINUTES": 60_000, "MINUTE": 60_000,
                 "HOURS": 3_600_000, "HOUR": 3_600_000,
                 "DAYS": 86_400_000, "DAY": 86_400_000}


def _parse_select(t: _Toks) -> SelectStmt:
    st = SelectStmt()
    t.expect("SELECT")
    while True:
        it = _parse_select_item(t)
        if it.alias == "EXPR":  # unaliased expression: KSQL's auto-naming
            it.alias = f"KSQL_COL_{len(st.items)}"
        st.items.append(it)
        if not t.accept(","):
            break
    t.expect("FROM")
    st.source = t.ident()
    if t.accept("WINDOW", "TUMBLING"):
        t.expect("(")
        t.expect("SIZE")
        n = t.next()
        unit = t.ident()
        if unit not in _WINDOW_UNITS:
            raise SqlError(f"unknown window unit {unit}")
        st.window_ms = int(float(n) * _WINDOW_UNITS[unit])
        t.expect(")")
    if t.accept("WHERE"):
        st.where = _parse_expr(t)
    if t.accept("GROUP", "BY"):
        st.group_by = t.ident()
    if t.accept("PARTITION", "BY"):
        st.partition_by = t.ident()
    if t.accept("EMIT", "CHANGES"):
        st.emit_changes = True
    if t.accept("LIMIT"):
        st.limit = int(t.next())
    return st


# --------------------------------------------------------------- metadata


class SourceMeta:
    """A registered STREAM or TABLE: name + topic + format + columns."""

    def __init__(self, name: str, kind: str, topic: str, value_format: str,
                 columns: List[Tuple[str, str]], key_col: Optional[str] = None,
                 query_id: Optional[str] = None, windowed: bool = False):
        self.name = name
        self.kind = kind                  # "STREAM" | "TABLE"
        self.topic = topic
        self.value_format = value_format  # "JSON" | "AVRO" | "DELIMITED"
        self.columns = columns            # [(NAME, KSQL_TYPE)]
        self.key_col = key_col
        self.query_id = query_id
        self.windowed = windowed

    def record_schema(self) -> RecordSchema:
        fields = tuple(Field(n, _KSQL_TO_AVRO[k], nullable=True)
                       for n, k in self.columns)
        return RecordSchema(name=self.name, namespace="iotml.sql", fields=fields)

    def describe(self) -> dict:
        return {"name": self.name, "type": self.kind, "topic": self.topic,
                "valueFormat": self.value_format, "keyColumn": self.key_col,
                "fields": [{"name": n, "type": k} for n, k in self.columns]}


class _NativeAvroSource:
    """Batch AVRO source decode through the C++ engine.

    The pure-python decoder dominates REKEY/CTAS cost; this decodes a whole
    poll columnar-natively and rebuilds records with exact python types
    (ints stay ints, booleans stay bools).  Conservative fallbacks keep
    python-decode semantics authoritative — the whole batch takes the
    per-message python path when: the native decode errors, any nullable
    union chose its null branch (python decodes those as None; the
    columnar layout cannot represent that), any string sits at the stride
    limit (possible truncation) or is not valid ASCII/UTF-8 for numpy's
    U-cast, or any int/long exceeds the float64-exact range (2^53), or
    any message lacks the Confluent magic byte (the python path's
    unframe() treats those as poisoned).  Known narrow divergence: a
    string with TRAILING NUL bytes decodes natively with them stripped
    (numpy S-dtype semantics) — undetectable post-decode and accepted;
    embedded NULs round-trip."""

    STRIDE = 64
    INT_EXACT = 2 ** 53

    def __init__(self, schema):
        from ..stream.native import NativeCodec

        self.codec = NativeCodec(schema)  # version-gated: bitmap guaranteed

        def conv_for(avro_type):
            if avro_type in ("int", "long"):
                return int
            if avro_type == "boolean":
                return bool
            return float
        self.numeric = [(f.name, conv_for(f.avro_type))
                        for f in schema.fields if f.avro_type != "string"]
        # columns needing the 2^53 exactness guard (float64 round-trip)
        self._int_cols = [i for i, (_, conv) in enumerate(self.numeric)
                          if conv is int]
        self.strings = [f.name for f in schema.fields
                        if f.avro_type == "string"]

    def decode(self, messages) -> Optional[list]:
        """→ list[dict] for the whole batch, or None → caller falls back."""
        import numpy as np

        if any(m.value[:1] != b"\x00" for m in messages):
            # python-path parity: unframe() rejects a non-zero magic byte
            # as poisoned; a blind 5-byte strip would decode it instead
            return None
        try:
            num, lab, nulls = self.codec.decode_batch_nulls(
                [m.value for m in messages], strip=5, stride=self.STRIDE)
            if nulls.any():
                # null unions decode as None only on the python path
                return None
            if self._int_cols and (
                    np.abs(num[:, self._int_cols]) >= self.INT_EXACT).any():
                return None  # int/long beyond float64-exact range
            num_l = num.tolist()
            if self.strings:
                lab_u = lab.astype("U")  # raises on non-ASCII bytes
                if (np.char.str_len(lab_u) >= self.STRIDE - 1).any():
                    return None  # possible truncation at the stride limit
                lab_l = lab_u.tolist()
            else:
                lab_l = None
        except (ValueError, TypeError, RuntimeError, UnicodeDecodeError):
            return None
        recs = []
        for i, m in enumerate(messages):
            rec = {}
            for (name, conv), v in zip(self.numeric, num_l[i]):
                rec[name] = conv(v)
            if lab_l is not None:
                for name, v in zip(self.strings, lab_l[i]):
                    rec[name] = v
            rec["ROWKEY"] = (m.key or b"").decode(errors="replace")
            rec["ROWTIME"] = m.timestamp_ms
            recs.append(rec)
        return recs


def _make_native_source(meta: SourceMeta):
    if meta.value_format != "AVRO":
        return None
    try:
        return _NativeAvroSource(meta.record_schema())
    except Exception:
        return None


def _decode_batch(meta: SourceMeta, codec: Optional[AvroCodec],
                  native: Optional[_NativeAvroSource],
                  messages) -> list:
    """→ list[Optional[dict]] aligned with messages (None = poisoned)."""
    if native is not None and \
            not any(needs_resolution(m.value) for m in messages):
        # a newer-writer record in the batch forces the python path:
        # the native decoder is positional against ONE schema and would
        # silently mis-read an evolved payload, not error on it
        recs = native.decode(messages)
        if recs is not None:
            return recs
    return [_decode_record(meta, codec, m) for m in messages]


#: writer codecs for the resolving AVRO decode, built on first use
_WRITER_CODECS: Dict[int, AvroCodec] = {}


def _resolving_decode(sid: int, payload: bytes,
                      codec: AvroCodec) -> Optional[dict]:
    """Schema-evolution decode: when the frame names a KNOWN newer
    writer whose field space covers this source's reader columns,
    decode with the WRITER's layout and project by name onto the
    reader (Avro schema resolution).  Returns None when not applicable
    — an id collision from an unrelated registry subject, or a reader
    the writer cannot satisfy — so the caller keeps the legacy
    positional decode (and its DLQ failure mode) for those."""
    # id 1 is the DEFAULT frame id — every in-process registry subject
    # (arbitrary SQL-declared schemas included) starts there, so it
    # identifies nothing; only the non-default KNOWN writer ids mark an
    # evolved car-schema payload
    if sid == 1:
        return None
    ws = WRITER_SCHEMAS.get(sid)
    if ws is None or ws.fields == codec.schema.fields:
        return None
    writer_names = {f.name for f in ws.fields}
    if any(f.name not in writer_names and not f.nullable
           for f in codec.schema.fields):
        return None
    wcodec = _WRITER_CODECS.get(sid)
    if wcodec is None:
        wcodec = _WRITER_CODECS[sid] = AvroCodec(ws)
    return resolve_record(wcodec.decode(payload), codec.schema)


def _decode_record(meta: SourceMeta, codec: Optional[AvroCodec],
                   m: Message) -> Optional[dict]:
    """Message → dict keyed by upper-case column name (+ pseudo-columns)."""
    rec: Optional[dict] = None
    if meta.value_format == "JSON":
        try:
            obj = json.loads(m.value)
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(obj, dict):
            return None
        rec = {k.upper(): v for k, v in obj.items()}
    elif meta.value_format == "AVRO":
        try:
            sid, payload = unframe(m.value)
            # mixed-version topic: a record written under a newer known
            # schema resolves against this source's reader instead of
            # mis-decoding positionally (and failing the chunk into the
            # DLQ — or worse, silently reading the wrong field)
            rec = _resolving_decode(sid, payload, codec)
            if rec is None:
                rec = codec.decode(payload)
        except (ValueError, IndexError, struct_error):
            return None
    elif meta.value_format == "DELIMITED":
        try:
            parts = m.value.decode().split(",")
        except UnicodeDecodeError:
            return None
        if len(parts) != len(meta.columns):
            return None
        rec = {}
        try:
            for (name, ktype), raw in zip(meta.columns, parts):
                if ktype in ("DOUBLE", "FLOAT"):
                    rec[name] = float(raw)
                elif ktype in ("INTEGER", "INT", "BIGINT"):
                    rec[name] = int(float(raw))
                elif ktype == "BOOLEAN":
                    rec[name] = raw.strip().lower() == "true"
                else:
                    rec[name] = raw
        except ValueError:
            return None
    else:  # pragma: no cover - formats are validated at CREATE time
        return None
    rec["ROWKEY"] = (m.key or b"").decode(errors="replace")
    rec["ROWTIME"] = m.timestamp_ms
    return rec


# ------------------------------------------------------------------ tasks


class SqlSelectTask(StreamTask):
    """A persistent CSAS query: decode → where → project → encode."""

    def __init__(self, broker: Broker, src_meta: SourceMeta,
                 sink_meta: SourceMeta, stmt: SelectStmt,
                 registry: SchemaRegistry, group: str,
                 trusted_passthrough: bool = False,
                 passthrough_sample: int = 0):
        super().__init__(broker, src_meta.topic, sink_meta.topic,
                         partitions=broker.topic(sink_meta.topic).partitions
                         if sink_meta.topic in broker.topics() else 1,
                         group=group)
        self.src_meta = src_meta
        self.sink_meta = sink_meta
        self.stmt = stmt
        self.src_codec = (AvroCodec(src_meta.record_schema())
                          if src_meta.value_format == "AVRO" else None)
        self._native_src = _make_native_source(src_meta)
        self.sink_codec = None
        self.sink_schema_id = None
        self._native_sink = None
        if sink_meta.value_format == "AVRO":
            schema = sink_meta.record_schema()
            self.sink_codec = AvroCodec(schema)
            self.sink_schema_id = registry.register(
                subject_for_topic(sink_meta.topic), schema.avro_json())
            # native batch encode (C++ engine): the pure-python zigzag
            # encoder dominates CSAS cost; byte-identical per
            # tests/test_sql.py::test_csas_native_encode_byte_parity
            try:
                from ..stream.native import NativeCodec

                self._native_sink = NativeCodec(schema)
                self._label_stride = _NativeAvroSource.STRIDE
                self._sink_numeric = [f.name for f in schema.fields
                                      if f.avro_type != "string"]
                self._sink_strings = [f.name for f in schema.fields
                                      if f.avro_type == "string"]
                self._sink_ints = [f.name for f in schema.fields
                                   if f.avro_type in ("int", "long")]
            except Exception:
                self._native_sink = None
        # ---- fused JSON→AVRO leg (the pipeline's input stage): when the
        # query is a bare star copy (SELECT * FROM <json-stream>, no WHERE,
        # no PARTITION BY — reference 01_installConfluentPlatform.sh's
        # SENSOR_DATA_S_AVRO CSAS), the C++ JSON parser emits straight into
        # the sink's columnar layout and the C++ Avro encoder takes it from
        # there: zero per-row Python on the eligible rows, byte-identical
        # output, row-level fallback for anything the parser can't
        # reproduce exactly.
        self._fused_json = None
        if (src_meta.value_format == "JSON"
                and self._native_sink is not None
                and stmt.where is None and not stmt.partition_by
                and len(stmt.items) == 1 and stmt.items[0].star
                and sink_meta.columns == src_meta.columns
                and len(sink_meta.columns) <= 64):
            self._fused_json = self._native_sink
        # ---- REKEY pass-through (SELECT ROWKEY AS X, * ... PARTITION BY
        # X over AVRO→AVRO): the sink record is the ROWKEY string field
        # followed by the source fields unchanged, and Avro encodes a
        # record as the concatenation of its field encodings — so the
        # output value is frame(avro_string(key) + source_payload) with no
        # decode/encode at all.  The source payload is still structurally
        # validated in batch (native decode); a batch that fails
        # validation, any non-framed value, or a non-UTF-8 key falls back
        # to the generic path wholesale.
        self._rekey_fast = bool(
            src_meta.value_format == "AVRO"
            and sink_meta.value_format == "AVRO"
            and self._native_src is not None
            and self.sink_schema_id is not None
            and stmt.where is None and stmt.partition_by
            and len(stmt.items) == 2
            and stmt.items[0].source_col == "ROWKEY"
            and stmt.items[0].alias == stmt.partition_by
            and stmt.items[1].star
            and sink_meta.columns[:1] == [(stmt.items[0].alias, "STRING")]
            and sink_meta.columns[1:] == list(src_meta.columns))
        if self._rekey_fast:
            # constant per task: frame header, plus the non-null union
            # branch (zigzag 1 = 0x02) when the sink key column is nullable
            self._rekey_header = frame(b"", self.sink_schema_id)
            if sink_meta.record_schema().fields[0].nullable:
                self._rekey_header += b"\x02"
        #: trusted pass-through (engine-level opt-in): skip the strict
        #: structural re-validation of rekey source payloads.  Sound only
        #: when the source topic is written exclusively by THIS engine's
        #: own native encoder (the reference pipeline's AVRO leg feeding
        #: its REKEY leg): those bytes were validated at encode time, and
        #: re-decoding every record was the rekey pump's dominant cost.
        #: External/untrusted source topics must keep validation on.
        self._trusted = bool(trusted_passthrough)
        #: sample-validation cadence under trust (engine-level knob):
        #: every Nth pass-through batch is strict-validated anyway, so a
        #: regression in the engine's own encoder surfaces within N
        #: batches instead of reaching downstream consumers silently
        self._sample_every = max(int(passthrough_sample), 0)
        self._passthrough_batches = 0
        #: partition-affinity verdict for the raw produce leg (None =
        #: not yet checked; see process_raw)
        self._raw_affine = None

    def _project(self, rec: dict) -> Optional[dict]:
        out = {}
        for it in self.stmt.items:
            if it.star:
                for name, _ in self.src_meta.columns:
                    out[name] = rec.get(name)
            else:
                try:
                    out[it.alias] = it.fn(rec)
                except (TypeError, ZeroDivisionError):
                    return None  # NULL in arithmetic / div-by-zero: drop row
        return out

    def _encode_avro_rows(self, rows):
        """rows → framed Avro values; native columnar batch when eligible.

        Eligibility is value-dependent: no None values (the python codec's
        null-union branch) and every string short enough for the native
        engine's fixed label stride.  Ineligible batches take the python
        codec row-by-row — output bytes are identical either way."""
        if self._native_sink is not None and rows:
            import numpy as np

            # strings checked BEFORE building the S-dtype array (it would
            # silently truncate long values rather than fail)
            # NUL-free: the C++ encoder measures strings to the first NUL
            ok = all(isinstance(row.get(n), str)
                     and len(row[n]) < self._label_stride
                     and "\x00" not in row[n]
                     for row in rows for n in self._sink_strings)
            if ok and self._sink_ints:
                # int/long ride a float64 matrix: beyond 2^53 the round
                # trip is lossy — python codec keeps exactness
                lim = _NativeAvroSource.INT_EXACT
                ok = all(isinstance(row.get(n), (int, float))
                         and abs(row[n]) < lim
                         for row in rows for n in self._sink_ints)
            if ok:
                try:
                    num = np.array(
                        [[row[n] for n in self._sink_numeric]
                         for row in rows], np.float64)
                    labels = np.array(
                        [[row[n] for n in self._sink_strings]
                         for row in rows],
                        dtype=f"S{self._label_stride}") if \
                        self._sink_strings else None
                    return self._native_sink.encode_batch(
                        num, labels, schema_id=self.sink_schema_id,
                        stride=self._label_stride)
                except (TypeError, ValueError, KeyError):
                    pass  # None/odd values: python codec handles the unions
        return [frame(self.sink_codec.encode(
            {n: row.get(n) for n, _ in self.sink_meta.columns}),
            self.sink_schema_id) for row in rows]

    def _process_fused_json(self, messages):
        """JSON→AVRO star copy, native end to end (see __init__)."""
        import numpy as np

        num, lab, nulls, fb = self._fused_json.json_decode_batch(
            [m.value for m in messages], stride=self._label_stride)
        ok = fb == 0
        encoded = []
        if ok.any():
            idx = np.nonzero(ok)[0]
            encoded = self._native_sink.encode_batch(
                num[idx], lab[idx] if self._sink_strings else None,
                schema_id=self.sink_schema_id, stride=self._label_stride,
                nulls=nulls[idx])
        out = []
        enc_i = 0
        for i, m in enumerate(messages):
            if ok[i]:
                out.append((m.key, encoded[enc_i], m.timestamp_ms))
                enc_i += 1
            else:
                # row-level fallback: the Python leg decides (poisoned
                # rows dead-letter; nulls/escapes/big ints encode exactly)
                rec = _decode_record(self.src_meta, self.src_codec, m)
                if rec is None:
                    self.dead_letter(m, "undecodable "
                                     f"{self.src_meta.value_format} record")
                    continue
                row = self._project(rec)
                if row is None:
                    continue
                val = frame(self.sink_codec.encode(
                    {n: row.get(n) for n, _ in self.sink_meta.columns}),
                    self.sink_schema_id)
                out.append((m.key, val, m.timestamp_ms))
        return out

    def _process_rekey(self, messages):
        """AVRO rekey pass-through (see __init__); None → generic path."""
        vals = []
        for m in messages:
            if not m.value or m.value[0] != 0:
                return None  # poisoned frame: generic path drops it
            vals.append(m.value)
        self._passthrough_batches += 1
        sampled = (self._trusted and self._sample_every
                   and self._passthrough_batches % self._sample_every == 0)
        if not self._trusted or sampled:
            try:
                # strict validation — the bytes pass through, so success
                # must guarantee forwarding the ORIGINAL payload is
                # byte-identical to decode→re-encode (no trailing bytes,
                # minimal varints, valid UTF-8, sane union branches);
                # anything else sends the whole batch to the generic path,
                # which drops/canonicalizes exactly the bad rows.  Skipped
                # under trusted_passthrough — except for the 1-in-N
                # sampled batches (passthrough_sample), which re-check
                # the engine's own encoder output as defense in depth.
                self._native_src.codec.decode_batch(
                    vals, strip=5, stride=_NativeAvroSource.STRIDE,
                    strict=True)
            except (ValueError, TypeError, RuntimeError):
                return None
        header = self._rekey_header
        out = []
        for m in messages:
            key = m.key or b""
            try:
                key.decode()
            except UnicodeDecodeError:
                return None  # replacement-char key: Python path is exact
            # avro string: zigzag-varint byte length, then the utf-8 bytes
            out.append((key,
                        header + zigzag_encode(len(key)) + key + m.value[5:],
                        m.timestamp_ms))
        return out

    def process_raw(self, messages):
        """Zero-copy produce leg of the fused JSON→AVRO star copy
        (ISSUE 12): the C++ JSON parser fills columnar buffers, the C++
        frame encoder emits a ready-to-append raw frame batch (Avro
        encoded AND framed in ONE native call — a record is framed once
        at conversion and never re-serialised), and RAW_PRODUCE appends
        it segment-verbatim.  Partition AFFINITY makes this sound: the
        star copy preserves the message key, so the sink's key-hash
        partition equals the source partition whenever the partition
        counts match (the bridge hashed the same key with the same
        function) — each source chunk lands on the same-numbered sink
        partition, byte- and routing-identical to the classic path.

        Chunks that cannot ride (no fused leg, pinned-classic producer,
        partition counts differ, a fallback row in the group, traced
        session) return None and take the classic path unchanged."""
        if self._fused_json is None or self.sink_schema_id is None:
            return None
        from ..stream.broker import Broker as _InprocBroker

        if isinstance(self.broker, _InprocBroker) and \
                self.broker.store is None:
            # in-memory in-process broker: produce_raw would only decode
            # the frames right back per record (the emulator's compat
            # path) — strictly extra work vs the classic fused encode
            # (the same opt-out NativeIngestBridge applies)
            return None
        raw = self.raw_producer()
        if raw.engaged is False:
            return None
        if self._raw_affine is None:
            try:
                self._raw_affine = (
                    self.broker.topic(self.sink_meta.topic).partitions
                    == self.broker.topic(self.src_meta.topic).partitions)
            except KeyError:
                return None
        if not self._raw_affine:
            return None
        import time as _time

        import numpy as np

        from ..data.pipeline import produce_batch_bytes
        from ..stream.producer import raw_produce_convert_seconds

        def classic_group(group) -> int:
            """One group through the classic path (exact per-key order,
            DLQ routing, key-hash partitioning) — every fallback site."""
            outs = self.process(group)
            if outs:
                self.broker.produce_many(self.sink_meta.topic, outs)
            return len(outs)

        def classic_entries(group, num, lab, nulls):
            """Lazy classic form of an encoded slice — built only when
            the producer downgrades (UNSUPPORTED_VERSION server)."""
            vals = self._native_sink.encode_batch(
                num, lab if self._sink_strings else None,
                schema_id=self.sink_schema_id,
                stride=self._label_stride, nulls=nulls)
            return [(m.key, v, m.timestamp_ms)
                    for m, v in zip(group, vals)]

        emitted = 0
        by_part: Dict[int, list] = {}
        for m in messages:
            by_part.setdefault(m.partition, []).append(m)
        for p, group in by_part.items():
            _t0 = _time.perf_counter()
            num, lab, nulls, fb = self._fused_json.json_decode_batch(
                [m.value for m in group], stride=self._label_stride)
            if fb.any():
                # a row the native parser can't reproduce exactly:
                # classic path for the WHOLE group
                emitted += classic_group(group)
                continue
            ts = np.fromiter((m.timestamp_ms for m in group), np.int64,
                             len(group))
            keys = [m.key for m in group]
            if any(k is None for k in keys):
                # unkeyed records round-robin in the classic
                # partitioner; only KEYED records carry the affinity
                # identity — classic path for the whole group
                emitted += classic_group(group)
                continue
            try:
                blob = self._fused_json.encode_frames(
                    num, lab, ts, keys=keys, nulls=nulls,
                    schema_id=self.sink_schema_id,
                    stride=self._label_stride)
            except ValueError:
                emitted += classic_group(group)
                continue
            raw_produce_convert_seconds.observe(
                _time.perf_counter() - _t0)
            cap = produce_batch_bytes()
            if len(blob) <= cap or len(group) <= 1:
                raw.produce_frames(
                    p, blob, len(group),
                    entries=lambda g=group, n=num, la=lab, nu=nulls:
                    classic_entries(g, n, la, nu))
            else:
                # oversize accumulation: split at frame boundaries by
                # re-encoding row slices (IOTML_PRODUCE_BATCH_BYTES)
                per = max(1, int(len(group) * cap / len(blob)))
                for i in range(0, len(group), per):
                    sl = slice(i, i + per)
                    sub = self._fused_json.encode_frames(
                        num[sl], lab[sl], ts[sl], keys=keys[sl],
                        nulls=nulls[sl], schema_id=self.sink_schema_id,
                        stride=self._label_stride)
                    raw.produce_frames(
                        p, sub, len(keys[sl]),
                        entries=lambda g=group[sl], n=num[sl],
                        la=lab[sl], nu=nulls[sl]: classic_entries(
                            g, n, la, nu))
            emitted += len(group)
        return emitted

    def process(self, messages):
        if self._fused_json is not None:
            return self._process_fused_json(messages)
        if self._rekey_fast:
            fast = self._process_rekey(messages)
            if fast is not None:
                return fast
        picked = []  # (key, row, timestamp) per surviving record
        recs = _decode_batch(self.src_meta, self.src_codec,
                             self._native_src, messages)
        for m, rec in zip(messages, recs):
            if rec is None:
                # poisoned message: dead-letter, don't halt (the real
                # KSQL DLQ behavior this comment used to approximate)
                self.dead_letter(m, "undecodable "
                                 f"{self.src_meta.value_format} record")
                continue
            if self.stmt.where is not None:
                try:
                    if not self.stmt.where(rec):
                        continue
                except TypeError:
                    continue  # NULL in a comparison: row excluded
            row = self._project(rec)
            if row is None:
                continue
            if self.stmt.partition_by:
                kv = row.get(self.stmt.partition_by, rec.get(self.stmt.partition_by))
                key = str(kv).encode() if kv is not None else m.key
            else:
                key = m.key
            picked.append((key, row, m.timestamp_ms))
        if not picked:
            return []
        if self.sink_meta.value_format == "AVRO":
            vals = self._encode_avro_rows([row for _, row, _ in picked])
        elif self.sink_meta.value_format == "DELIMITED":
            vals = [",".join("" if row.get(n) is None else str(row[n])
                             for n, _ in self.sink_meta.columns).encode()
                    for _, row, _ in picked]
        else:
            vals = [json.dumps(row, default=str).encode()
                    for _, row, _ in picked]
        return [(key, val, ts) for (key, _, ts), val in zip(picked, vals)]


class SqlAggTask(StreamTask):
    """A persistent CTAS query: windowed/global group-by with COUNT/SUM/
    MIN/MAX/AVG, emitting continuous-refinement updates as JSON rows.

    The latest record per (group, window) key is the table value — the same
    changelog semantics KSQL tables have."""

    def __init__(self, broker: Broker, src_meta: SourceMeta,
                 sink_meta: SourceMeta, stmt: SelectStmt,
                 group: str):
        super().__init__(broker, src_meta.topic, sink_meta.topic, group=group)
        self.src_meta = src_meta
        self.sink_meta = sink_meta
        self.stmt = stmt
        self.src_codec = (AvroCodec(src_meta.record_schema())
                          if src_meta.value_format == "AVRO" else None)
        self._native_src = _make_native_source(src_meta)
        # (group_key, window_start) → {alias: accumulator}
        self.acc: Dict[tuple, dict] = {}
        # Restore changelog state only when this group has committed input
        # offsets: state + offsets were written together, so either both
        # exist (resume) or neither does (fresh query over a topic that may
        # hold another query's retained output — replaying input from 0
        # with seeded state would double-count).
        src_topic = src_meta.topic
        n_src = (broker.topic(src_topic).partitions
                 if src_topic in broker.topics() else 0)
        if any(broker.committed(group, src_topic, p) is not None
               for p in range(n_src)):
            self._restore_from_changelog()
        # ---- vectorized COUNT fast path (the reference CTAS:
        # SELECT ROWKEY AS CAR, COUNT(*) ... WINDOW TUMBLING GROUP BY
        # ROWKEY): grouping needs only (key, timestamp) and COUNT needs no
        # fields at all, so eligible batches skip per-row dict
        # materialization — the source payloads are batch-validated
        # natively (the Python path drops undecodable rows, so the count
        # must too) and the (key, window) histogram comes from one
        # Counter pass.
        self._fast_count = bool(
            stmt.where is None and stmt.group_by == "ROWKEY"
            and self._native_src is not None
            and all((it.agg == "COUNT" and it.agg_arg is None)
                    or (not it.agg and it.source_col == "ROWKEY")
                    for it in stmt.items)
            and any(it.agg == "COUNT" for it in stmt.items))

    def _restore_from_changelog(self) -> None:
        """Rebuild aggregate state from the output topic.

        The consumer resumes from committed offsets, so without this a
        restarted CTAS would silently undercount: already-consumed input is
        skipped but `acc` starts empty.  The output topic *is* the table's
        changelog (latest row per key wins — KSQL's state-store restore from
        the changelog topic); AVG additionally persists its running sum and
        count as `__sum_`/`__n_` fields in each emitted row."""
        if self.dst not in self.broker.topics():
            return
        spec = self.broker.topic(self.dst)
        for p in range(spec.partitions):
            off = self.broker.begin_offset(self.dst, p)
            end = self.broker.end_offset(self.dst, p)
            while off < end:
                try:
                    msgs = self.broker.fetch(self.dst, p, off,
                                             max_messages=1024)
                except OffsetOutOfRangeError as e:
                    off = e.earliest  # raced a retention trim: skip ahead
                    continue
                if not msgs:
                    break
                for m in msgs:
                    off = m.offset + 1
                    try:
                        row = json.loads(m.value)
                    except (ValueError, UnicodeDecodeError):
                        continue
                    if not isinstance(row, dict):
                        continue
                    gval = (m.key or b"").decode(errors="replace")
                    win = row.get("WINDOW_START_MS", 0)
                    slot = self.acc.setdefault((gval, win), {})
                    for k, v in row.items():
                        if k == "WINDOW_START_MS":
                            continue
                        slot[k] = v  # latest record per key wins

    def _changelog_row(self, slot: dict, row: dict) -> dict:
        """Add AVG aux state (`__sum_`/`__n_`) so restore is exact."""
        for it in self.stmt.items:
            if it.agg == "AVG":
                for aux in ("__sum_" + it.alias, "__n_" + it.alias):
                    if aux in slot:
                        row[aux] = slot[aux]
        return row

    def _update(self, key: tuple, rec: dict):
        slot = self.acc.setdefault(key, {})
        for it in self.stmt.items:
            if not it.agg:
                continue
            if it.agg == "COUNT":
                slot[it.alias] = slot.get(it.alias, 0) + 1
                continue
            try:
                v = it.agg_arg(rec) if it.agg_arg else None
            except (TypeError, ZeroDivisionError):
                continue  # NULL in aggregate argument: skip this input
            if v is None:
                continue
            cur = slot.get(it.alias)
            if it.agg == "SUM":
                slot[it.alias] = (cur or 0) + v
            elif it.agg == "MIN":
                slot[it.alias] = v if cur is None else min(cur, v)
            elif it.agg == "MAX":
                slot[it.alias] = v if cur is None else max(cur, v)
            elif it.agg == "AVG":
                s, n = slot.get("__sum_" + it.alias, 0), slot.get("__n_" + it.alias, 0)
                s, n = s + v, n + 1
                slot["__sum_" + it.alias], slot["__n_" + it.alias] = s, n
                slot[it.alias] = s / n

    def process(self, messages):
        """Fold a chunk into the aggregate state, transactionally: if
        anything in the chunk raises, every slot this chunk touched is
        rolled back before the exception propagates — the engine's
        rewind-and-retry would otherwise fold the same records into the
        accumulators again on every retry."""
        undo: Dict[tuple, Optional[dict]] = {}
        try:
            return self._process_chunk(messages, undo)
        except Exception:
            for key, prev in undo.items():
                if prev is None:
                    self.acc.pop(key, None)
                else:
                    self.acc[key] = prev
            raise

    def _count_batch(self, messages):
        """(key, window) → count for an eligible COUNT-only batch, or None
        → per-row path (validation failure / unframed value)."""
        vals = []
        for m in messages:
            if not m.value or m.value[0] != 0:
                return None
            vals.append(m.value)
        try:
            # the Python path drops rows that fail to decode (including
            # invalid UTF-8 in a string field) — validate the whole batch
            # in strict mode so the count matches exactly; a batch with
            # any bad row takes the per-row path (which drops it)
            self._native_src.codec.decode_batch(
                vals, strip=5, stride=_NativeAvroSource.STRIDE, strict=True)
        except (ValueError, TypeError, RuntimeError):
            return None
        w = self.stmt.window_ms
        return Counter(
            ((m.key or b"").decode(errors="replace"),
             (m.timestamp_ms // w) * w if w else 0)
            for m in messages)

    def _process_chunk(self, messages, undo):
        touched = set()
        counted = self._count_batch(messages) if self._fast_count else None
        if counted is not None:
            for key, cnt in counted.items():
                if key not in undo:
                    undo[key] = dict(self.acc[key]) if key in self.acc \
                        else None
                slot = self.acc.setdefault(key, {})
                for it in self.stmt.items:
                    if it.agg == "COUNT":
                        slot[it.alias] = slot.get(it.alias, 0) + cnt
            touched.update(counted)
        else:
            recs = _decode_batch(self.src_meta, self.src_codec,
                                 self._native_src, messages)
            for m, rec in zip(messages, recs):
                if rec is None:
                    self.dead_letter(m, "undecodable "
                                     f"{self.src_meta.value_format} record")
                    continue
                if self.stmt.where is not None:
                    try:
                        if not self.stmt.where(rec):
                            continue
                    except TypeError:
                        continue
                gval = (rec.get(self.stmt.group_by)
                        if self.stmt.group_by else "")
                win = ((m.timestamp_ms // self.stmt.window_ms)
                       * self.stmt.window_ms if self.stmt.window_ms else 0)
                key = (str(gval), win)
                if key not in undo:  # shallow copy: slot values are scalars
                    undo[key] = (dict(self.acc[key]) if key in self.acc
                                 else None)
                self._update(key, rec)
                touched.add(key)
        out = []
        for gval, win in sorted(touched):
            slot = self.acc[(gval, win)]
            row = {}
            for it in self.stmt.items:
                if it.agg:
                    row[it.alias] = slot.get(it.alias, 0 if it.agg == "COUNT" else None)
                elif it.source_col == self.stmt.group_by:
                    row[it.alias] = gval
                elif not it.star:
                    row[it.alias] = gval if it.alias == self.stmt.group_by else None
            if self.stmt.window_ms:
                row["WINDOW_START_MS"] = win
            row = self._changelog_row(self.acc[(gval, win)], row)
            out.append((gval.encode(), json.dumps(row, default=str).encode(), win))
        return out

    def table(self) -> Dict[tuple, dict]:
        """Materialized view: (group, window_start) → aggregate row."""
        return {k: {it.alias: v.get(it.alias) for it in self.stmt.items if it.agg}
                for k, v in self.acc.items()}


class Query:
    """A running persistent query (CSAS/CTAS)."""

    def __init__(self, query_id: str, sink: str, sql: str, task: StreamTask):
        self.query_id = query_id
        self.sink = sink
        self.sql = sql
        self.task = task
        self.error: Optional[str] = None  # last pump failure, surfaced in SHOW QUERIES

    def describe(self) -> dict:
        d = {"id": self.query_id, "sink": self.sink, "queryString": self.sql,
             "state": "ERROR" if self.error else "RUNNING"}
        if self.error:
            d["error"] = self.error
        return d


# ------------------------------------------------------------------ engine


class SqlEngine:
    """Executes the KSQL-equivalent dialect against a Broker.

    One engine == one KSQL server: it owns stream/table metadata, persistent
    queries, and (via the registry) Avro schema ids for its output topics.
    """

    def __init__(self, broker: Broker, registry: Optional[SchemaRegistry] = None,
                 trusted_passthrough: bool = False,
                 owner_token: Optional[object] = None,
                 passthrough_sample: int = 0):
        self.broker = broker
        self.registry = registry or SchemaRegistry()
        self.sources: Dict[str, SourceMeta] = {}
        self.queries: Dict[str, Query] = {}
        self._qseq = 0
        #: when True, pass-through queries whose SOURCE is itself the
        #: output of one of this engine's own queries (query_id set) skip
        #: strict payload re-validation — those bytes were produced by the
        #: engine's validating encoder one hop earlier.  Sources fed by
        #: external producers always keep validation regardless.
        self.trusted_passthrough = bool(trusted_passthrough)
        #: defense-in-depth sampling under trust: validate one batch in
        #: every `passthrough_sample` even on trusted legs (0 = off).
        #: The broker's ownership grant already guarantees only the
        #: engine writes these topics; sampling catches the remaining
        #: failure class — a bug in the engine's own encoder — at ~1/N
        #: of the full re-validation cost (ADVICE r5).
        self.passthrough_sample = int(passthrough_sample)
        #: produce grant for engine-owned topics (Broker.restrict_topic):
        #: when the platform restricts the AVRO leg to this engine, pump
        #: rounds run under this token so only the engine's own tasks may
        #: write there — the write-exclusivity that makes
        #: trusted_passthrough sound, enforced instead of inferred.
        self.owner_token = owner_token

    # -- public API ---------------------------------------------------

    def execute(self, text: str) -> List[dict]:
        """Run one or more ';'-separated statements; one result dict each."""
        results = []
        for stmt in split_statements(text):
            results.append(self._execute_one(stmt))
        return results

    def pump(self, chunk: int = 4096) -> int:
        """Advance all persistent queries; returns records emitted.

        Each query is isolated: one task raising (e.g. an Avro encode type
        mismatch) marks THAT query errored — surfaced via SHOW QUERIES —
        and the rest keep pumping, instead of one poisoned query silently
        starving everything after it in dict order.

        Failure handling is at-least-once: poll() advances the in-memory
        cursor before process() runs, so on error the cursor is rewound to
        the committed offsets and the chunk is retried next pump (records
        emitted before the failure within the round may be re-emitted —
        KSQL's default delivery guarantee).  The error therefore stays
        visible in SHOW QUERIES until the chunk actually reprocesses."""
        grant = (self.broker.producer_grant(self.owner_token)
                 if self.owner_token is not None
                 and hasattr(self.broker, "producer_grant")
                 else contextlib.nullcontext())
        n = 0
        with grant:
            for q in list(self.queries.values()):
                try:
                    n += q.task.process_available(chunk)
                    q.error = None
                except Exception as e:  # noqa: BLE001 - per-query fault isolation
                    q.error = f"{type(e).__name__}: {e}"
                    q.task.consumer.rewind_to_committed()
        return n

    def table(self, name: str) -> Dict[tuple, dict]:
        """Materialized view of a CTAS table."""
        meta = self.sources.get(name.upper())
        if meta is None or meta.kind != "TABLE":
            raise SqlError(f"no such table: {name}")
        q = self.queries.get(meta.query_id)
        if q is None or not isinstance(q.task, SqlAggTask):
            raise SqlError(f"table {name} has no running query")
        return q.task.table()

    # -- statement dispatch -------------------------------------------

    def _execute_one(self, sql: str) -> dict:
        t = _Toks(tokenize(sql))
        first = (t.peek() or "").upper()
        if first == "CREATE":
            return self._create(t, sql)
        if first == "SELECT":
            return self._transient_select(_parse_select(t))
        if first == "PRINT":
            return self._print(t)
        if first == "SHOW" or first == "LIST":
            return self._show(t)
        if first == "DESCRIBE":
            t.next()
            t.accept("EXTENDED")
            name = t.ident()
            meta = self.sources.get(name)
            if meta is None:
                raise SqlError(f"no such stream/table: {name}")
            return {"statementText": sql, "sourceDescription": meta.describe()}
        if first == "TERMINATE":
            t.next()
            if t.accept("ALL"):
                ids = list(self.queries)
            else:
                ids = [t.ident()]
            for qid in ids:
                if qid not in self.queries:
                    raise SqlError(f"no such query: {qid}")
                del self.queries[qid]
            return {"statementText": sql, "commandStatus": {"status": "SUCCESS",
                    "message": f"terminated {len(ids)} queries"}}
        if first == "DROP":
            return self._drop(t, sql)
        raise SqlError(f"unsupported statement: {sql[:60]!r}")

    # -- CREATE --------------------------------------------------------

    def _parse_with(self, t: _Toks) -> dict:
        props = {}
        if t.accept("WITH"):
            t.expect("(")
            while True:
                k = t.ident()
                t.expect("=")
                tok = t.peek()
                if tok is not None and tok.startswith("'"):
                    props[k] = t.string()
                else:
                    props[k] = t.next()
                if not t.accept(","):
                    break
            t.expect(")")
        return props

    def _create(self, t: _Toks, sql: str) -> dict:
        t.expect("CREATE")
        if t.accept("STREAM"):
            kind = "STREAM"
        elif t.accept("TABLE"):
            kind = "TABLE"
        else:
            raise SqlError("expected STREAM or TABLE after CREATE")
        name = t.ident()
        if name in self.sources:
            raise SqlError(f"{kind.lower()} {name} already exists")

        if t.peek() == "(":  # explicit column list → base stream DDL
            t.expect("(")
            columns = []
            while True:
                col = t.ident()
                ktype = t.ident()
                if ktype not in _KSQL_TO_AVRO:
                    raise SqlError(f"unknown type {ktype}")
                columns.append((col, ktype))
                if not t.accept(","):
                    break
            t.expect(")")
            props = self._parse_with(t)
            topic = props.get("KAFKA_TOPIC", name.lower())
            vfmt = props.get("VALUE_FORMAT", "JSON").upper()
            if vfmt not in _SUPPORTED_VALUE_FORMATS:
                raise SqlError(f"unsupported VALUE_FORMAT {vfmt}")
            partitions = int(props.get("PARTITIONS", 1))
            self.broker.create_topic(topic, partitions=partitions)
            meta = SourceMeta(name, kind, topic, vfmt, columns,
                              key_col=props.get("KEY", "").upper() or None)
            self.sources[name] = meta
            if vfmt == "AVRO":
                self.registry.register(subject_for_topic(topic),
                                       meta.record_schema().avro_json())
            return {"statementText": sql, "commandStatus": {
                "status": "SUCCESS", "message": f"{kind} {name} created"}}

        # CSAS / CTAS
        props = self._parse_with(t)
        t.expect("AS")
        stmt = _parse_select(t)
        src = self.sources.get(stmt.source)
        if src is None:
            raise SqlError(f"unknown source: {stmt.source}")
        topic = props.get("KAFKA_TOPIC", name)
        vfmt = props.get("VALUE_FORMAT", src.value_format).upper()
        if vfmt not in _SUPPORTED_VALUE_FORMATS:
            raise SqlError(f"unsupported VALUE_FORMAT {vfmt}")
        partitions = int(props.get("PARTITIONS",
                                   self.broker.topic(src.topic).partitions))
        self.broker.create_topic(topic, partitions=partitions)

        # Consumer-group id: stable across restarts for the SAME statement
        # (so committed offsets + restored changelog state line up), but
        # keyed by a fingerprint of the SQL text so a re-created query with
        # different semantics starts fresh instead of inheriting the old
        # query's offsets and state.  Whitespace-normalized only — case
        # folding would conflate queries differing in a quoted literal's
        # case, which ARE semantically different.
        import hashlib
        fp = hashlib.sha1(" ".join(sql.split()).encode()).hexdigest()[:8]

        if kind == "TABLE" or stmt.is_aggregate:
            if not stmt.is_aggregate:
                raise SqlError("CREATE TABLE AS requires an aggregate SELECT")
            columns = []
            for it in stmt.items:
                if it.agg:
                    columns.append((it.alias, "BIGINT" if it.agg == "COUNT"
                                    else "DOUBLE"))
                elif not it.star:
                    columns.append((it.alias, self._col_type(src, it)))
            if stmt.window_ms:
                columns.append(("WINDOW_START_MS", "BIGINT"))
            meta = SourceMeta(name, "TABLE", topic, "JSON", columns,
                              key_col=stmt.group_by,
                              windowed=stmt.window_ms is not None)
            self._qseq += 1
            qid = f"CTAS_{name}_{self._qseq}"
            task = SqlAggTask(self.broker, src, meta, stmt,
                              group=f"CTAS_{name}_{fp}")
        else:
            columns = self._infer_columns(src, stmt)
            meta = SourceMeta(name, "STREAM", topic, vfmt, columns,
                              key_col=stmt.partition_by)
            self._qseq += 1
            qid = f"CSAS_{name}_{self._qseq}"
            task = SqlSelectTask(self.broker, src, meta, stmt,
                                 self.registry, group=f"CSAS_{name}_{fp}",
                                 trusted_passthrough=(
                                     self.trusted_passthrough
                                     and src.query_id is not None),
                                 passthrough_sample=self.passthrough_sample)
        meta.query_id = qid
        self.sources[name] = meta
        self.queries[qid] = Query(qid, name, sql, task)
        return {"statementText": sql, "commandStatus": {
            "status": "SUCCESS", "message": f"{kind} {name} created and "
            f"running as {qid}"}}

    @staticmethod
    def _col_type(src: SourceMeta, it: SelectItem) -> str:
        if it.source_col:
            if it.source_col in ("ROWKEY",):
                return "STRING"
            if it.source_col in ("ROWTIME",):
                return "BIGINT"
            for n, k in src.columns:
                if n == it.source_col:
                    return k
        return "DOUBLE"  # arbitrary expression: KSQL's numeric default

    def _infer_columns(self, src: SourceMeta, stmt: SelectStmt):
        columns: List[Tuple[str, str]] = []
        for it in stmt.items:
            if it.star:
                columns.extend(src.columns)
            else:
                columns.append((it.alias, self._col_type(src, it)))
        return columns

    # -- transient queries --------------------------------------------

    def _scan(self, meta: SourceMeta, limit: Optional[int] = None,
              where: Optional[Callable] = None):
        """Pull everything currently in a source's topic (from beginning)."""
        codec = (AvroCodec(meta.record_schema())
                 if meta.value_format == "AVRO" else None)
        spec = self.broker.topic(meta.topic)
        out = []
        for p in range(spec.partitions):
            off = self.broker.begin_offset(meta.topic, p)
            end = self.broker.end_offset(meta.topic, p)
            while off < end:
                try:
                    msgs = self.broker.fetch(meta.topic, p, off,
                                             max_messages=1024)
                except OffsetOutOfRangeError as e:
                    off = e.earliest  # raced a retention trim: skip ahead
                    continue
                if not msgs:
                    break
                for m in msgs:
                    rec = _decode_record(meta, codec, m)
                    off = m.offset + 1
                    if rec is None:
                        continue
                    if where is not None:
                        try:
                            if not where(rec):
                                continue
                        except TypeError:
                            continue
                    out.append(rec)
                    if limit is not None and len(out) >= limit:
                        return out
        return out

    def _transient_select(self, stmt: SelectStmt) -> dict:
        meta = self.sources.get(stmt.source)
        if meta is None:
            raise SqlError(f"unknown source: {stmt.source}")
        if stmt.is_aggregate:
            raise SqlError("transient aggregate queries are not supported; "
                           "use CREATE TABLE ... AS")
        # limit pushes down into the scan: WHERE already ran there, so the
        # scan stops at the n-th match instead of decoding the whole topic
        recs = self._scan(meta, limit=stmt.limit, where=stmt.where)
        rows = []
        header = []
        for it in stmt.items:
            if it.star:
                header.extend(n for n, _ in meta.columns)
            else:
                header.append(it.alias)
        for rec in recs:
            row = []
            try:
                for it in stmt.items:
                    if it.star:
                        row.extend(rec.get(n) for n, _ in meta.columns)
                    else:
                        row.append(it.fn(rec))
            except (TypeError, ZeroDivisionError):
                continue  # NULL in projection arithmetic: drop row
            rows.append(row)
            if stmt.limit is not None and len(rows) >= stmt.limit:
                break
        return {"header": header, "rows": rows}

    def _print(self, t: _Toks) -> dict:
        t.expect("PRINT")
        if (t.peek() or "").startswith("'"):
            topic = t.string()
        else:
            # unquoted: try the token as written, then case-folded variants
            raw = t.next()
            known = self.broker.topics()
            topic = next((c for c in (raw, raw.lower(), raw.upper())
                          if c in known), raw)
        from_beginning = t.accept("FROM", "BEGINNING")
        limit = None
        if t.accept("LIMIT"):
            limit = int(t.next())
        if topic not in self.broker.topics():
            raise SqlError(f"no such topic: {topic}")
        spec = self.broker.topic(topic)
        rows = []
        for p in range(spec.partitions):
            off = (self.broker.begin_offset(topic, p) if from_beginning
                   else max(self.broker.begin_offset(topic, p),
                            self.broker.end_offset(topic, p) - (limit or 10)))
            end = self.broker.end_offset(topic, p)
            while off < end and (limit is None or len(rows) < limit):
                try:
                    msgs = self.broker.fetch(topic, p, off, max_messages=256)
                except OffsetOutOfRangeError as e:
                    off = e.earliest  # raced a retention trim: skip ahead
                    continue
                if not msgs:
                    break
                for m in msgs:
                    rows.append({"partition": p, "offset": m.offset,
                                 "rowtime": m.timestamp_ms,
                                 "key": (m.key or b"").decode(errors="replace"),
                                 "value": self._render_value(m.value)})
                    off = m.offset + 1
                    if limit is not None and len(rows) >= limit:
                        break
        return {"topic": topic, "rows": rows}

    def _render_value(self, value: bytes) -> str:
        """Best-effort value rendering: registry Avro → JSON → utf-8 → hex."""
        try:
            sid, payload = unframe(value)
            reg = self.registry.by_id(sid)
            rec = AvroCodec(reg.record_schema()).decode(payload)
            return json.dumps(rec, default=str)
        except (ValueError, KeyError, IndexError, struct_error):
            pass
        try:
            return value.decode()
        except UnicodeDecodeError:
            return value.hex()

    # -- SHOW / DROP ---------------------------------------------------

    def _show(self, t: _Toks) -> dict:
        t.next()
        what = t.ident()
        if what == "STREAMS":
            return {"streams": [m.describe() for m in self.sources.values()
                                if m.kind == "STREAM"]}
        if what == "TABLES":
            return {"tables": [m.describe() for m in self.sources.values()
                               if m.kind == "TABLE"]}
        if what == "QUERIES":
            return {"queries": [q.describe() for q in self.queries.values()]}
        if what == "TOPICS":
            return {"topics": [{"name": n,
                                "partitions": self.broker.topic(n).partitions}
                               for n in self.broker.topics()]}
        raise SqlError(f"cannot SHOW {what}")

    def _drop(self, t: _Toks, sql: str) -> dict:
        t.expect("DROP")
        if t.accept("STREAM"):
            kind = "STREAM"
        elif t.accept("TABLE"):
            kind = "TABLE"
        else:
            raise SqlError("expected STREAM or TABLE after DROP")
        if_exists = t.accept("IF", "EXISTS")
        name = t.ident()
        t.accept("DELETE", "TOPIC")  # metadata-only engine: topic retained
        meta = self.sources.get(name)
        if meta is None:
            if if_exists:
                return {"statementText": sql, "commandStatus": {
                    "status": "SUCCESS", "message": f"{name} did not exist"}}
            raise SqlError(f"no such {kind.lower()}: {name}")
        if meta.kind != kind:
            raise SqlError(f"{name} is a {meta.kind}, not a {kind}")
        # KSQL refuses to drop a source with a live query writing to it
        if meta.query_id and meta.query_id in self.queries:
            raise SqlError(f"cannot drop {name}: query {meta.query_id} is "
                           f"running (TERMINATE it first)")
        readers = [q.query_id for q in self.queries.values()
                   if q.task.src == meta.topic]
        if readers:
            raise SqlError(f"cannot drop {name}: queries {readers} read it")
        del self.sources[name]
        return {"statementText": sql, "commandStatus": {
            "status": "SUCCESS", "message": f"{kind} {name} dropped"}}


# ------------------------------------------------- reference DDL, verbatim

#: The four-object pipeline the reference installs
#: (`01_installConfluentPlatform.sh:229-258`), expressed in this dialect.
REFERENCE_PIPELINE_DDL = """
CREATE STREAM SENSOR_DATA_S (
  COOLANT_TEMP DOUBLE, INTAKE_AIR_TEMP DOUBLE, INTAKE_AIR_FLOW_SPEED DOUBLE,
  BATTERY_PERCENTAGE DOUBLE, BATTERY_VOLTAGE DOUBLE, CURRENT_DRAW DOUBLE,
  SPEED DOUBLE, ENGINE_VIBRATION_AMPLITUDE DOUBLE, THROTTLE_POS DOUBLE,
  TIRE_PRESSURE11 INTEGER, TIRE_PRESSURE12 INTEGER,
  TIRE_PRESSURE21 INTEGER, TIRE_PRESSURE22 INTEGER,
  ACCELEROMETER11_VALUE DOUBLE, ACCELEROMETER12_VALUE DOUBLE,
  ACCELEROMETER21_VALUE DOUBLE, ACCELEROMETER22_VALUE DOUBLE,
  CONTROL_UNIT_FIRMWARE INTEGER, FAILURE_OCCURRED STRING
) WITH (KAFKA_TOPIC='sensor-data', VALUE_FORMAT='JSON');

CREATE STREAM SENSOR_DATA_S_AVRO
  WITH (VALUE_FORMAT='AVRO', KAFKA_TOPIC='SENSOR_DATA_S_AVRO')
  AS SELECT * FROM SENSOR_DATA_S;

CREATE STREAM SENSOR_DATA_S_AVRO_REKEY
  AS SELECT ROWKEY AS CAR, * FROM SENSOR_DATA_S_AVRO PARTITION BY CAR;

CREATE TABLE SENSOR_DATA_EVENTS_PER_5MIN_T
  AS SELECT ROWKEY AS CAR, COUNT(*) AS EVENT_COUNT
     FROM SENSOR_DATA_S_AVRO_REKEY
     WINDOW TUMBLING (SIZE 5 MINUTES) GROUP BY ROWKEY;
"""


def install_reference_pipeline(engine: SqlEngine) -> List[dict]:
    """Run the reference's KSQL DDL (§2.3) against an engine."""
    return engine.execute(REFERENCE_PIPELINE_DDL)
