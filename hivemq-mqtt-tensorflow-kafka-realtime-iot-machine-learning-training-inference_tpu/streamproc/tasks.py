"""KSQL-equivalent continuous stream transforms.

The reference preprocesses broker-side with four KSQL objects
(`01_installConfluentPlatform.sh:229-258`, SURVEY §2.3):

  SENSOR_DATA_S                JSON stream over `sensor-data` (19 columns)
  SENSOR_DATA_S_AVRO           CSAS: JSON → AVRO (the ML input topic)
  SENSOR_DATA_S_AVRO_REKEY     CSAS: re-key by CAR (ROWKEY → partition key)
  SENSOR_DATA_EVENTS_PER_5MIN_T CTAS: tumbling 5-min event count per car

Here each is a `StreamTask`: an offset-cursored consumer plus a pure
`process(messages) → [(key, value, ts)]` step appended to an output topic.
Tasks are incremental (`process_available()`) so tests and the demo driver
can interleave them with producers, and restartable via consumer commits —
the same continuous-query semantics KSQL provides, in-process.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..core.schema import CAR_SCHEMA, KSQL_CAR_SCHEMA
from ..obs import tracing
from ..ops.avro import AvroCodec
from ..ops.framing import frame
from ..stream.broker import Broker, Message
from ..stream.consumer import StreamConsumer
from . import dlq as _dlq


class StreamTask:
    """Continuous transform: src topic → process() → dst topic."""

    def __init__(self, broker: Broker, src: str, dst: str,
                 partitions: int = 1, group: Optional[str] = None,
                 src_partitions: Optional[int] = None, consumer=None):
        self.broker = broker
        self.src = src
        self.dst = dst
        #: lazily-built RawBatchProducer for tasks with a raw produce
        #: leg (process_raw); None until first used
        self._raw_producer = None
        broker.create_topic(dst, partitions=partitions)
        if consumer is not None:
            # injected cursor — a GroupConsumer makes the task GROUP-
            # ELASTIC: N instances of the same task split the source
            # partitions and rebalance on member death (the
            # partition-parallel KSQL pumps of iotml.cluster.fleet)
            self.consumer = consumer
        else:
            n_src = src_partitions if src_partitions is not None \
                else broker.topic(src).partitions
            # resume from committed group offsets so a restarted task
            # does not re-emit already-transformed records (KSQL's
            # continuous-query restart semantics)
            self.consumer = StreamConsumer.from_committed(
                broker, src, list(range(n_src)),
                group=group or f"task-{dst}", fallback_offset=0, eof=True)

    def process(self, messages: List[Message]) -> List[Tuple]:
        """Return [(key, value, timestamp_ms)] outputs."""
        raise NotImplementedError

    def raw_producer(self):
        """The task's RawBatchProducer for its output topic (built on
        first use) — the zero-copy produce plane with the classic
        fallback ladder (IOTML_RAW_PRODUCE)."""
        if self._raw_producer is None:
            from ..stream.producer import RawBatchProducer

            self._raw_producer = RawBatchProducer(self.broker, self.dst)
        return self._raw_producer

    def process_raw(self, messages: List[Message]) -> Optional[int]:
        """Optional zero-copy produce hook: transform `messages` and
        ship the outputs as pre-framed raw batches (ISSUE 12 — a record
        is framed ONCE at conversion and appended segment-verbatim).
        Return the records emitted, or None to take the classic
        process() + produce_many path for this chunk.  Only consulted
        on untraced sessions: trace headers exist only on the classic
        per-record path."""
        return None

    def dead_letter(self, message: Message, error) -> None:
        """Route one poisoned input to `<src>_DLQ` instead of silently
        dropping it (counted under iotml_dlq_total{source=...}); a
        failing DLQ path degrades back to the plain drop."""
        _dlq.route(self.broker, message, str(error),
                   task=type(self).__name__)

    def _forward_traces(self, msgs, outs):
        """Re-attach trace headers to a chunk's outputs and mark the
        `streamproc` stage.  Tasks emit (key, value, ts) without their
        source messages, so forwarding happens HERE, positionally — sound
        only for 1:1 chunks (every task builds outputs in input order).
        Filtering chunks (row drops) lose the association and the trace
        simply ends at this stage: graceful degradation, sampled traces
        are statistics, not an audit log.

        The output carries a FORK of the input's context, marked on this
        task's lineage — never a mark on the shared input object: the
        input topic's other consumers (a sibling task, a batcher) fork
        from the same header, and mutating its t_last after handoff
        would skew their spans by a stage their pipeline never ran."""
        if len(outs) != len(msgs):
            return outs
        fwd = []
        for m, out in zip(msgs, outs):
            ctx = tracing.from_headers(m.headers) if m.headers else None
            if ctx is not None:
                hop = ctx.fork()
                hop.mark("streamproc")
                fwd.append((out[0], out[1], out[2],
                            tracing.headers_for(hop)))
            else:
                fwd.append(out)
        return fwd

    def process_available(self, chunk: int = 4096) -> int:
        """Consume and transform everything currently available.

        Offsets are committed after EACH successfully processed chunk (not
        only at end-of-stream), so a failure in a later chunk — with the
        engine's rewind-to-committed retry — re-emits at most the failed
        chunk, never the whole backlog."""
        n = 0
        while True:
            msgs = self.consumer.poll(chunk)
            if not msgs:
                self.consumer.commit()
                return n
            if not tracing.ENABLED:
                # the zero-copy produce leg (tasks that implement it):
                # converted chunks ship as pre-framed raw batches, no
                # per-record python between convert and append
                handled = self.process_raw(msgs)
                if handled is not None:
                    n += handled
                    self.consumer.commit()
                    continue
            outs = self.process(msgs)
            if outs:
                if tracing.ENABLED:
                    outs = self._forward_traces(msgs, outs)
                # ONE bulk append per chunk: a per-record produce() paid
                # a lock round-trip + partitioner dispatch per message —
                # ~24% of the whole KSQL pump at fleet rates.  Same
                # per-record semantics (key-hash partitioning, append
                # order, retention) by produce_many's contract.
                self.broker.produce_many(self.dst, outs)
                n += len(outs)
            self.consumer.commit()


class JsonToAvro(StreamTask):
    """SENSOR_DATA_S_AVRO: JSON sensor records → Confluent-framed Avro.

    Field matching is case-insensitive and accepts both producer names
    (`tire_pressure_1_1`) and KSQL names (`TIRE_PRESSURE11`), mirroring
    KSQL's case-insensitive column resolution.

    ``schema_version=2`` writes the evolved schema (REGION cohort tag,
    `core.schema.KSQL_CAR_SCHEMA_V2`) framed under its own id — the
    rolling-upgrade shape where SOME converter instances emit the new
    schema onto the live topic while v1 readers keep consuming it
    through Avro schema resolution (`ops.avro.ResolvingCodec`).
    """

    def __init__(self, broker: Broker, src: str = "sensor-data",
                 dst: str = "SENSOR_DATA_S_AVRO",
                 schema_version: int = 1, **kw):
        super().__init__(broker, src, dst, **kw)
        from ..core.schema import WRITER_VERSIONS

        if schema_version not in WRITER_VERSIONS:
            raise ValueError(f"unknown writer schema version "
                             f"{schema_version} "
                             f"(have: {sorted(WRITER_VERSIONS)})")
        self.schema, self.schema_id = WRITER_VERSIONS[schema_version]
        self.codec = AvroCodec(self.schema)
        # lookup: lowercase alias → KSQL field name
        self._alias: Dict[str, str] = {}
        for f_prod, f_ksql in zip(CAR_SCHEMA.fields,
                                  self.schema.sensor_fields):
            self._alias[f_prod.name.lower()] = f_ksql.name
            self._alias[f_ksql.name.lower()] = f_ksql.name
        self._alias["failure_occurred"] = "FAILURE_OCCURRED"
        for name in self.schema.meta_fields:  # v2: region → REGION
            self._alias[name.lower()] = name

    def process(self, messages):
        out = []
        for m in messages:
            try:
                obj = json.loads(m.value)
                if not isinstance(obj, dict):
                    raise ValueError(f"expected JSON object, got "
                                     f"{type(obj).__name__}")
                rec = {}
                for k, v in obj.items():
                    name = self._alias.get(k.lower())
                    if name is None:
                        continue
                    f = self.schema.field(name)
                    if v is None:
                        rec[name] = None
                    elif f.avro_type in ("int", "long"):
                        rec[name] = int(v)
                    elif f.avro_type == "string":
                        rec[name] = str(v)
                    else:
                        rec[name] = float(v)
                val = frame(self.codec.encode(rec), self.schema_id)
            except (ValueError, TypeError, KeyError) as e:
                # poisoned sensor JSON used to HALT the whole chunk
                # (json.loads raised out of process_available); now it
                # dead-letters and the stream keeps flowing
                self.dead_letter(m, e)
                continue
            out.append((m.key, val, m.timestamp_ms))
        return out


class RekeyByCar(StreamTask):
    """SENSOR_DATA_S_AVRO_REKEY: partition the stream by car id.

    The reference's `SELECT ROWKEY as CAR, * ... PARTITION BY CAR`: the MQTT
    client id rides as the message key, so re-keying is routing every record
    to the key-hashed partition of the output topic (keyed partitioning in
    `Broker.produce`), giving per-car ordering — the property sequence models
    need.
    """

    def process(self, messages):
        return [(m.key, m.value, m.timestamp_ms) for m in messages]


class TumblingCounter(StreamTask):
    """SENSOR_DATA_EVENTS_PER_5MIN_T: tumbling-window event count per car.

    Counts land in output as JSON {"CAR", "WINDOW_START_MS", "EVENT_COUNT"}.
    Like KSQL tables, counts for a window are emitted as updates: every
    `process_available()` round emits the current count for windows touched
    in that round (KSQL's continuous refinement), so the latest record per
    (car, window) key is the table value.
    """

    def __init__(self, broker: Broker, src: str = "SENSOR_DATA_S_AVRO_REKEY",
                 dst: str = "SENSOR_DATA_EVENTS_PER_5MIN_T",
                 window_ms: int = 5 * 60 * 1000, **kw):
        super().__init__(broker, src, dst, **kw)
        self.window_ms = window_ms
        self.counts: Dict[tuple, int] = {}

    def process(self, messages):
        touched = set()
        for m in messages:
            car = (m.key or b"").decode() or "unknown"
            win = (m.timestamp_ms // self.window_ms) * self.window_ms
            k = (car, win)
            self.counts[k] = self.counts.get(k, 0) + 1
            touched.add(k)
        out = []
        for car, win in sorted(touched):
            payload = json.dumps({"CAR": car, "WINDOW_START_MS": win,
                                  "EVENT_COUNT": self.counts[(car, win)]}).encode()
            out.append((car.encode(), payload, win))
        return out

    def table(self) -> Dict[tuple, int]:
        """Materialized view of (car, window_start_ms) → count."""
        return dict(self.counts)


class DelimitedToAvro(StreamTask):
    """KSQL DELIMITED→AVRO recipe for the CSV fixture topic.

    The reference replays `car-sensor-data.csv` through a FileStreamSource
    into `car-data-csv`, declares a DELIMITED stream over it, and CSASes it
    to Avro (reference `test_file_source_and _testdata.sh:49-61`).  Input
    lines are `time,car,<18 sensors>`; output is Confluent-framed KSQL-schema
    Avro keyed by car id, with the label defaulted to "false" (the fixture
    has no failure column).
    """

    def __init__(self, broker: Broker, src: str = "car-data-csv",
                 dst: str = "SENSOR_DATA_S_AVRO", label: str = "false", **kw):
        super().__init__(broker, src, dst, **kw)
        self.codec = AvroCodec(KSQL_CAR_SCHEMA)
        self.label = label

    def process(self, messages):
        out = []
        for m in messages:
            try:
                parts = m.value.decode().split(",")
            except UnicodeDecodeError as e:
                self.dead_letter(m, e)  # poisoned bytes: DLQ, don't halt
                continue
            if parts[0] == "time":
                continue  # replayed header: expected shape, not poison
            if len(parts) != 2 + len(CAR_SCHEMA.fields):
                self.dead_letter(
                    m, f"expected {2 + len(CAR_SCHEMA.fields)} columns, "
                       f"got {len(parts)}")  # KSQL would null-fill; we DLQ
                continue
            rec = {}
            try:
                for f_prod, f_ksql, raw in zip(CAR_SCHEMA.fields,
                                               KSQL_CAR_SCHEMA.sensor_fields,
                                               parts[2:]):
                    rec[f_ksql.name] = int(float(raw)) \
                        if f_ksql.avro_type in ("int", "long") else float(raw)
            except ValueError as e:
                self.dead_letter(m, f"non-numeric sensor value: {e}")
                continue
            rec["FAILURE_OCCURRED"] = self.label
            key = parts[1].encode()
            out.append((key, frame(self.codec.encode(rec)), m.timestamp_ms))
        return out
