"""iotml.supervise — live self-healing runtime.

Supervised component lifecycles (``supervisor``), published leadership
topology with fencing epochs (``topology``), the process-wide thread /
supervisor registry (``registry``), and live chaos drills with recovery
SLOs (``drill``, ``python -m iotml.supervise drill``).

This ``__init__`` is deliberately lazy: ``registry`` is imported by
low-level modules (obs.metrics, every thread-spawning module) and must
stay dependency-free, so the heavier supervisor/drill machinery loads
only on attribute access.
"""

from __future__ import annotations

_LAZY = {
    "Supervisor": "supervisor", "SupervisedUnit": "supervisor",
    "Topology": "topology",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module 'iotml.supervise' has no "
                             f"attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)


__all__ = sorted(_LAZY)
