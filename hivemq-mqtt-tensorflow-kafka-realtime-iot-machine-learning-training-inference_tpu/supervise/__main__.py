"""``python -m iotml.supervise`` — self-healing runtime CLI.

    python -m iotml.supervise drill [--drill NAME | --all] [--seed S]
                                    [--records N] [--json]
                                    [--slo-promote S] [--slo-score S]
    python -m iotml.supervise list

``drill`` runs a LIVE chaos drill — real threads, real wire servers,
real supervision — and exits with the invariant verdict (0 = the
system healed itself and every delivery invariant held).  CI runs the
leader-kill drill exactly this way (.github/workflows/supervise.yml).
``list`` shows the available drills.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.supervise",
        description="supervised runtime: live chaos drills with "
                    "recovery SLOs")
    sub = ap.add_subparsers(dest="cmd")
    dp = sub.add_parser("drill", help="run a live drill; exit status is "
                                      "the invariant verdict")
    dp.add_argument("--drill", default="leader-kill",
                    help="drill name (see `list`)")
    dp.add_argument("--all", action="store_true",
                    help="run every drill in sequence")
    dp.add_argument("--seed", type=int, default=7)
    dp.add_argument("--records", type=int, default=0,
                    help="records to pump (0 = the drill's default)")
    dp.add_argument("--slo-promote", type=float, default=10.0,
                    help="leader-kill: max seconds kill -> promotion")
    dp.add_argument("--slo-score", type=float, default=20.0,
                    help="leader-kill: max seconds kill -> first "
                         "post-failover score")
    dp.add_argument("--json", action="store_true")
    sub.add_parser("list", help="list available drills")
    args = ap.parse_args(argv)

    from .drill import DRILLS

    if args.cmd == "list":
        for name, fn in sorted(DRILLS.items()):
            doc = (fn.__doc__ or "").strip().splitlines()[0]
            print(f"{name:<14} {doc}")
        return 0
    if args.cmd != "drill":
        ap.print_help()
        return 2

    names = sorted(DRILLS) if args.all else [args.drill]
    unknown = [n for n in names if n not in DRILLS]
    if unknown:
        print(f"unknown drill(s) {unknown}; have: {sorted(DRILLS)}",
              file=sys.stderr)
        return 2
    ok = True
    for name in names:
        kw = {"seed": args.seed}
        if args.records:
            kw["records"] = args.records
        if name == "leader-kill":
            kw["slo_promote_s"] = args.slo_promote
            kw["slo_first_score_s"] = args.slo_score
        report = DRILLS[name](**kw)
        if args.json:
            print(json.dumps(report.to_dict(), indent=2, sort_keys=True,
                             default=str))
        else:
            print("\n".join(report.lines()))
        ok = ok and report.ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
