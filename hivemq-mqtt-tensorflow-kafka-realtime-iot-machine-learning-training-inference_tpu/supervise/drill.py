"""Live chaos drills — the supervised runtime under real fire.

PR 3's ``iotml.chaos`` proves delivery invariants in a *single-threaded
deterministic* replay; these drills prove the *live multi-threaded*
system actually heals itself.  Each drill runs real components on real
threads (wire servers, background replication, a supervised scorer and
trainer), injects the failure (leader kill / MQTT flap / scorer crash)
through the same faultpoints and kill switches the chaos subsystem
compiled in, and then asserts two things:

- the PR 3 **delivery invariants** still hold (commits monotonic,
  at-least-once counts, final commit at log end, predictions bounded);
- **recovery SLOs**: time-to-promote, time-to-first-post-failover
  score, input loss bounded by the replication lag measured at the
  kill, and supervised units back to RUNNING without manual
  intervention.

Run via ``python -m iotml.supervise drill`` (the verdict is the exit
status — CI runs exactly this).  Drill wall-clock is bounded; SLO
bounds default generous enough for a loaded CI box while still failing
a system that does not heal.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import threading
import time
from typing import Dict, List, Optional

from ..chaos import faults, scenarios
from ..chaos.runner import (GROUP, IN_TOPIC, PRED_TOPIC, Invariant,
                            _check_commits_monotonic, _record_commits)
from .supervisor import Supervisor
from .topology import Topology

#: records per simulated fleet tick (shared with iotml.chaos)
CARS_PER_TICK = scenarios.CARS_PER_TICK


@dataclasses.dataclass
class DrillReport:
    drill: str
    seed: int
    records: int
    published: int
    scored: int
    restarts: Dict[str, int]
    slos: Dict[str, Optional[float]]
    invariants: List[Invariant]
    injected: Dict[str, int]

    @property
    def ok(self) -> bool:
        return all(i.ok for i in self.invariants)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        return d

    def lines(self) -> List[str]:
        out = [f"drill={self.drill} seed={self.seed} "
               f"records={self.records} published={self.published} "
               f"scored={self.scored}"]
        for k, v in sorted(self.slos.items()):
            # keys ending in _s are wall-clock seconds; others are
            # record counts / quality numbers (the online drill's
            # record-based SLOs) and carry no unit suffix
            unit = "s" if k.endswith("_s") else ""
            out.append(f"  slo {k}: "
                       + ("n/a" if v is None else f"{v:.3f}{unit}"))
        for k, v in sorted(self.restarts.items()):
            out.append(f"  restarts {k}: {v}")
        for k, v in sorted(self.injected.items()):
            out.append(f"  injected {k}: {v}")
        out += ["  " + i.verdict() for i in self.invariants]
        out.append(("DRILL PASS" if self.ok else "DRILL FAIL")
                   + f" ({self.drill})")
        return out


# ------------------------------------------------------------- helpers
def _make_scorer(out_broker, consumer):
    import numpy as np

    from ..data.dataset import SensorBatches
    from ..models.autoencoder import CAR_AUTOENCODER
    from ..serve.scorer import StreamScorer
    from ..stream.producer import OutputSequence
    from ..train.loop import Trainer

    trainer = Trainer(CAR_AUTOENCODER)
    trainer._ensure_state(np.zeros((100, 18), np.float32))
    batches = SensorBatches(consumer, batch_size=100)
    out = OutputSequence(out_broker, PRED_TOPIC, partition=0)
    return StreamScorer(CAR_AUTOENCODER, trainer.state.params, batches, out)


def _scorer_unit_loop(scorer, consumer, state):
    """The supervised scorer body: crash-resume semantics on every
    (re)start (a fresh incarnation rewinds to committed offsets exactly
    like a restarted process), rewind-and-retry on connection loss, a
    heartbeat per healthy round."""

    def loop(unit):
        # a (re)started incarnation must not trust in-memory cursors:
        # the previous one may have died mid-drain with rows polled but
        # uncommitted — resume from the commit table (at-least-once)
        consumer.rewind_to_committed()
        while not unit.should_stop():
            try:
                n = scorer.score_available()
            except ConnectionError:
                # broker failover in flight: the client has re-resolved;
                # rewind and redeliver (the PR 3 redelivery contract)
                consumer.rewind_to_committed()
                state["rewinds"] += 1
                time.sleep(0.02)
                continue
            unit.heartbeat()
            if n:
                state["last_score_t"] = time.monotonic()
                if state.get("t_kill") is not None and \
                        state.get("t_first_score_after_kill") is None:
                    state["t_first_score_after_kill"] = time.monotonic()
            else:
                time.sleep(0.005)

    return loop


def _wait(cond, timeout_s: float, interval_s: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return cond()


# ------------------------------------------------------ leader-kill
def drill_leader_kill(seed: int = 7, records: int = 1500,
                      slo_promote_s: float = 10.0,
                      slo_first_score_s: float = 20.0) -> DrillReport:
    """Fenced leader failover, live: a leader+follower wire topology
    with the fleet pumping through it, the leader killed mid-drain, the
    supervisor detecting the death and promoting the follower at a
    bumped epoch, scorer and trainer resuming on their own — and a
    resurrected old leader fenced by its stale epoch."""
    import tempfile

    from ..core.schema import KSQL_CAR_SCHEMA
    from ..gen.simulator import FleetGenerator, FleetScenario
    from ..ops.avro import AvroCodec
    from ..ops.framing import frame
    from ..stream.broker import Broker
    from ..stream.consumer import StreamConsumer
    from ..stream.kafka_wire import (FencedEpochError, KafkaWireBroker,
                                     KafkaWireServer)
    from ..stream.replica import FollowerReplica

    if records < 3 * CARS_PER_TICK:
        raise ValueError(f"leader-kill needs >= {3 * CARS_PER_TICK} "
                         f"records (kill lands mid-drain), got {records}")
    eng = faults.arm(faults.ChaosEngine(()))  # counts any stray points
    leader = Broker()
    commit_log: List[tuple] = []
    _record_commits(leader, commit_log, "leader")
    lsrv = KafkaWireServer(leader, epoch=0).start()
    rep = FollowerReplica(f"127.0.0.1:{lsrv.port}",
                          topics=[IN_TOPIC, PRED_TOPIC],
                          groups=(GROUP, "drill-trainer"),
                          poll_interval_s=0.005,
                          commit_interval_s=0.05)
    _record_commits(rep.local, commit_log, "follower")
    topo = Topology(f"127.0.0.1:{lsrv.port}", epoch=0,
                    fallback=[f"127.0.0.1:{rep.port}"])
    state: dict = {"rewinds": 0, "t_kill": None,
                   "t_first_score_after_kill": None,
                   "trainer_rounds": []}
    promoted = threading.Event()

    def failover(_unit):
        # the supervisor's on_death hook: promote at a bumped epoch,
        # publish the new topology — clients re-resolve from here on
        new_epoch = topo.epoch + 1
        addr = rep.promote(new_epoch)
        state["replicated_at_promote"] = sum(
            rep.local.end_offset(IN_TOPIC, p)
            for p in range(rep.local.topic(IN_TOPIC).partitions))
        topo.publish(addr, new_epoch)
        state["t_promoted"] = time.monotonic()
        promoted.set()

    def leader_probe():
        s = socket.create_connection(("127.0.0.1", lsrv.port),
                                     timeout=0.25)
        s.close()
        return True

    producer = KafkaWireBroker(f"127.0.0.1:{lsrv.port}",
                               client_id="drill-devsim", topology=topo)
    consumer_client = KafkaWireBroker(f"127.0.0.1:{lsrv.port}",
                                      client_id="drill-scorer",
                                      topology=topo)
    parts = 2
    producer.create_topic(IN_TOPIC, partitions=parts)
    producer.create_topic(PRED_TOPIC, partitions=1)
    rep.start()
    consumer = StreamConsumer(
        consumer_client, [f"{IN_TOPIC}:{p}:0" for p in range(parts)],
        group=GROUP)
    scorer = _make_scorer(producer, consumer)

    sup = Supervisor(poll_interval_s=0.05, name="drill-supervisor")
    sup.add_probed("leader-broker", leader_probe, on_death=failover,
                   probe_failures=2)
    sup.add_loop("scorer", _scorer_unit_loop(scorer, consumer, state),
                 heartbeat_timeout_s=30.0)

    tmp = tempfile.TemporaryDirectory(prefix="iotml_drill_")

    def trainer_loop(unit):
        # a FRESH trainer per incarnation: the supervised-restart story
        # is a crashed trainer coming back `from_committed` against the
        # promoted leader — resumed offsets are the mirrored commits
        from ..train.artifacts import ArtifactStore
        from ..train.live import ContinuousTrainer

        client = KafkaWireBroker(topo.leader, client_id="drill-trainer",
                                 topology=topo)
        ct = ContinuousTrainer(
            client, IN_TOPIC, ArtifactStore(tmp.name),
            group="drill-trainer", batch_size=25, take_batches=2,
            epochs_per_round=1, only_normal=False)
        unit.trainer = ct  # post-drill introspection

        def on_round(stats):
            unit.heartbeat()
            state["trainer_rounds"].append(
                (time.monotonic(), stats["round"]))

        ct.run(stop=unit.should_stop, poll_interval_s=0.01,
               on_round=on_round)

    sup.add_loop("trainer", trainer_loop, heartbeat_timeout_s=60.0,
                 max_restarts=8)
    sup.start()

    gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK, seed=seed))
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    published = 0
    killed = False
    kill_at = max(CARS_PER_TICK, records // 2)
    ticks = max(1, -(-records // CARS_PER_TICK))
    try:
        for _ in range(ticks):
            if not killed and published >= kill_at:
                # producer quiescent while we snapshot the loss window,
                # so `loss <= lag` is measured, not hoped: nothing is
                # produced between the snapshot and the kill
                state["lag_at_kill"] = sum(rep.lag().values())
                state["published_pre_kill"] = published
                state["t_kill"] = time.monotonic()
                lsrv.kill()
                killed = True
            cols = gen.step_columns()
            entries = [
                (gen.scenario.car_id(i).encode(),
                 frame(codec.encode(gen.row_record(cols, i,
                                                   KSQL_CAR_SCHEMA))), 0)
                for i in range(len(cols["car"]))]
            for attempt in range(100):
                try:
                    producer.produce_many(IN_TOPIC, entries)
                    break
                except (FencedEpochError, ConnectionError):
                    # dead or fenced party: topology re-resolves inside
                    # the client; redeliver (kills land between ticks,
                    # so the dead leader never half-applied this batch)
                    if attempt == 99:
                        raise
                    time.sleep(0.05)
            published += len(entries)
        promoted_ok = promoted.wait(timeout=slo_promote_s + 5)
        # drain: everything the promoted log retained must end up scored
        # and committed without anyone touching the scorer
        _wait(lambda: state.get("t_first_score_after_kill") is not None,
              slo_first_score_s + 5)
        _wait(lambda: all(
            rep.local.committed(GROUP, IN_TOPIC, p)
            == rep.local.end_offset(IN_TOPIC, p) for p in range(parts)),
            20.0)
        trainer_resumed = _wait(
            lambda: any(t > state["t_kill"]
                        for t, _ in state["trainer_rounds"]),
            25.0) if killed else False

        # ---------------------------------------- resurrected old leader
        fence_ok = False
        if promoted_ok:
            # the resurrection test: the OLD leader's broker comes back
            # serving at its stale epoch 0; a current-epoch client's
            # produce AND commit against it must both answer FENCED
            zombie = KafkaWireServer(leader, epoch=0).start()
            try:
                probe_client = KafkaWireBroker(
                    f"127.0.0.1:{zombie.port}",
                    client_id="drill-zombie-probe", epoch=topo.epoch)
                try:
                    probe_client.produce(IN_TOPIC, b"split-brain")
                except FencedEpochError:
                    try:
                        probe_client.commit(GROUP, IN_TOPIC, 0, 1)
                    except FencedEpochError:
                        fence_ok = True
                probe_client.close()
            finally:
                zombie.shutdown()
                zombie.server_close()
    finally:
        sup.stop()
        for c in (producer, consumer_client):
            try:
                c.close()
            except OSError:
                pass
        if not rep.promoted:
            rep.stop()
        else:
            rep.server.shutdown()
            rep.server.server_close()
        if not killed:
            lsrv.kill()
        faults.disarm()
        tmp.cleanup()

    # ------------------------------------------------------- verdicts
    t_promote = (state.get("t_promoted", 0) - state["t_kill"]) \
        if promoted_ok and killed else None
    t_score = (state["t_first_score_after_kill"] - state["t_kill"]) \
        if state.get("t_first_score_after_kill") and killed else None
    loss = (state.get("published_pre_kill", 0)
            - state.get("replicated_at_promote", 0)) if promoted_ok else -1
    lag = state.get("lag_at_kill", -1)
    retained = sum(rep.local.end_offset(IN_TOPIC, p) for p in range(parts))
    pred_end = rep.local.end_offset(PRED_TOPIC, 0)
    invariants = [
        Invariant("promoted_within_slo",
                  killed and promoted_ok and t_promote is not None
                  and t_promote <= slo_promote_s,
                  f"leader killed -> follower promoted in "
                  f"{t_promote:.3f}s (slo {slo_promote_s}s)"
                  if t_promote is not None else "promotion never happened"),
        Invariant("first_score_within_slo",
                  t_score is not None and t_score <= slo_first_score_s,
                  f"first post-failover score after {t_score:.3f}s "
                  f"(slo {slo_first_score_s}s)" if t_score is not None
                  else "scorer never scored after the kill"),
        Invariant("promotion_loss_bounded",
                  promoted_ok and 0 <= loss <= max(lag, 0),
                  f"unreplicated input at promotion: {loss} records "
                  f"within measured lag {lag}" if promoted_ok else
                  "no promotion to measure"),
        Invariant("trainer_resumed",
                  trainer_resumed,
                  "trainer completed rounds after the failover without "
                  "manual intervention" if trainer_resumed else
                  "no trainer round completed after the kill"),
        _check_commits_monotonic(commit_log),
        Invariant("final_commit_at_end",
                  all(rep.local.committed(GROUP, IN_TOPIC, p)
                      == rep.local.end_offset(IN_TOPIC, p)
                      for p in range(parts)),
                  "committed == promoted log end on every partition"),
        Invariant("all_retained_scored",
                  scorer.scored >= retained,
                  f"scored {scorer.scored} >= {retained} records the "
                  f"promoted log retained (at-least-once, duplicates "
                  f"allowed)"),
        Invariant("predictions_bounded_gap_free",
                  pred_end <= scorer.scored and not scorer.out._buf,
                  f"predictions end {pred_end} <= scored "
                  f"{scorer.scored}, output buffer drained "
                  f"(OutputSequence's gap check never tripped)"),
        Invariant("old_leader_fenced",
                  fence_ok,
                  "resurrected old leader rejected epoch-stamped "
                  "produce AND commit" if fence_ok else
                  "stale leader accepted writes — SPLIT LOG"),
        Invariant("no_degraded_units", not sup.degraded(),
                  f"degraded units: {sup.degraded() or 'none'}"),
    ]
    return DrillReport(
        drill="leader-kill", seed=seed, records=records,
        published=published, scored=scorer.scored,
        restarts={u.name: u.restarts for u in sup.units()},
        slos={"time_to_promote_s": t_promote,
              "time_to_first_post_failover_score_s": t_score},
        invariants=invariants,
        injected=dict(sorted(eng.injected.items())))


# ----------------------------------------------------- broker-restart
def drill_broker_restart(seed: int = 7, records: int = 1000,
                         slo_restart_s: float = 10.0,
                         slo_first_score_s: float = 20.0) -> DrillReport:
    """Durable-broker crash restart, live: a wire-served broker mounted
    on the segmented store (fsync=always) dies mid-write (connections
    severed, torn frame on the active segment), the supervisor's probe
    detects the death and its on_death hook REMOUNTS the store — crash
    recovery truncates the torn tail — and serves it at a bumped epoch;
    the producer and the supervised scorer resume unaided with ZERO
    acked-record loss and cursors at the persisted committed offsets."""
    import tempfile

    from ..core.schema import KSQL_CAR_SCHEMA
    from ..gen.simulator import FleetGenerator, FleetScenario
    from ..ops.avro import AvroCodec
    from ..ops.framing import frame
    from ..store import StorePolicy
    from ..stream.broker import Broker
    from ..stream.consumer import StreamConsumer
    from ..stream.kafka_wire import (FencedEpochError, KafkaWireBroker,
                                     KafkaWireServer)

    if records < 3 * CARS_PER_TICK:
        raise ValueError(f"broker-restart needs >= {3 * CARS_PER_TICK} "
                         f"records (kill lands mid-stream), got {records}")
    eng = faults.arm(faults.ChaosEngine(()))
    tmp = tempfile.TemporaryDirectory(prefix="iotml_drill_store_")
    policy_kw = dict(fsync="always", segment_bytes=256 * 1024)
    commit_log: List[tuple] = []
    state: dict = {"rewinds": 0, "t_kill": None, "t_restarted": None,
                   "t_first_score_after_kill": None, "torn": 0,
                   "acked": {}, "truncated": -1, "recovered_end": {}}
    restarted = threading.Event()

    live = {"broker": Broker(store_dir=tmp.name,
                             store_policy=StorePolicy(**policy_kw))}
    _record_commits(live["broker"], commit_log, "store")
    live["srv"] = KafkaWireServer(live["broker"], epoch=0).start()
    topo = Topology(f"127.0.0.1:{live['srv'].port}", epoch=0)

    def broker_probe():
        s = socket.create_connection(
            ("127.0.0.1", live["srv"].port), timeout=0.25)
        s.close()
        return True

    def restart(_unit):
        # the supervisor's on_death hook — what a kubelet restart does,
        # minus the node: remount the store dir (recovery truncates the
        # torn frame the kill left), serve at a bumped epoch, publish
        new_epoch = topo.epoch + 1
        broker = Broker(store_dir=tmp.name,
                        store_policy=StorePolicy(**policy_kw))
        _record_commits(broker, commit_log, "store")
        state["truncated"] = broker.store.recovered_truncated_bytes()
        state["recovered_end"] = {
            (t, p): broker.end_offset(t, p)
            for (t, p) in state["acked"]}
        srv = KafkaWireServer(broker, epoch=new_epoch).start()
        live["broker"], live["srv"] = broker, srv
        topo.publish(f"127.0.0.1:{srv.port}", new_epoch)
        state["t_restarted"] = time.monotonic()
        restarted.set()

    producer = KafkaWireBroker(topo.leader, client_id="drill-devsim",
                               topology=topo)
    consumer_client = KafkaWireBroker(topo.leader, client_id="drill-scorer",
                                      topology=topo)
    parts = 2
    producer.create_topic(IN_TOPIC, partitions=parts)
    producer.create_topic(PRED_TOPIC, partitions=1)
    consumer = StreamConsumer(
        consumer_client, [f"{IN_TOPIC}:{p}:0" for p in range(parts)],
        group=GROUP)
    scorer = _make_scorer(producer, consumer)

    sup = Supervisor(poll_interval_s=0.05, name="drill-supervisor")
    sup.add_probed("durable-broker", broker_probe, on_death=restart,
                   probe_failures=2)
    sup.add_loop("scorer", _scorer_unit_loop(scorer, consumer, state),
                 heartbeat_timeout_s=30.0)
    sup.start()

    gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK, seed=seed))
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    published = 0
    killed = False
    kill_at = max(CARS_PER_TICK, records // 2)
    ticks = max(1, -(-records // CARS_PER_TICK))
    try:
        for _ in range(ticks):
            if not killed and published >= kill_at:
                # mid-write death: snapshot what was ACKED (everything —
                # fsync=always means ack follows the sync), leave a torn
                # frame on the active segment, sever every connection
                broker = live["broker"]
                for t in (IN_TOPIC, PRED_TOPIC):
                    for p in range(broker.topic(t).partitions):
                        state["acked"][(t, p)] = broker.end_offset(t, p)
                state["torn"] = broker.store.log_for(
                    IN_TOPIC, 0).simulate_torn_write()
                state["t_kill"] = time.monotonic()
                live["srv"].kill()
                killed = True
            cols = gen.step_columns()
            entries = [
                (gen.scenario.car_id(i).encode(),
                 frame(codec.encode(gen.row_record(cols, i,
                                                   KSQL_CAR_SCHEMA))), 0)
                for i in range(len(cols["car"]))]
            for attempt in range(100):
                try:
                    producer.produce_many(IN_TOPIC, entries)
                    break
                except (FencedEpochError, ConnectionError):
                    # dead or fenced party: the topology-aware client
                    # re-resolves; redeliver (kills land between ticks,
                    # so the dead server never half-applied this batch)
                    if attempt == 99:
                        raise
                    time.sleep(0.05)
            published += len(entries)
        restarted_ok = restarted.wait(timeout=slo_restart_s + 5)
        _wait(lambda: state.get("t_first_score_after_kill") is not None,
              slo_first_score_s + 5)
        _wait(lambda: all(
            live["broker"].committed(GROUP, IN_TOPIC, p)
            == live["broker"].end_offset(IN_TOPIC, p)
            for p in range(parts)), 20.0)
    finally:
        sup.stop()
        for c in (producer, consumer_client):
            try:
                c.close()
            except OSError:
                pass
        if not killed or restarted.is_set():
            # live["srv"] is a RUNNING server (the original, or the
            # restarted incarnation); a killed-but-never-restarted one
            # must not be killed twice (shutdown() would block)
            live["srv"].kill()
        live["broker"].close()
        faults.disarm()
        tmp.cleanup()

    t_restart = (state["t_restarted"] - state["t_kill"]) \
        if restarted.is_set() and killed else None
    t_score = (state["t_first_score_after_kill"] - state["t_kill"]) \
        if state.get("t_first_score_after_kill") and killed else None
    lost = {k: (acked, state["recovered_end"].get(k))
            for k, acked in state["acked"].items()
            if state["recovered_end"].get(k, -1) < acked}
    retained = sum(live["broker"].end_offset(IN_TOPIC, p)
                   for p in range(parts))
    pred_end = live["broker"].end_offset(PRED_TOPIC, 0)
    invariants = [
        Invariant("restarted_within_slo",
                  killed and restarted_ok and t_restart is not None
                  and t_restart <= slo_restart_s,
                  f"broker killed -> remounted+serving in "
                  f"{t_restart:.3f}s (slo {slo_restart_s}s)"
                  if t_restart is not None else "restart never happened"),
        Invariant("first_score_within_slo",
                  t_score is not None and t_score <= slo_first_score_s,
                  f"first post-restart score after {t_score:.3f}s "
                  f"(slo {slo_first_score_s}s)" if t_score is not None
                  else "scorer never scored after the kill"),
        Invariant("zero_acked_loss",
                  killed and restarted.is_set() and not lost,
                  "every record acked before the mid-write kill was "
                  "re-served from disk after recovery (fsync=always)"
                  if not lost else f"ACKED RECORDS LOST: {lost}"),
        Invariant("torn_tail_truncated",
                  state["truncated"] == state["torn"] > 0,
                  f"recovery truncated {state['truncated']} bytes == "
                  f"the {state['torn']} torn bytes the kill left"),
        _check_commits_monotonic(commit_log),
        Invariant("final_commit_at_end",
                  all(live["broker"].committed(GROUP, IN_TOPIC, p)
                      == live["broker"].end_offset(IN_TOPIC, p)
                      for p in range(parts)),
                  "committed == log end on every partition (cursors "
                  "resumed from the persisted offsets file)"),
        Invariant("all_retained_scored",
                  scorer.scored >= retained,
                  f"scored {scorer.scored} >= {retained} records the "
                  f"durable log retained (at-least-once, duplicates "
                  f"allowed)"),
        Invariant("predictions_bounded_gap_free",
                  pred_end <= scorer.scored and not scorer.out._buf,
                  f"predictions end {pred_end} <= scored "
                  f"{scorer.scored}, output buffer drained"),
        Invariant("no_degraded_units", not sup.degraded(),
                  f"degraded units: {sup.degraded() or 'none'}"),
    ]
    return DrillReport(
        drill="broker-restart", seed=seed, records=records,
        published=published, scored=scorer.scored,
        restarts={u.name: u.restarts for u in sup.units()},
        slos={"time_to_restart_s": t_restart,
              "time_to_first_post_restart_score_s": t_score},
        invariants=invariants,
        injected=dict(sorted(eng.injected.items())))


# ------------------------------------------------------------ inproc
def _drill_inproc(name: str, events, seed: int, records: int,
                  extra_invariants=None,
                  min_scorer_restarts: int = 0) -> DrillReport:
    """Shared body for the in-process live drills (mqtt-flap /
    scorer-crash): fleet → MQTT → bridge → JsonToAvro → scorer, every
    stage on its own supervised thread, faultpoints armed."""
    from ..gen.simulator import FleetGenerator, FleetScenario
    from ..mqtt.bridge import KafkaBridge
    from ..mqtt.broker import MqttBroker
    from ..stream.broker import Broker
    from ..stream.consumer import StreamConsumer
    from ..streamproc.tasks import JsonToAvro

    eng = faults.arm(faults.ChaosEngine(events))
    mqtt = MqttBroker()
    stream = Broker()
    commit_log: List[tuple] = []
    _record_commits(stream, commit_log, "stream")
    KafkaBridge(mqtt, stream, partitions=2)
    task = JsonToAvro(stream, src="sensor-data", dst=IN_TOPIC,
                      partitions=2)
    parts = stream.topic(IN_TOPIC).partitions
    consumer = StreamConsumer(
        stream, [f"{IN_TOPIC}:{p}:0" for p in range(parts)], group=GROUP)
    scorer = _make_scorer(stream, consumer)
    state: dict = {"rewinds": 0}

    def task_loop(unit):
        while not unit.should_stop():
            try:
                n = task.process_available()
            except ConnectionError:
                task.consumer.rewind_to_committed()
                time.sleep(0.02)
                continue
            unit.heartbeat()
            time.sleep(0.002 if n else 0.01)

    sup = Supervisor(poll_interval_s=0.02, name="drill-supervisor")
    sup.add_loop("ksql-task", task_loop, heartbeat_timeout_s=30.0)
    sup.add_loop("scorer", _scorer_unit_loop(scorer, consumer, state),
                 heartbeat_timeout_s=30.0, max_restarts=10)
    sup.start()

    gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK, seed=seed))
    published = 0
    ticks = max(1, -(-records // CARS_PER_TICK))
    try:
        from ..core.schema import CAR_SCHEMA

        for _ in range(ticks):
            cols = gen.step_columns()
            for i in range(len(cols["car"])):
                rec = gen.row_record(cols, i, CAR_SCHEMA)
                rec["failure_occurred"] = str(cols["failure_occurred"][i])
                mqtt.publish(
                    f"vehicles/sensor/data/{gen.scenario.car_id(i)}",
                    json.dumps(rec).encode(), qos=1)
                published += 1
            time.sleep(0.002)  # live pacing: stages overlap, not lockstep
        # quiesce: scorer has consumed everything the (possibly lossy)
        # pipeline delivered, and its commits reached the log end
        _wait(lambda: task.consumer.at_end(), 20.0)
        _wait(lambda: consumer.at_end()
              and all(stream.committed(GROUP, IN_TOPIC, p)
                      == stream.end_offset(IN_TOPIC, p)
                      for p in range(parts)), 30.0)
    finally:
        sup.stop()
        faults.disarm()

    delivered = sum(stream.end_offset(IN_TOPIC, p) for p in range(parts))
    invariants = [
        Invariant("at_least_once_counts",
                  scorer.scored >= published - eng.dropped_count,
                  f"published={published} scored={scorer.scored} "
                  f"intentionally_dropped={eng.dropped_count}"),
        _check_commits_monotonic(commit_log),
        Invariant("final_commit_at_end",
                  all(stream.committed(GROUP, IN_TOPIC, p)
                      == stream.end_offset(IN_TOPIC, p)
                      for p in range(parts)),
                  "committed == log end on every partition"),
        Invariant("all_delivered_scored",
                  scorer.scored >= delivered,
                  f"scored {scorer.scored} >= {delivered} delivered to "
                  f"the input topic"),
        Invariant("scorer_restarts",
                  sup.unit("scorer").restarts >= min_scorer_restarts,
                  f"scorer restarted {sup.unit('scorer').restarts} "
                  f"time(s) (needed >= {min_scorer_restarts}) — "
                  f"supervision, not manual intervention"),
        Invariant("no_degraded_units", not sup.degraded(),
                  f"degraded units: {sup.degraded() or 'none'}"),
    ] + list(extra_invariants(scorer, sup) if extra_invariants else [])
    return DrillReport(
        drill=name, seed=seed, records=records, published=published,
        scored=scorer.scored,
        restarts={u.name: u.restarts for u in sup.units()},
        slos={}, invariants=invariants,
        injected=dict(sorted(eng.injected.items())))


def drill_mqtt_flap(seed: int = 7, records: int = 1000) -> DrillReport:
    """Flapping device links against the live threaded pipeline: seeded
    MQTT delivery drops (accounted in the intentional-loss ledger) and
    delay bursts; every surviving record must still be scored and
    committed."""
    schedule = scenarios.build("mqtt-flap", seed=seed, records=records)
    return _drill_inproc("mqtt-flap", schedule.events, seed, records)


def drill_scorer_crash(seed: int = 7, records: int = 750) -> DrillReport:
    """The scorer thread DIES twice mid-stream (RuntimeError out of the
    drain loop — not the ConnectionError it knows how to rewind from);
    the supervisor must restart it and the restarted incarnations must
    finish the stream with at-least-once delivery intact."""
    # scorer.poll is hit once per drain-loop round (idle rounds
    # included), so live hit counts accrue at wall-clock speed, not
    # record speed — schedule the two kills on early hits that every
    # run reaches, and let each kill take down one incarnation (the
    # counter is global, so hit 15 lands on the RESTARTED scorer)
    events = [
        scenarios.FaultEvent(5, "scorer.poll", "error",
                             params=(("exc", "RuntimeError"),)),
        scenarios.FaultEvent(15, "scorer.poll", "error",
                             params=(("exc", "RuntimeError"),)),
    ]
    return _drill_inproc("scorer-crash", events, seed, records,
                         min_scorer_restarts=1)


DRILLS = {
    "leader-kill": drill_leader_kill,
    "broker-restart": drill_broker_restart,
    "mqtt-flap": drill_mqtt_flap,
    "scorer-crash": drill_scorer_crash,
}
