"""Process-wide registries for supervised threads and supervisors.

Every background thread the framework starts outside ``iotml/supervise/``
must be *daemon*, *named*, and registered here (lint rule R8 closes this
by construction) — the registry is what turns "fire-and-forget threads
scattered over twelve modules" into an enumerable runtime surface the
supervisor and ``/healthz`` can reason about.  Registration is
deliberately cheap and dependency-free: one weak reference per thread,
no locks on the thread's own path, importable from anywhere without
cycles (this module imports nothing from ``iotml``).

Supervisors (``supervise.supervisor.Supervisor``) register themselves on
start so the metrics server's ``/healthz`` can report unit states
without the obs layer importing the supervise package eagerly.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional

_lock = threading.Lock()
#: weak refs so a registered thread (or its owner) can be garbage
#: collected normally — the registry observes lifecycles, never extends
#: them.
_threads: "List[weakref.ref]" = []
_supervisors: "List[weakref.ref]" = []


def register_thread(thread: threading.Thread,
                    name: Optional[str] = None) -> threading.Thread:
    """Register a background thread; returns it (wrap-the-constructor
    idiom: ``register_thread(threading.Thread(...))``).

    Enforces at runtime what lint R8 enforces at review time: the
    thread must be a daemon (a non-daemon background thread blocks
    process exit — the supervisor owns orderly shutdown, not atexit
    hangs) and must carry a meaningful name (``Thread-7`` in a stack
    dump of a wedged process is useless)."""
    if name is not None:
        thread.name = name
    if not thread.daemon:
        raise ValueError(
            f"background thread {thread.name!r} must be daemon=True: "
            f"orderly shutdown belongs to the supervisor, not to a "
            f"non-daemon thread pinning process exit")
    if thread.name.startswith("Thread-"):
        raise ValueError(
            "background thread needs an explicit name (got default "
            f"{thread.name!r}): unnamed threads make wedged-process "
            "stack dumps unreadable")
    with _lock:
        # opportunistic compaction BEFORE appending, keeping unstarted
        # threads (ident is None): registration happens at construction
        # time (wrap-the-constructor idiom), so an is_alive()-only
        # filter would evict every just-registered thread once the list
        # is long — silently un-enumerating exactly what R8 registers
        if len(_threads) > 64:
            _threads[:] = [r for r in _threads
                           if (t := r()) is not None
                           and (t.ident is None or t.is_alive())]
        _threads.append(weakref.ref(thread))
    return thread


def threads() -> List[threading.Thread]:
    """Live registered threads (snapshot)."""
    with _lock:
        refs = list(_threads)
    return [t for r in refs if (t := r()) is not None and t.is_alive()]


def register_supervisor(sup) -> None:
    with _lock:
        _supervisors[:] = [r for r in _supervisors if r() is not None]
        _supervisors.append(weakref.ref(sup))


def unregister_supervisor(sup) -> None:
    with _lock:
        _supervisors[:] = [r for r in _supervisors
                           if r() is not None and r() is not sup]


def supervisors() -> list:
    with _lock:
        refs = list(_supervisors)
    return [s for r in refs if (s := r()) is not None]


def snapshot() -> Dict[str, dict]:
    """Unit-state snapshot across every live supervisor — the
    ``/healthz`` "supervisor" section (empty dict when nothing is
    supervised, so unsupervised processes pay one list read)."""
    out: Dict[str, dict] = {}
    for sup in supervisors():
        try:
            out.update(sup.snapshot())
        except Exception:  # noqa: BLE001 - a dying supervisor must not
            continue       # take the health endpoint down with it
    return out
