"""Supervisor — owned lifecycles for the platform's moving parts.

The reference gets self-healing for free from Kubernetes: every
component is a Deployment whose pods are probed, restarted, and backed
off by the kubelet (SURVEY §2.6/§2.7).  The rebuild's ``Platform``
brought the *services* in-process but launched them fire-and-forget —
a crashed scorer thread or a wedged bridge simply went dark.  This
module is the kubelet-equivalent for in-process components:

- a ``SupervisedUnit`` wraps either a *loop* (a callable driven on an
  owned, named, daemon thread — restarted when it crashes or wedges) or
  a *probed external* (a server whose liveness is a probe callable —
  its death triggers an ``on_death`` hook, e.g. leader failover);
- liveness is three signals, cheapest first: thread aliveness,
  per-unit heartbeats (``unit.heartbeat()`` from inside the loop), and
  the PR 2 stage-liveness ages (``obs.tracing.liveness()``) for units
  that declare the trace stage they keep fresh;
- restarts run under the stream stack's ``ExpBackoff`` with a
  restart-storm budget: more than ``max_restarts`` within
  ``restart_window_s`` and the supervisor GIVES UP — the unit enters
  ``degraded`` (surfaced via ``iotml_supervisor_*`` metrics and
  ``/healthz``) instead of burning a core on a crash loop.

The supervisor never force-kills a thread (Python cannot); a wedged
loop is asked to stop via its stop event, and a replacement is started
regardless — the old daemon thread stays visible in the registry until
it exits.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, Deque, Dict, List, Optional

from ..obs import metrics as obs_metrics
from ..utils.backoff import ExpBackoff
from . import registry

# unit states (strings, not an enum: they land in JSON snapshots)
IDLE = "idle"
RUNNING = "running"
CRASHED = "crashed"
WAITING = "waiting_backoff"
DEGRADED = "degraded"
FAILED_OVER = "failed_over"
STOPPED = "stopped"


class SupervisedUnit:
    """One supervised component.

    Exactly one of ``loop`` / ``probe`` must be given:

    loop(unit):
        The unit's body, run on an owned daemon thread.  It should call
        ``unit.heartbeat()`` each round and exit when
        ``unit.should_stop()`` — returning normally is a clean stop, an
        escaping exception is a crash (recorded, restarted under
        backoff).
    probe():
        Liveness check for an EXTERNAL component (a wire server, a
        peer process).  ``probe_failures`` consecutive False/raising
        probes mark the unit dead; then ``on_death(unit)`` fires once
        (leader failover lives here) or, if ``restart`` was given,
        the component is restarted under the same backoff/budget.
    """

    def __init__(self, name: str, loop: Optional[Callable] = None, *,
                 probe: Optional[Callable[[], bool]] = None,
                 restart: Optional[Callable[[], None]] = None,
                 on_death: Optional[Callable[["SupervisedUnit"], None]] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 stage: Optional[str] = None, stage_timeout_s: float = 5.0,
                 probe_failures: int = 3,
                 max_restarts: Optional[int] = None,
                 restart_window_s: float = 30.0,
                 backoff: Optional[ExpBackoff] = None):
        if max_restarts is None:
            # IOTML_SUPERVISE_MAX_RESTARTS: fleet-wide restart-storm
            # budget override (in config.py's non_config set — a harness
            # knob, not pipeline config); read at construction so tests
            # can monkeypatch the environment
            max_restarts = int(os.environ.get(
                "IOTML_SUPERVISE_MAX_RESTARTS", "5"))
        if (loop is None) == (probe is None):
            raise ValueError(
                f"unit {name!r}: exactly one of loop= (owned thread) or "
                f"probe= (external liveness) is required")
        self.name = name
        self.loop = loop
        self.probe = probe
        self.restart_fn = restart
        self.on_death = on_death
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.stage = stage
        self.stage_timeout_s = stage_timeout_s
        self.probe_failures = probe_failures
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        self.backoff = backoff or ExpBackoff(base_s=0.05, cap_s=2.0)

        self.state = IDLE
        self.restarts = 0
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._clean_exit = False
        self._thread: Optional[threading.Thread] = None
        self._beat = time.monotonic()
        self._probe_misses = 0
        self._restart_times: Deque[float] = collections.deque()
        self._next_start_at = 0.0  # monotonic deadline while WAITING

    # ----------------------------------------------------- loop-side API
    def heartbeat(self) -> None:
        """Called by the unit's own loop each healthy round."""
        self._beat = time.monotonic()

    def should_stop(self) -> bool:
        if self._stop.is_set():
            return True
        # incarnation fencing: a wedged thread that was already REPLACED
        # must see stop=True forever, even though _spawn cleared the
        # shared event for the new incarnation — otherwise an unwedged
        # zombie would resume its loop beside its replacement and
        # double-drive the unit's work
        cur = threading.current_thread()
        return cur.name.startswith("iotml-unit-") and cur is not self._thread

    # ------------------------------------------------------- introspect
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def to_dict(self) -> dict:
        return {"state": self.state, "restarts": self.restarts,
                "last_error": self.last_error,
                "beat_age_s": round(time.monotonic() - self._beat, 3)}

    # ---------------------------------------------------------- internal
    def _spawn(self) -> None:
        unit = self

        def body():
            try:
                unit.loop(unit)
                unit._clean_exit = True  # returning normally is a clean
                # stop per the class contract, stop event or not
            except Exception as e:  # noqa: BLE001 - ANY escaping
                # exception is a crash by definition; the monitor (not
                # this dying thread) decides restart vs give-up
                unit.last_error = f"{type(e).__name__}: {e}"

        self._clean_exit = False
        self._stop.clear()
        self._beat = time.monotonic()
        self._thread = registry.register_thread(
            threading.Thread(target=body, daemon=True,
                             name=f"iotml-unit-{self.name}"))
        # state flips BEFORE the thread starts: an observer that sees
        # alive() true must never read a stale IDLE (a /healthz scrape
        # landing between start() and a later assignment did exactly
        # that under load).  If the body crashes instantly, the monitor
        # sees RUNNING + dead thread — the normal restart path.
        self.state = RUNNING
        self._thread.start()

    def _budget_exhausted(self, now: float) -> bool:
        while self._restart_times and \
                now - self._restart_times[0] > self.restart_window_s:
            self._restart_times.popleft()
        return len(self._restart_times) >= self.max_restarts


class Supervisor:
    """Monitor thread over registered units.

    ``start()`` runs the monitor; each tick walks every unit and applies
    the decision table (dead → backoff-restart or give-up; wedged →
    stop + replace; probe-dead external → on_death/restart).  The
    supervisor registers itself so ``/healthz`` picks up ``snapshot()``
    from any process with a metrics server."""

    def __init__(self, poll_interval_s: Optional[float] = None,
                 name: str = "supervisor"):
        self.name = name
        if poll_interval_s is None:
            # IOTML_SUPERVISE_POLL_S: monitor cadence override (see
            # max_restarts note above)
            poll_interval_s = float(os.environ.get(
                "IOTML_SUPERVISE_POLL_S", "0.05"))
        self.poll_interval_s = poll_interval_s
        self._units: Dict[str, SupervisedUnit] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------ registration
    def add(self, unit: SupervisedUnit) -> SupervisedUnit:
        with self._lock:
            if unit.name in self._units:
                raise ValueError(f"duplicate unit {unit.name!r}")
            self._units[unit.name] = unit
        obs_metrics.supervisor_unit_up.set(0, unit=unit.name)
        return unit

    def add_loop(self, name: str, loop: Callable, **kw) -> SupervisedUnit:
        return self.add(SupervisedUnit(name, loop, **kw))

    def add_probed(self, name: str, probe: Callable[[], bool],
                   **kw) -> SupervisedUnit:
        return self.add(SupervisedUnit(name, probe=probe, **kw))

    def unit(self, name: str) -> SupervisedUnit:
        with self._lock:
            return self._units[name]

    def units(self) -> List[SupervisedUnit]:
        with self._lock:
            return list(self._units.values())

    # -------------------------------------------------------- lifecycle
    def start(self) -> "Supervisor":
        for u in self.units():
            if u.loop is not None and u.state == IDLE:
                u._spawn()
            elif u.probe is not None and u.state == IDLE:
                u.state = RUNNING
        self._stop.clear()
        self._thread = registry.register_thread(
            threading.Thread(target=self._monitor, daemon=True,
                             name=f"iotml-{self.name}"))
        self._thread.start()
        registry.register_supervisor(self)
        return self

    def stop(self, join_timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout_s)
        for u in self.units():
            u._stop.set()
        for u in self.units():
            if u._thread is not None:
                u._thread.join(timeout=join_timeout_s)
            if u.state in (RUNNING, WAITING, CRASHED):
                u.state = STOPPED
            obs_metrics.supervisor_unit_up.set(0, unit=u.name)
        registry.unregister_supervisor(self)

    def __enter__(self) -> "Supervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------- monitoring
    def snapshot(self) -> Dict[str, dict]:
        return {u.name: u.to_dict() for u in self.units()}

    def degraded(self) -> List[str]:
        return [u.name for u in self.units() if u.state == DEGRADED]

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            now = time.monotonic()
            for u in self.units():
                try:
                    self._tick_unit(u, now)
                except Exception as e:  # noqa: BLE001 - one unit's
                    # broken probe must not stop supervision of the rest
                    u.last_error = f"monitor: {type(e).__name__}: {e}"

    def _tick_unit(self, u: SupervisedUnit, now: float) -> None:
        if u.state in (DEGRADED, FAILED_OVER, STOPPED):
            return
        if u.state == IDLE:
            # registered after start(): bring it up on the next tick
            if u.loop is not None:
                u._spawn()
            else:
                u.state = RUNNING
            return
        if u.state == WAITING:
            if now >= u._next_start_at:
                if u.loop is not None:
                    u._spawn()
                    obs_metrics.supervisor_unit_up.set(1, unit=u.name)
                else:
                    # deferred EXTERNAL restart: optimistic RUNNING —
                    # if the component is still down, the probe path
                    # re-detects and the budget/backoff still bound it
                    try:
                        u.restart_fn()
                        u._probe_misses = 0
                        u.state = RUNNING
                    except Exception as e:  # noqa: BLE001 - failed
                        # restart is just the next probe miss
                        u.last_error = f"restart: {type(e).__name__}: {e}"
                        u.state = RUNNING
            return
        if u.loop is not None:
            self._tick_loop_unit(u, now)
        else:
            self._tick_probed_unit(u)

    # --------------------------------------------------- loop unit rules
    def _tick_loop_unit(self, u: SupervisedUnit, now: float) -> None:
        dead = not u.alive()
        wedged = (not dead and u.heartbeat_timeout_s is not None
                  and now - u._beat > u.heartbeat_timeout_s)
        if not dead and not wedged and u.stage is not None:
            wedged = self._stage_stalled(u)
        if not dead and not wedged:
            obs_metrics.supervisor_unit_up.set(1, unit=u.name)
            if u.backoff.attempt and (
                    not u._restart_times
                    or now - u._restart_times[-1] > u.restart_window_s):
                u.backoff.reset()  # stable since the last restart
            return
        if dead and (u._stop.is_set() or u._clean_exit):
            u.state = STOPPED  # clean shutdown OR the loop returning
            return             # normally (finite work done) — not a crash
        if wedged:
            # cannot kill a Python thread: ask it to stop and replace it;
            # the old thread stays visible in the registry until it exits
            u.last_error = u.last_error or \
                f"wedged: no heartbeat for {u.heartbeat_timeout_s}s"
            u._stop.set()
            obs_metrics.supervisor_wedged.inc(unit=u.name)
        self._restart_or_give_up(u, now)

    def _stage_stalled(self, u: SupervisedUnit) -> bool:
        """PR 2 stage-liveness as a probe: the unit's trace stage going
        stale while the unit claims to run means the pipeline behind it
        stopped moving.  Only meaningful when tracing is on AND the
        stage has reported at least once."""
        from ..obs import tracing

        if not tracing.ENABLED:
            return False
        age = tracing.liveness().get(u.stage)
        return age is not None and age > u.stage_timeout_s

    # ------------------------------------------------- probed unit rules
    def _tick_probed_unit(self, u: SupervisedUnit) -> None:
        try:
            ok = bool(u.probe())
        except Exception as e:  # noqa: BLE001 - an unreachable server
            # raises; that IS the negative probe result
            ok = False
            u.last_error = f"probe: {type(e).__name__}: {e}"
        if ok:
            u._probe_misses = 0
            obs_metrics.supervisor_unit_up.set(1, unit=u.name)
            return
        u._probe_misses += 1
        if u._probe_misses < u.probe_failures:
            return
        obs_metrics.supervisor_unit_up.set(0, unit=u.name)
        if u.on_death is not None:
            # the failover hook fires ONCE; re-admission of a recovered
            # peer is an operator action, not a supervisor guess
            u.state = FAILED_OVER
            hook, u.on_death = u.on_death, None
            obs_metrics.supervisor_failovers.inc(unit=u.name)
            hook(u)
            return
        if u.restart_fn is not None:
            self._restart_or_give_up(u, time.monotonic())
        else:
            u.state = CRASHED

    # ----------------------------------------------------------- restart
    def _restart_or_give_up(self, u: SupervisedUnit, now: float) -> None:
        """Both unit kinds restart through the same WAITING/backoff
        state — an immediate external retry would burn the whole storm
        budget in probe_failures × poll_interval (sub-second) and park
        a transiently-down service in DEGRADED forever."""
        obs_metrics.supervisor_unit_up.set(0, unit=u.name)
        if u._budget_exhausted(now):
            u.state = DEGRADED
            obs_metrics.supervisor_degraded.set(1, unit=u.name)
            return
        u._restart_times.append(now)
        u.restarts += 1
        obs_metrics.supervisor_restarts.inc(unit=u.name)
        u.state = WAITING
        u._next_start_at = now + u.backoff.next_delay()
