"""Published stream topology: who leads, at which fencing epoch.

The reference delegates leadership to ZooKeeper-backed Kafka controllers
(SURVEY §L0); the rebuild's equivalent is this small shared object: the
supervisor *publishes* ``(leader address, epoch)`` on every promotion,
and ``KafkaWireBroker`` clients built with ``topology=...`` *resolve*
it on every (re)connect instead of walking a static bootstrap order.
The epoch is the fencing token: monotonically increased at each
promotion, stamped by clients into the wire protocol, and checked by
servers on the log-mutating APIs (produce / offset-commit) — a
resurrected old leader, or a client that slept through a failover,
answers FENCED instead of silently splitting the log.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple


class Topology:
    """Thread-safe (leader, epoch) cell with a fallback server list.

    ``resolve()`` returns ``(servers, epoch)`` where ``servers`` is the
    active leader first, then the remaining known servers (a client that
    cannot reach the published leader still has somewhere to go while a
    promotion is in flight)."""

    def __init__(self, leader: str, epoch: int = 0,
                 fallback: Optional[List[str]] = None):
        self._lock = threading.Lock()
        self._leader = leader
        self._epoch = int(epoch)
        self._fallback = [s for s in (fallback or []) if s != leader]
        #: bumped on every publish so pollers can cheaply detect change
        self.generation = 0

    # ------------------------------------------------------------ write
    def publish(self, leader: str, epoch: int) -> None:
        """Install a new leadership term.  Epochs only move forward —
        a belated publish from a slow failover path must not roll the
        fleet back onto a fenced leader."""
        with self._lock:
            if epoch < self._epoch:
                raise ValueError(
                    f"epoch must be monotonic: have {self._epoch}, "
                    f"got {epoch}")
            old = self._leader
            self._leader = leader
            self._epoch = int(epoch)
            if old != leader and old not in self._fallback:
                self._fallback.append(old)
            self._fallback = [s for s in self._fallback if s != leader]
            self.generation += 1

    def replace_fallback(self, old: Optional[str], new: str) -> None:
        """Swap one fallback address for another (no leadership change).

        The multi-shard ``iotml.cluster.PartitionMap`` keeps every other
        shard's address in each cell's fallback list; when shard X fails
        over, the OTHER cells' fallbacks must learn X's new address —
        without touching their own leader or epoch."""
        with self._lock:
            self._fallback = [s for s in self._fallback
                              if s != old and s != new]
            if new != self._leader:
                self._fallback.append(new)
            self.generation += 1

    # ------------------------------------------------------------- read
    def resolve(self) -> Tuple[List[str], int]:
        with self._lock:
            return [self._leader] + list(self._fallback), self._epoch

    @property
    def leader(self) -> str:
        with self._lock:
            return self._leader

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch
