from .loop import Trainer, TrainState, make_train_step, make_eval_step  # noqa: F401
from .checkpoint import CheckpointManager  # noqa: F401
from .artifacts import ArtifactStore  # noqa: F401
