"""Model artifact store — the GCS-bucket train→predict handoff.

The reference uploads the saved model to a GCS bucket after training and the
predict deployment downloads it fresh on start (cardata-v3.py:229-232,
:255-261; bucket provisioned by terraform main.tf:121-125).  `ArtifactStore`
abstracts that handoff: a local-directory backend (default, also the test
backend) and an optional GCS backend when `google-cloud-storage` is
installed.  Objects are opaque blobs keyed by name, so both orbax checkpoint
dirs (zipped) and h5 files move through the same interface.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Optional


class ArtifactStore:
    """upload/download blobs by name; scheme chosen from the root URI."""

    def __init__(self, root: str):
        self.root = root
        self._gcs = root.startswith("gs://")
        if self._gcs:
            from google.cloud import storage  # optional dep

            bucket_name, _, self._prefix = root[5:].partition("/")
            self._bucket = storage.Client().get_bucket(bucket_name)
        else:
            os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------ blobs
    def upload(self, local_path: str, name: str) -> str:
        """Publish a blob ATOMICALLY under `name`.

        GCS object creation is atomic by the service's own contract; the
        local backend must match it — a plain `shutil.copy2` makes the
        destination visible while half-written, so a reader that trusts
        `exists()` (the tiered-store fetch path does) could download a
        torn blob.  Local uploads stage to a pid-unique tmp and
        `os.replace` into place, then fsync the directory so the rename
        survives a host crash too."""
        if self._gcs:
            blob = self._bucket.blob(os.path.join(self._prefix, name))
            blob.upload_from_filename(local_path)
            return f"{self.root}/{name}"
        dst = os.path.join(self.root, name)
        dirname = os.path.dirname(dst) or "."
        os.makedirs(dirname, exist_ok=True)
        from ..store import fsync_dir

        tmp = dst + f".tmp.{os.getpid()}"
        try:
            shutil.copy2(local_path, tmp)
            os.replace(tmp, dst)  # atomic within a filesystem
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        fsync_dir(dirname)
        return dst

    def list(self, prefix: str = "") -> list:
        """Blob names under `prefix`, sorted.  Staging tmps (the
        `.tmp.<pid>` uploads in flight above) are never listed — a
        sweeper enumerating the store must see only published blobs."""
        if self._gcs:
            full = os.path.join(self._prefix, prefix) if prefix \
                else self._prefix
            names = [b.name for b in self._bucket.list_blobs(prefix=full)]
            if self._prefix:
                names = [n[len(self._prefix):].lstrip("/") for n in names]
            return sorted(n for n in names if ".tmp." not in n)
        base = os.path.join(self.root, prefix) if prefix else self.root
        out = []
        if not os.path.isdir(base):
            return out
        for dirpath, _dirs, files in os.walk(base):
            for f in files:
                rel = os.path.relpath(os.path.join(dirpath, f), self.root)
                if ".tmp." in rel:
                    continue
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def delete(self, name: str) -> bool:
        """Remove one blob; False when it did not exist (idempotent —
        the tier sweeper retries deletions after a crash)."""
        if self._gcs:
            blob = self._bucket.blob(os.path.join(self._prefix, name))
            if not blob.exists():
                return False
            blob.delete()
            return True
        path = os.path.join(self.root, name)
        try:
            os.remove(path)
            return True
        except FileNotFoundError:
            return False

    def download(self, name: str, local_path: str) -> str:
        if self._gcs:
            blob = self._bucket.blob(os.path.join(self._prefix, name))
            blob.download_to_filename(local_path)
            return local_path
        src = os.path.join(self.root, name)
        os.makedirs(os.path.dirname(os.path.abspath(local_path)), exist_ok=True)
        shutil.copy2(src, local_path)
        return local_path

    def exists(self, name: str) -> bool:
        if self._gcs:
            return self._bucket.blob(os.path.join(self._prefix, name)).exists()
        return os.path.exists(os.path.join(self.root, name))

    # --------------------------------------------------- pointer blobs
    # The continuous train→serve handoff (train.live / serve.live) flips a
    # tiny "latest" pointer after each immutable versioned model upload —
    # the reference's predict pods re-download a fixed GCS name on restart
    # (cardata-v3.py:255-261); a long-lived scorer instead polls the
    # pointer and hot-swaps.  Text writes must be atomic so a reader never
    # sees a half-copied name.
    def put_text(self, name: str, text: str) -> None:
        if self._gcs:
            blob = self._bucket.blob(os.path.join(self._prefix, name))
            blob.upload_from_string(text)  # GCS object writes are atomic
            return
        dst = os.path.join(self.root, name)
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        tmp = dst + f".tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(text)
        os.replace(tmp, dst)  # atomic within a filesystem

    def get_text(self, name: str) -> Optional[str]:
        """Pointer read; None while the pointer does not exist yet."""
        if self._gcs:
            blob = self._bucket.blob(os.path.join(self._prefix, name))
            if not blob.exists():
                return None
            return blob.download_as_bytes().decode()
        try:
            with open(os.path.join(self.root, name)) as fh:
                return fh.read()
        except FileNotFoundError:
            return None

    # ------------------------------------------------- checkpoint trees
    def upload_tree(self, local_dir: str, name: str) -> str:
        """Ship a directory (e.g. an orbax step dir) as a zip blob.

        The staging archive gets a unique path: concurrent jobs on one host
        (scaled scorer replicas, parallel trainers) must not interleave
        writes into the same /tmp file."""
        stage = tempfile.mkdtemp(prefix="iotml_up_")
        tmp = shutil.make_archive(os.path.join(stage, name), "zip", local_dir)
        try:
            return self.upload(tmp, f"{name}.zip")
        finally:
            shutil.rmtree(stage, ignore_errors=True)

    def download_tree(self, name: str, local_dir: str) -> str:
        stage = tempfile.mkdtemp(prefix="iotml_dl_")
        tmp = os.path.join(stage, f"{name}.zip")
        self.download(f"{name}.zip", tmp)
        try:
            shutil.unpack_archive(tmp, local_dir, "zip")
        finally:
            shutil.rmtree(stage, ignore_errors=True)
        return local_dir
