"""Checkpoint/resume: orbax model state + explicit stream cursors.

The reference's resume story (SURVEY §5) is two-part: the model moves as a
Keras h5 blob through GCS (cardata-v3.py:227-232, :255-261), and the *data
position* is the Kafka offset, passed as an absolute CLI argument.  Here both
halves live in one orbax checkpoint: params/opt-state/step plus the
`(topic, partition, next_offset)` cursor list from
`StreamConsumer.positions()`, so a restarted trainer resumes both model and
stream exactly where it stopped.

Crash safety (ISSUE 7 satellite): a save stages into a hidden temp
directory and is RENAMED into place (one atomic publication, parent dir
fsynced via the store's ``fsync_dir`` — durability promises live in one
package), so a kill mid-save can never leave a half-written ``step_*``
directory under the canonical name; ``restore()`` walks steps newest-
first and SKIPS a torn/corrupt checkpoint back to the newest intact one
instead of raising mid-unpickle.  For async + versioned + hot-swappable
checkpoints use ``iotml.mlops`` — this manager remains the minimal
single-trainer resume primitive.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..store import fsync_dir


class CheckpointManager:
    """Thin orbax wrapper: save/restore (state pytree, cursors)."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ckpt = ocp.PyTreeCheckpointer()
        #: torn/corrupt step dirs skipped by the last restore() walk
        self.skipped_torn = 0

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def save(self, state, cursors=None, step: Optional[int] = None):
        step = int(state.step) if step is None else step
        payload = {
            "params": jax.device_get(state.params),
            "opt_state": jax.device_get(state.opt_state),
            "step": np.asarray(int(state.step)),
            "cursors": [list(c) for c in (cursors or [])],
        }
        final = self._path(step)
        # stage under a hidden name, publish by rename: readers (and
        # latest_step) can never observe a partially-written step dir,
        # and a kill mid-save leaves only a .tmp orphan save() reclaims
        tmp = os.path.join(self.directory, f".tmp_step_{step:010d}")
        shutil.rmtree(tmp, ignore_errors=True)
        self._ckpt.save(tmp, payload, force=True)
        shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)
        fsync_dir(self.directory)
        return final

    def steps(self) -> list:
        """Committed step ids, ascending (staged .tmp dirs excluded)."""
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("step_"):
                try:
                    steps.append(int(name.split("_")[1]))
                except ValueError:
                    continue
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None) -> Optional[dict]:
        """Restore `step`, or the newest INTACT checkpoint.

        With no explicit step, a torn latest (pre-atomic-save legacy, a
        bit-rotted disk, manual surgery) is skipped — newest-first —
        back to the first checkpoint that loads, instead of raising
        mid-unpickle and bricking the resume path.  An explicit step
        still raises: the caller named it, silence would lie."""
        self.skipped_torn = 0
        if step is not None:
            return self._load(step)
        for s in reversed(self.steps()):
            try:
                return self._load(s)
            except Exception:  # noqa: BLE001 - any torn artifact
                # (truncated msgpack, missing leaf file, bad metadata)
                self.skipped_torn += 1
                continue
        return None

    def _load(self, step: int) -> dict:
        payload = self._ckpt.restore(self._path(step))
        payload["cursors"] = [tuple([c[0], int(c[1]), int(c[2])])
                              for c in payload.get("cursors", [])]
        return payload
