"""Checkpoint/resume: orbax model state + explicit stream cursors.

The reference's resume story (SURVEY §5) is two-part: the model moves as a
Keras h5 blob through GCS (cardata-v3.py:227-232, :255-261), and the *data
position* is the Kafka offset, passed as an absolute CLI argument.  Here both
halves live in one orbax checkpoint: params/opt-state/step plus the
`(topic, partition, next_offset)` cursor list from
`StreamConsumer.positions()`, so a restarted trainer resumes both model and
stream exactly where it stopped.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin orbax wrapper: save/restore (state pytree, cursors)."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ckpt = ocp.PyTreeCheckpointer()

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def save(self, state, cursors=None, step: Optional[int] = None):
        step = int(state.step) if step is None else step
        payload = {
            "params": jax.device_get(state.params),
            "opt_state": jax.device_get(state.opt_state),
            "step": np.asarray(int(state.step)),
            "cursors": [list(c) for c in (cursors or [])],
        }
        self._ckpt.save(self._path(step), payload, force=True)
        return self._path(step)

    def latest_step(self) -> Optional[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("step_"):
                steps.append(int(name.split("_")[1]))
        return max(steps) if steps else None

    def restore(self, step: Optional[int] = None) -> Optional[dict]:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        payload = self._ckpt.restore(self._path(step))
        payload["cursors"] = [tuple([c[0], int(c[1]), int(c[2])])
                              for c in payload.get("cursors", [])]
        return payload
