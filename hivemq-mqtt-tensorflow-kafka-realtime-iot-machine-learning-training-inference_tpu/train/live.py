"""Continuous stream training with per-round artifact publication.

The reference's training side is a K8s Job that fits one slice, uploads the
model to GCS, and exits; `run.sh:16-91` then re-runs it and restarts the
predict pods so they download the new weights — a restart loop standing in
for continuous learning.  `ContinuousTrainer` is that loop as a long-lived
process: a persistent consumer cursor over the stream, fixed-shape training
rounds (so the scanned/fused fit compiles once), and an immutable versioned
model upload + atomic "latest"-pointer flip after every round, which a
`serve.live.LiveScorer` polls to hot-swap mid-stream.

Round shape: each round trains on exactly `take_batches` full batches
(fixed [S, B, F] → one compiled program for every round).  Rounds start
only once the stream has at least `min_available` new records, so a round
never stalls mid-fit waiting on the fleet.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Callable, Optional

import numpy as np

from ..chaos import faults as chaos
from ..data.dataset import SensorBatches
from ..obs import metrics as obs_metrics
from ..obs import watermark
from ..stream.consumer import StreamConsumer
from .artifacts import ArtifactStore
from .loop import Trainer


def commit_manifest_offsets(broker, group: str, manifest) -> None:
    """Commit a durable manifest's stamped offsets for ``group``,
    FORWARD-ONLY and commit_many-batched — the shared post-durability
    half of offsets-as-checkpoint (``committed <= newest-durable-
    manifest`` at every instant).  Runs on the checkpoint-writer
    thread for both the micro-batch ``ContinuousTrainer`` and the
    per-window ``iotml.online`` learner, so the two training modes
    keep ONE crash-consistency story."""
    by_topic: dict = {}
    for t, p, off in manifest.offsets:
        cur = broker.committed(group, t, p)
        if cur is None or off > cur:
            by_topic.setdefault(t, []).append((p, off))
    commit_many = getattr(broker, "commit_many", None)
    for t, entries in by_topic.items():
        if commit_many is not None:
            commit_many(group, t, entries)
        else:
            for p, off in entries:
                broker.commit(group, t, p, off)


class ContinuousTrainer:
    """Round-based continuous training → versioned artifacts + pointer.

    Args:
      broker: Broker duck-type (in-process or a wire client).
      topic: input stream (the reference's SENSOR_DATA_S_AVRO leg).
      store/model_name: artifact root and the h5 blob base name; round K
        uploads `{model_name}.r{K}` then flips pointer `{model_name}.latest`.
      group: consumer group; the cursor resumes from committed offsets and
        commits after each round (the `committed` contract of the CLIs).
      take_batches × batch_size: records per round (reference job: 100×100
        per epoch, cardata-v3.py:217-222 — default 20×100 keeps rounds
        sub-second so the scorer sees fresh weights quickly).
    """

    def __init__(self, broker, topic: str, store: Optional[ArtifactStore],
                 model_name: str = "cardata-live.h5",
                 group: str = "cardata-live-train",
                 model=None, batch_size: int = 100, take_batches: int = 20,
                 epochs_per_round: int = 1, only_normal: bool = True,
                 learning_rate: float = 1e-3, normalizer=None,
                 backfill_since_ms: Optional[int] = None,
                 registry=None, checkpointer=None, warm_start: bool = True,
                 checkpoint_interval_s: float = 0.0,
                 mesh=None, device_normalize: bool = False):
        if model is None:
            from ..models.autoencoder import CAR_AUTOENCODER

            model = CAR_AUTOENCODER
        if store is None and registry is None and checkpointer is None:
            raise ValueError("need an ArtifactStore, a ModelRegistry, or "
                             "an AsyncCheckpointer to publish models to")
        self.broker = broker
        self.topic = topic
        self.store = store
        self.model_name = model_name
        self.group = group
        self.model = model
        self.batch_size = batch_size
        self.take_batches = take_batches
        self.epochs_per_round = epochs_per_round
        # mesh mode (ISSUE 15): partition-parallel columnar feeds into a
        # sharded train step — each data-axis device owns a partition
        # subset and a take_batches round trains D× the records of the
        # single-chip shape.  device_normalize additionally folds the
        # affine normalization into the jitted step (feeds ship raw
        # columns).  Checkpoints/restore ride the SAME surface: the
        # sharded state gathers host-side at snapshot, so a manifest
        # stamps every device's cursors as one atomic unit.
        self.mesh = mesh
        if device_normalize and mesh is None:
            # same contract as OnlineLearner: the affine fold lives in
            # the sharded step — silently falling back to host
            # normalization would mask a misconfiguration
            raise ValueError("device_normalize needs a mesh (the affine "
                             "fold lives in the sharded step)")
        if mesh is not None:
            if epochs_per_round != 1:
                raise ValueError("mesh streaming rounds are single-epoch "
                                 "(the cursor is the slice)")
            from ..core.normalize import CAR_NORMALIZER
            from ..parallel.streaming import (MeshFeeds,
                                              ShardedStreamTrainer)

            n_dev = mesh.shape["data"]
            feeds = MeshFeeds(broker, topic, n_dev, group=group,
                              batch_size=batch_size,
                              take_batches=take_batches,
                              only_normal=only_normal,
                              normalizer=normalizer,
                              device_normalize=device_normalize,
                              poll_chunk=8192)
            self.trainer = ShardedStreamTrainer(
                model, mesh, feeds, learning_rate=learning_rate,
                normalizer=(normalizer or CAR_NORMALIZER)
                if device_normalize else None)
        else:
            self.trainer = Trainer(model, learning_rate=learning_rate)
        # versioned-registry mode (iotml.mlops): checkpoints publish
        # async into the registry, each stamped with the cursors it was
        # trained through, and the GROUP COMMIT trails checkpoint
        # durability (the writer commits the manifest's offsets after
        # publication) — so committed <= manifest offsets always, and a
        # crash resumes model + stream position as one consistent unit
        self.registry = registry
        self.checkpointer = checkpointer
        if registry is not None and checkpointer is None:
            from ..mlops.checkpoint import AsyncCheckpointer

            self.checkpointer = AsyncCheckpointer(
                registry, min_interval_s=checkpoint_interval_s)
        if self.checkpointer is not None:
            self.registry = self.checkpointer.registry
            self.checkpointer.commit_fn = self._commit_checkpointed
        parts = range(broker.topic(topic).partitions)
        self._parts = list(parts)
        # ONE persistent cursor for the process lifetime: rebuilding a
        # consumer per round (and re-reading committed offsets) was the
        # dominant cost of the naive loop.  Mesh mode: the feeds ARE the
        # cursor — one facade over every device's consumer, positions()
        # spanning all partitions so offsets-as-checkpoint still names
        # the whole trained frontier.
        if mesh is not None:
            self.consumer = self.trainer.feeds
        else:
            self.consumer = StreamConsumer.from_committed(
                broker, topic, parts, group=group)
        # registry warm start: reload the newest committed version's
        # weights (+ optimizer moments when archived) and its stamped
        # offsets — the manifest beats BOTH offset 0 and backfill for
        # its partitions, because the restored model already knows the
        # data up to those cursors (re-reading it is double-train, and
        # a timestamp seek past them is a gap in the model's knowledge)
        manifest_offsets = {}
        if self.registry is not None and warm_start:
            from ..mlops.checkpoint import restore_trainer

            m = restore_trainer(self.trainer, self.registry)
            if m is not None:
                manifest_offsets = {(t, p): off for t, p, off in m.offsets}
                self.restored_version: Optional[int] = m.version
            else:
                self.restored_version = None
        else:
            self.restored_version = None
        # cold-start backfill (the durable store's replay API): a FIRST
        # incarnation of this group — no committed cursor, no manifest —
        # starts from the log's history at `backfill_since_ms` instead
        # of offset 0 of whatever happens to be retained, so a trainer
        # deployed against a long-retained durable topic trains on
        # exactly the requested window.  Partitions WITH a committed
        # cursor or a manifest cursor are never moved (resume beats
        # replay; the committed contract stays intact).
        if backfill_since_ms is not None:
            oft = getattr(broker, "offset_for_timestamp", None)
            if oft is not None:
                for p in parts:
                    if broker.committed(group, topic, p) is None and \
                            (topic, p) not in manifest_offsets:
                        self.consumer.seek(
                            topic, p, oft(topic, p, backfill_since_ms))
        # apply manifest cursors FORWARD-ONLY: committed can trail the
        # manifest (commit follows checkpoint) but must never be
        # rewound — commits stay monotonic even across a restore
        for (t, p), off in manifest_offsets.items():
            cur = broker.committed(group, t, p) or 0
            if off > cur:
                self.consumer.seek(t, p, off)
        # large poll chunks: each wire fetch is a round trip into the
        # broker process (expensive when that process is busy), and the
        # batcher's poll budgeting (_need_rows) guarantees a bounded
        # iteration never over-polls past the `take` boundary
        if mesh is None:
            batch_kw = {} if normalizer is None \
                else dict(normalizer=normalizer)
            self.batches = SensorBatches(self.consumer,
                                         batch_size=batch_size,
                                         take=take_batches,
                                         only_normal=only_normal,
                                         poll_chunk=8192, **batch_kw)
        else:
            # the per-device batchers live inside the feeds; rounds are
            # driven through the sharded trainer's fit_compiled shim
            self.batches = None
        self.rounds = 0
        self.records_trained = 0
        self.last_loss: Optional[float] = None
        #: new records required before a round starts — padded ~10% over
        #: the round size so the label filter cannot starve the last
        #: batch; a mesh round consumes one take_batches budget PER
        #: device
        round_records = take_batches * batch_size * \
            (mesh.shape["data"] if mesh is not None else 1)
        self.min_available = int(round_records * 1.1) + 1

    # ------------------------------------------------------------ rounds
    def available(self) -> int:
        """Records between the persistent cursor and the log end."""
        return sum(self.broker.end_offset(t, p) - off
                   for t, p, off in self.consumer.positions())

    def train_round(self) -> dict:
        """One fixed-shape fit over the next slice + artifact publish."""
        t0 = time.perf_counter()
        history = self.trainer.fit_compiled(self.batches,
                                            epochs=self.epochs_per_round)
        if not history["loss"]:
            return {}
        self.rounds += 1
        self.records_trained += history["records"][-1] * self.epochs_per_round
        self.last_loss = float(history["loss"][-1])
        obs_metrics.live_train_rounds.inc()
        obs_metrics.live_train_loss.set(self.last_loss)
        # the round's slice is fully trained: publish the ingest→train
        # watermark from the event-time ranges the consume paths folded
        # (ISSUE 13) — batch-granular, exact on the columnar plane
        watermark.observe_taken("train", self.consumer.take_event_time(),
                                group=self.group)
        if self.checkpointer is not None:
            # async path: capture (device->host) the state + the exact
            # cursors it was trained through and return to training —
            # serialize/fsync happen on the writer thread, and the
            # GROUP COMMIT trails durability (_commit_checkpointed runs
            # after the manifest lands), so a crash at ANY point
            # resumes model + stream position as one consistent unit
            self._snapshot()
            artifact = f"registry:r{self.rounds}"
            if self.store is not None:  # legacy pointer riders along
                artifact = self.publish()
        else:
            artifact = self.publish()
            # commit AFTER the artifact is durable (the `committed`
            # resume contract: a crash re-trains the slice rather than
            # skipping it)
            self.consumer.commit()
        return {"t": time.time(), "round": self.rounds,
                "loss": self.last_loss,
                "records": history["records"][-1],
                "records_cum": self.records_trained,
                "seconds": round(time.perf_counter() - t0, 4),
                "artifact": artifact}

    def publish(self) -> str:
        """Upload round K's weights as an immutable blob, flip the pointer."""
        import jax

        from ..models.h5_export import autoencoder_params_to_h5

        name = f"{self.model_name}.r{self.rounds}"
        with tempfile.TemporaryDirectory(prefix="iotml_live_") as tmp:
            local = os.path.join(tmp, "model.h5")
            autoencoder_params_to_h5(
                jax.tree.map(np.asarray, self.trainer.state.params), local)
            self.store.upload(local, name)
        self.store.put_text(f"{self.model_name}.latest", name)
        return name

    def _snapshot(self, force: bool = False) -> None:
        """Enqueue the current state + cursors for the async writer.
        The checkpointer's cadence throttle may coalesce it away
        (tracked so a clean exit can force-archive the newest state)."""
        if not self.checkpointer.would_accept(force):
            # skip the capture entirely: positions() plus one broker
            # end_offset round trip per partition is wasted work on a
            # snapshot the throttle would discard — with sub-second
            # rounds that's nearly every round
            self.checkpointer.coalesced += 1
            self._last_coalesced = True
            return
        before = self.checkpointer.coalesced
        cursors = self.consumer.positions()
        ends = {(t, p): self.broker.end_offset(t, p)
                for t, p, _off in cursors}
        self.checkpointer.snapshot(
            self.trainer.state, cursors,
            metrics={"loss": self.last_loss if self.last_loss is not None
                     else float("nan"),
                     "records": float(self.records_trained)},
            end_offsets=ends, force=force)
        self._last_coalesced = self.checkpointer.coalesced > before

    def _commit_checkpointed(self, manifest) -> None:
        """The writer's post-durability hook: commit the manifest's
        stamped offsets for this group, FORWARD-ONLY (see
        ``commit_manifest_offsets``).  A skipped (dropped) snapshot
        just means the next one commits further ahead."""
        commit_manifest_offsets(self.broker, self.group, manifest)

    def close(self, timeout_s: float = 30.0) -> None:
        """Flush pending checkpoints and stop an owned writer thread."""
        if self.checkpointer is not None:
            self.checkpointer.stop(flush=True, timeout_s=timeout_s)

    def run(self, stop: Optional[Callable[[], bool]] = None,
            max_rounds: Optional[int] = None,
            poll_interval_s: float = 0.05,
            on_round: Optional[Callable[[dict], None]] = None) -> int:
        """Train rounds until `stop()` or `max_rounds`; returns rounds run."""
        if self.checkpointer is not None:
            # live mode owns its writer thread (idempotent; a no-op when
            # a supervisor registered unit_loop() instead); deterministic
            # tests call train_round() + write_once() directly
            self.checkpointer.start()
        start = self.rounds
        while (stop is None or not stop()) and \
                (max_rounds is None or self.rounds - start < max_rounds):
            chaos.point("trainer.poll")
            if self.available() < self.min_available:
                time.sleep(poll_interval_s)
                continue
            stats = self.train_round()
            if stats and on_round is not None:
                on_round(stats)
        if self.checkpointer is not None:
            # the newest state must not die on a clean exit: re-enqueue
            # it when the cadence throttle coalesced the last round's
            # snapshot, then drain the queue
            if self.rounds > start and getattr(self, "_last_coalesced",
                                               False):
                self._snapshot(force=True)
            self.checkpointer.flush(timeout_s=30.0)
        return self.rounds - start
