"""jit-compiled micro-batch streaming training.

The reference trains with `autoencoder.fit(dataset, epochs=20)` over a
batched Kafka stream (cardata-v3.py:220-222): micro-batch streaming
ingestion, *not* online learning (reference README.md:130-140) — every epoch
re-reads the topic from the start offset.

TPU-first translation:
- one `jax.jit` train step, donated state, fixed [B, F] shapes (padded tails
  carry a validity mask so the step never recompiles);
- loss = masked MSE + Keras activity-regularizer penalty (models/autoencoder);
- the Keras `accuracy` metric quirk (elementwise equality on a regression —
  what `metrics=['accuracy']` resolves to under MSE loss) is reproduced so
  history dicts match the reference logs' shape;
- epochs iterate the *stream* via `SensorBatches.epochs`, preserving the
  re-read-from-offset semantics.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict

from ..obs import metrics as obs_metrics
from ..obs import tracing


@struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: FrozenDict
    opt_state: optax.OptState
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, model, rng, sample_x, tx: Optional[optax.GradientTransformation] = None,
               learning_rate: float = 1e-3, tx_key=None):
        """Init params from a sample batch. lr 1e-3 = Keras Adam default
        (what `optimizer='adam'` means in the reference).

        Params AND optimizer state init under ONE jit (cached per
        (model, optimizer)): flax's eager init executes the full forward
        op-by-op and optax's init is an eager zeros-op per param leaf —
        over a TPU tunnel each eager op is a network round trip, which
        made a fresh recurrent Trainer cost seconds before training at
        all.  `tx_key` is the hashable cache descriptor when the caller
        built the optimizer itself (a fresh optax object per Trainer
        would otherwise defeat the cache by identity)."""
        tx = tx or optax.adam(learning_rate)
        init = jitted_state_init(model, tx, tx_key=tx_key)
        params, opt_state = init(rng, jnp.asarray(sample_x))
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=opt_state, apply_fn=model.apply, tx=tx)


def _masked_mse(pred, target, mask):
    """Mean squared error over valid rows only (mask is [B] of 0/1)."""
    per_elem = jnp.square(pred - target)
    # broadcast mask over trailing dims
    m = mask.reshape(mask.shape + (1,) * (per_elem.ndim - 1))
    denom = jnp.maximum(jnp.sum(m) * per_elem[0].size, 1.0)
    return jnp.sum(per_elem * m) / denom


def _keras_accuracy(pred, target, mask):
    m = mask.reshape(mask.shape + (1,) * (pred.ndim - 1))
    eq = (pred == target).astype(jnp.float32) * m
    return jnp.sum(eq) / jnp.maximum(jnp.sum(m) * pred[0].size, 1.0)


def make_loss_fn(model, supervised: bool = False):
    """Loss closure.  Autoencoder mode targets the input itself
    (zip(x, x), cardata-v3.py:218); supervised mode uses (x, y) windows."""

    def loss_fn(params, x, y, mask):
        out = model.apply({"params": params}, x, with_penalty=True) \
            if not supervised else (model.apply({"params": params}, x), 0.0)
        pred, penalty = out if isinstance(out, tuple) else (out, 0.0)
        target = x if not supervised else y
        loss = _masked_mse(pred, target, mask) + penalty
        return loss, (pred, target)

    return loss_fn


def make_raw_train_step(model, tx, supervised: bool = False,
                        row_loss: bool = False):
    """Un-jitted step — `parallel.data_parallel` re-jits it with mesh
    shardings; single-chip callers use `make_train_step`.

    ``row_loss=True`` adds ``metrics["row_loss"]``: the per-row masked
    pre-update MSE ([B], padding rows 0).  Under a mesh the vector stays
    sharded over 'data', so each device's rows land back on their own
    chip — the per-chip drift-detector signal (iotml.online) at zero
    collective cost; scan paths that ignore it have it dead-code
    eliminated."""
    loss_fn = make_loss_fn(model, supervised)

    def step(state: TrainState, x, y, mask):
        (loss, (pred, target)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params, x, y, mask)
        updates, opt_state = state.tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "accuracy": _keras_accuracy(pred, target, mask)}
        if row_loss:
            per_elem = jnp.square(pred - target)
            metrics["row_loss"] = jnp.mean(
                per_elem.reshape(per_elem.shape[0], -1), axis=-1) * mask
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state), metrics

    return step


def make_train_step(model, tx, supervised: bool = False):
    return jax.jit(make_raw_train_step(model, tx, supervised))


def make_scanned_fit(model, tx, supervised: bool = False):
    """Whole-fit-as-one-XLA-program: lax.scan over batches (inner) and
    epochs (outer), state donated, data device-resident.

    Per-step dispatch is the TPU throughput killer for small models — the
    reference's 100-row batches are microseconds of MXU work, so a
    step-per-dispatch loop is pure host/link latency.  Scanning the entire
    fit compiles once and runs N_epochs × N_batches updates in a single
    device program; numerically identical to the step loop.
    """
    raw = make_raw_train_step(model, tx, supervised)

    def fit(state: TrainState, xs, ys, masks, epochs: int):
        def batch_step(st, inp):
            x, y, m = inp
            st, metrics = raw(st, x, y, m)
            return st, (metrics["loss"], metrics["accuracy"])

        def epoch_step(st, _):
            st, (losses, accs) = jax.lax.scan(batch_step, st, (xs, ys, masks))
            return st, (jnp.mean(losses), jnp.mean(accs))

        return jax.lax.scan(epoch_step, state, None, length=epochs)

    return jax.jit(fit, static_argnames=("epochs",), donate_argnums=(0,))


# jax.jit caches per function object; a fresh closure per fit_compiled call
# would re-trace (and without backend caching, re-compile) every time.  Keyed
# on (model, tx identity-or-descriptor, supervised) so repeated jobs — e.g.
# bench warm passes, periodic retrains — reuse the compiled program.
# Bounded LRU (not a bare dict): the closures hold their models strongly,
# so an unbounded cache in a long-lived process that rebuilds models per
# retrain cycle would pin every dead model and compiled program forever.
_CACHE_LIMIT = 8
_SCANNED_CACHE: OrderedDict = OrderedDict()
_EVAL_CACHE: OrderedDict = OrderedDict()
_INIT_CACHE: OrderedDict = OrderedDict()


def _lru_get(cache, key, make):
    fn = cache.get(key)
    if fn is None:
        fn = cache[key] = make()
        if len(cache) > _CACHE_LIMIT:
            cache.popitem(last=False)  # evict least-recently used
    else:
        cache.move_to_end(key)
    return fn


def adam_cached(learning_rate: float) -> optax.GradientTransformation:
    """One optax.adam object per learning rate.

    `TrainState.tx` is a static (non-pytree) field, and a fresh
    `optax.adam(lr)` builds fresh init/update closures that compare
    UNEQUAL to the last one — so every fresh Trainer used to retrace and
    recompile the scanned fit (~4 s on a TPU tunnel) even though the
    program was identical.  Sharing the object makes the static field
    compare equal and the compile cache hit."""
    return _lru_get(_INIT_CACHE, ("adam-tx", learning_rate),
                    lambda: optax.adam(learning_rate))


def adam_injectable_cached(learning_rate: float
                           ) -> optax.GradientTransformation:
    """Adam with RUNTIME-mutable hyperparameters (optax
    inject_hyperparams): the learning rate lives in ``opt_state
    .hyperparams`` as a traced array, so the online learner's
    drift-triggered LR boost is an opt_state edit — no retrace, no
    recompile, same jitted step.  Cached per initial rate for the same
    compile-cache reason as ``adam_cached`` (the tx object's identity
    keys the jit caches)."""
    return _lru_get(
        _INIT_CACHE, ("adam-inject-tx", learning_rate),
        lambda: optax.inject_hyperparams(optax.adam)(
            learning_rate=learning_rate))


def jitted_state_init(model, tx, tx_key=None):
    """jit-compiled (params, opt_state) init, cached per (model, tx)."""
    key = (model, tx_key if tx_key is not None else id(tx))

    def make():
        @jax.jit
        def init(rng, x):
            params = model.init(rng, x)["params"]
            return params, tx.init(params)

        return init

    return _lru_get(_INIT_CACHE, key, make)


def scanned_fit_cached(model, tx, supervised: bool, tx_key=None):
    key = (model, tx_key if tx_key is not None else id(tx), supervised)
    return _lru_get(_SCANNED_CACHE, key,
                    lambda: make_scanned_fit(model, tx, supervised))


def make_scanned_window_steps(model, tx, supervised: bool = False):
    """K sequential SGD updates as ONE device program (lax.scan),
    returning the per-window pre-update losses — the online learner's
    catch-up path.  Numerically identical to K single steps; what
    changes is dispatch: one jit call + one host→device transfer per
    GROUP instead of per window, which is the difference between the
    incremental mode meeting its throughput SLO and not (measured:
    0.62× → >1× of micro-batch train rate at K=8).  The per-window
    loss vector keeps drift detection at window granularity even
    through a fused group."""
    raw = make_raw_train_step(model, tx, supervised)

    def run(state: TrainState, xs, masks):
        def step(st, inp):
            x, m = inp
            st, metrics = raw(st, x, x, m)
            return st, metrics["loss"]

        return jax.lax.scan(step, state, (xs, masks))

    return jax.jit(run, donate_argnums=(0,))


def scanned_window_steps_cached(model, tx, tx_key=None):
    key = (model, tx_key if tx_key is not None else id(tx), "winscan")
    return _lru_get(_SCANNED_CACHE, key,
                    lambda: make_scanned_window_steps(model, tx))


def make_eval_step(model, supervised: bool = False):
    """jit eval closure, cached per model (bounded LRU, see
    _SCANNED_CACHE): every StreamScorer (and each serve drain in a
    restart-per-drain deployment) calls this, and a fresh jit closure per
    call would recompile the eval program each time — ~0.6s per drain on
    a TPU tunnel, dominating a 10k-row drain."""
    def make():
        @jax.jit
        def step(params, x):
            return model.apply({"params": params}, x)

        return step

    return _lru_get(_EVAL_CACHE, model, make)


class Trainer:
    """model.fit for streams: epochs × batches with history, like Keras."""

    def __init__(self, model, rng=None, learning_rate: float = 1e-3,
                 supervised: bool = False, tx=None):
        self.model = model
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        # tx_key: hashable descriptor for the jit cache when we built the
        # optimizer ourselves (a user-supplied tx is keyed by identity)
        self._tx_key = ("adam", learning_rate) if tx is None else None
        self.learning_rate = learning_rate
        self.tx = tx or adam_cached(learning_rate)
        self.supervised = supervised
        self.state: Optional[TrainState] = None
        self._step = None

    def _ensure_state(self, sample_x):
        if self.state is None:
            self.state = TrainState.create(self.model, self.rng, sample_x,
                                           tx=self.tx, tx_key=self._tx_key)
            self._step = make_train_step(self.model, self.tx, self.supervised)

    def fit(self, batches, epochs: int = 1, verbose: bool = False,
            callbacks=()) -> dict:
        """batches: SensorBatches (or any iterable-of-Batch with .epochs).

        This is the Keras-shaped per-step loop: it re-reads the stream
        every epoch and fires callbacks per batch — but each step is one
        device dispatch (~150-200ms over a TPU tunnel), so prefer
        `fit_compiled` for anything but live-stream/callback training.
        When the batch source is a frozen slice (`cache=True`) and no
        per-batch observation is requested, the two are semantically
        identical and this delegates automatically."""
        if not callbacks and not verbose and getattr(batches, "cache", False):
            return self.fit_compiled(batches, epochs)
        history = {"loss": [], "accuracy": [], "records": [], "seconds": []}
        epoch_iter = batches.epochs(epochs) if hasattr(batches, "epochs") \
            else (iter(batches) for _ in range(epochs))
        for e, it in enumerate(epoch_iter):
            t0 = time.perf_counter()
            tot_loss = tot_acc = 0.0
            n = records = 0
            for b in it:
                self._ensure_state(b.x)
                y = b.y if b.y is not None else b.x
                with obs_metrics.train_step_seconds.time():
                    self.state, m = self._step(self.state, b.x, y, b.mask)
                obs_metrics.records_trained.inc(b.n_valid)
                tot_loss += float(m["loss"])
                tot_acc += float(m["accuracy"])
                n += 1
                records += b.n_valid
                for cb in callbacks:
                    cb.on_batch_end(b, m)
            dt = time.perf_counter() - t0
            if tracing.ENABLED and hasattr(batches, "take_traces"):
                # every record decoded this epoch went through the step:
                # close with the e2e (ingest → train) span.  Epoch 2+ of a
                # stream re-read decodes the same records again — each
                # re-read is its own trace only if re-injected upstream,
                # so typically only the first epoch closes spans.
                for ctx in batches.take_traces():
                    ctx.close("train")
            history["loss"].append(tot_loss / max(n, 1))
            history["accuracy"].append(tot_acc / max(n, 1))
            history["records"].append(records)
            history["seconds"].append(dt)
            if verbose:
                print(f"epoch {e + 1}/{epochs} - loss {history['loss'][-1]:.6f} "
                      f"- {records} records - {dt:.2f}s")
        return history

    def fit_compiled(self, batches, epochs: int = 1, fused: str = "auto"
                     ) -> dict:
        """One-XLA-program fit: decode the epoch's batches once, move them to
        device, and run all epochs × batches inside a single jitted
        `lax.scan` (see `make_scanned_fit`).  Semantically identical to
        `fit` over an immutable log slice; orders of magnitude less dispatch
        overhead for small step sizes.

        fused: "auto" additionally collapses the whole fit into ONE Pallas
        kernel when the model/optimizer match `ops.fused_train`'s contract
        (the DenseAutoencoder + Adam hot path — another ~7× on top of the
        scan by eliminating per-step kernel dispatch); "never" forces the
        scan; "always" raises if unsupported."""
        import numpy as np

        t0 = time.perf_counter()
        # Staging policy, measured on the TPU tunnel: per-TRANSFER
        # completion latency dominates (each host→device transfer the
        # program waits on costs a tunnel round trip that swings 20-150 ms
        # with the weather), so the slice is decoded, stacked once, and
        # shipped as ONE device_put of the (xs, masks) pair.  A chunked
        # double-buffered variant (device_put per 32 batches overlapping
        # the stream decode) was tried and reverted: the decode it hides
        # is ~0.15 s while the extra transfer waits cost up to ~0.8 s on a
        # slow tunnel — on locally-attached TPUs the trade flips, and the
        # multi-chip path's DevicePrefetcher does overlap there.
        #
        # Iterate via .epochs(1) when the source has it: for a cache=True
        # SensorBatches that's what populates the replay cache (a bare
        # iter() would consume the stream without caching, and a later
        # fit over the same source would see nothing).
        it = next(batches.epochs(1)) if hasattr(batches, "epochs") \
            else iter(batches)
        with obs_metrics.step_seconds.time(loop="train",
                                           phase="host_pipeline"):
            # the host leg of the round: poll + decode + batch assembly
            # all happen inside the batcher's iterator
            bs = list(it)
        if not bs:
            return {"loss": [], "accuracy": [], "records": [], "seconds": []}
        xs = np.stack([b.x for b in bs])
        masks = np.stack([b.mask for b in bs])
        records = sum(b.n_valid for b in bs)
        self._ensure_state(bs[0].x)

        from ..ops import fused_train

        activity_l1 = getattr(self.model, "activity_l1", None)
        use_fused = fused != "never" and \
            fused_train.supported(self.state, self.supervised) and \
            self._tx_key is not None and \
            activity_l1 is not None and \
            xs.nbytes <= fused_train.VMEM_DATA_BUDGET_BYTES
        if fused == "always" and not use_fused:
            raise ValueError("fused fit unsupported for this model/optimizer/"
                             "slice size")
        # device leg: transfer + compiled program + the one sync below —
        # measured through the device_get because dispatch is async and
        # the program is not "done" until the host observes its results
        t_dev = time.perf_counter()
        if use_fused:
            xs, masks = jax.device_put((xs, masks))
            self.state, losses, accs = fused_train.fused_fit(
                self.state, xs, masks, epochs,
                lr=self.learning_rate, l1=activity_l1)
        else:
            scanned = scanned_fit_cached(self.model, self.tx, self.supervised,
                                         tx_key=self._tx_key)
            if any(b.y is not None for b in bs):
                ys = np.stack([b.y if b.y is not None else b.x for b in bs])
                xs, ys, masks = jax.device_put((xs, ys, masks))
            else:
                # autoencoder mode targets the input itself: reuse the
                # transferred xs instead of shipping a byte-identical copy
                xs, masks = jax.device_put((xs, masks))
                ys = xs
            self.state, (losses, accs) = scanned(self.state, xs, ys, masks,
                                                 epochs)
        obs_metrics.records_trained.inc(records * epochs)
        if tracing.ENABLED and hasattr(batches, "take_traces"):
            # the whole fit ran as one device program: per-record close
            # lands here, after the scan — the e2e span includes the
            # compiled fit, which is exactly what ingest-to-train means
            # for this path
            for ctx in batches.take_traces():
                ctx.close("train")
        # ONE sync for both metric vectors: each device_get is a full
        # tunnel round trip, and the second would wait on nothing new
        losses, accs = (np.asarray(a)
                        for a in jax.device_get((losses, accs)))
        obs_metrics.step_seconds.observe(time.perf_counter() - t_dev,
                                         loop="train",
                                         phase="device_compute")
        dt = time.perf_counter() - t0
        return {"loss": losses.tolist(), "accuracy": accs.tolist(),
                "records": [records] * epochs, "seconds": [dt / epochs] * epochs}

    def predict(self, batches, callbacks=(), params=None):
        """Batched jit inference; calls callbacks with (batch, outputs) for
        ordered write-back (the OutputCallback pattern, cardata-v3.py:243-249).
        `params` overrides trained state (e.g. weights loaded from h5/orbax)."""
        ev = make_eval_step(self.model, self.supervised)
        params = params if params is not None else self.state.params
        outs = []
        for b in batches:
            out = ev(params, b.x)
            for cb in callbacks:
                cb.on_predict_batch_end(b, out)
            outs.append(jax.device_get(out)[: b.n_valid])
        return outs
