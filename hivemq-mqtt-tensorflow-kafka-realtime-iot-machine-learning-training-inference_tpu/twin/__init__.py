"""iotml.twin — the per-car digital twin as a queryable feature store.

The reference maintains a digital twin of every car in MongoDB via a
Kafka Connect sink (PAPER.md L6: one document per car, latest state
wins).  This package is the streaming-native version of that layer:
`TwinService` materialises per-car state (latest sensor reading +
rolling-window aggregates) straight from the sensor stream, changelogs
every update to the compacted ``CAR_TWIN`` topic (``iotml.store``'s
key-based compaction keeps it bounded at ~one record per car), and
rebuilds its table FROM that changelog after a crash — the Kafka
Streams state-store pattern, with the commit log as the only storage.

Exposed two ways: queryable over the existing connect REST surface
(``GET /twin/<car_id>``, list/scan — `connect.ConnectServer.attach_twin`)
and as a `TwinFeatureStore` the `StreamScorer` joins against (per-car
historical features concatenated onto the live window before scoring).

Sharded by partition: one service instance owns a partition subset and
changelogs into the same partitions it consumes, so twin materialisation
runs partition-parallel on the cluster exactly like the scorer fleet.
"""

from .features import TwinFeatureStore
from .service import CHANGELOG_TOPIC, TwinService
from .state import CarTwin, TwinTable

__all__ = ["CarTwin", "TwinTable", "TwinService", "TwinFeatureStore",
           "CHANGELOG_TOPIC"]
