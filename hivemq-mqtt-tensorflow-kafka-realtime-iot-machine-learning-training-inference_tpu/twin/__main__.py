"""CLI: ``python -m iotml.twin drill`` — the live twin-rebuild drill.

Exit status is the verdict (0 = every invariant held), so CI and
deploy/smoke.sh gate on it directly, the same contract as
``python -m iotml.chaos run`` and the supervise/mlops drills.
"""

from __future__ import annotations

import argparse
import json
import sys

from .drill import run_twin_rebuild_drill


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m iotml.twin")
    sub = ap.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("drill", help="kill + rebuild-from-changelog drill")
    d.add_argument("--seed", type=int, default=7)
    d.add_argument("--records", type=int, default=1000)
    d.add_argument("--json", action="store_true",
                   help="emit the full report as JSON")
    args = ap.parse_args(argv)

    report = run_twin_rebuild_drill(seed=args.seed, records=args.records)
    if args.json:
        print(json.dumps(report.to_dict(), indent=2, default=str))
    else:
        print(f"twin-rebuild drill  seed={report.seed} "
              f"records={report.records} published={report.published} "
              f"cars={report.cars} rebuilt={report.rebuilt_records} "
              f"compaction_removed={report.compaction_removed}")
        for inv in report.invariants:
            print(f"  {inv.verdict()}")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
