"""Live twin-rebuild drill — kill the twin service, rebuild from the
compacted changelog, prove state equality.  Drill it, don't assert it.

The drill drives the real stack: a durable broker (small segments so
the changelog actually rolls), a seeded fleet publishing framed-Avro
sensor records, and a TwinService changelogging into the compacted
``CAR_TWIN`` topic.  Mid-stream the service is KILLED (the object is
abandoned — no flush, no goodbye; its table dies with it), the broker
compacts the changelog (so the rebuild reads the *compacted* form, not
a convenient full history), and a second incarnation rebuilds:

- ``rebuild_equals_snapshot``: the rebuilt table is BYTE-identical to
  the dead service's last materialised state;
- ``resume_no_refold``: the restarted service finishes the stream with
  every record folded exactly once (per-car counts sum to published);
- ``compaction_reclaimed``: the changelog rebuild read ~one record per
  car, not one per update — compaction did real work;
- ``retired_stay_retired``: a car tombstoned before the kill does not
  resurrect through the rebuild;
- ``rest_serves_twin``: ``GET /twin/<car_id>`` over a live connect
  server answers the latest state + rolling aggregates for a rebuilt
  car.

Exit status = verdict (``python -m iotml.twin drill``).
"""

from __future__ import annotations

import dataclasses
import json
import shutil
import tempfile
from typing import List

from ..chaos.runner import Invariant

CARS = 10
IN_TOPIC = "SENSOR_DATA_S_AVRO"


@dataclasses.dataclass
class TwinDrillReport:
    seed: int
    records: int
    published: int
    cars: int
    rebuilt_records: int
    compaction_removed: int
    invariants: List[Invariant]

    @property
    def ok(self) -> bool:
        return all(i.ok for i in self.invariants)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        return d


def run_twin_rebuild_drill(seed: int = 7,
                           records: int = 1000) -> TwinDrillReport:
    store_dir = tempfile.mkdtemp(prefix="iotml_twin_drill_")
    try:
        return _run(seed, records, store_dir)
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)


def _run(seed: int, records: int, store_dir: str) -> TwinDrillReport:
    import urllib.request

    from ..connect import ConnectServer, ConnectWorker
    from ..gen.simulator import FleetGenerator, FleetScenario
    from ..store import StorePolicy
    from ..stream.broker import Broker
    from ..twin import TwinService

    broker = Broker(store_dir=store_dir,
                    store_policy=StorePolicy(fsync="interval",
                                             segment_bytes=8 * 1024,
                                             compact_grace_ms=10**9))
    broker.create_topic(IN_TOPIC, partitions=2)
    gen = FleetGenerator(FleetScenario(num_cars=CARS, seed=seed,
                                       failure_rate=0.05))
    ticks = max(2, records // CARS)
    kill_tick = ticks // 2

    svc = TwinService(broker)
    published = 0
    for _ in range(kill_tick):
        published += gen.publish(broker, IN_TOPIC, n_ticks=1, partitions=2)
        svc.pump_once()
    while svc.pump_once():
        pass
    retired_car = svc.cars()[-1]
    svc.retire(retired_car)
    snapshot = svc.table.snapshot()
    updates_before_kill = svc.emitted
    # --- the kill: the service object is abandoned mid-run.  Nothing is
    # flushed; the only durable trace of its work is the changelog.
    del svc

    # the changelog compacts between incarnations (roll the active
    # segments so there is something sealed to clean)
    for p in range(2):
        broker.store.log_for("CAR_TWIN", p).roll()
    stats = broker.run_compaction(force=True)
    removed = sum(s.records_removed for s in stats.values())

    svc2 = TwinService(broker)
    rebuilt_snapshot = svc2.table.snapshot()
    rebuilt_records = svc2.rebuilt_records

    for _ in range(ticks - kill_tick):
        published += gen.publish(broker, IN_TOPIC, n_ticks=1, partitions=2)
        svc2.pump_once()
    while svc2.pump_once():
        pass

    # --- REST over the live connect server
    rest_doc = None
    srv = ConnectServer(ConnectWorker(broker)).start()
    try:
        srv.attach_twin(svc2)
        car = svc2.cars()[0]
        with urllib.request.urlopen(f"{srv.url}/twin/{car}",
                                    timeout=5) as resp:
            rest_doc = json.loads(resp.read())
    finally:
        srv.stop()
    broker.close()

    rest_ok = (rest_doc is not None and rest_doc.get("latest")
               and rest_doc.get("aggregates", {}).get("window_len", 0) > 0)
    # exactly-once accounting: every car sees one record per tick, so a
    # surviving car's fold count must equal the tick count exactly (a
    # redelivery double-fold or a skipped batch both break equality).
    # The retired car restarts from zero at the first post-kill tick —
    # its pre-kill history died with the tombstone, by design.
    per_car = {car: json.loads(v)["count"]
               for car, v in svc2.table.snapshot().items()}
    expected = {car: ticks for car in per_car}
    expected[retired_car] = ticks - kill_tick
    refold_ok = per_car == expected

    invariants = [
        Invariant(
            "rebuild_equals_snapshot",
            rebuilt_snapshot == snapshot,
            f"rebuilt table byte-identical to the killed service's "
            f"state ({len(snapshot)} cars)" if rebuilt_snapshot == snapshot
            else "rebuilt table DIVERGED from the pre-kill snapshot"),
        Invariant(
            "resume_no_refold",
            refold_ok,
            f"per-car fold counts exact after restart "
            f"({sum(per_car.values())} records over {len(per_car)} cars)"
            if refold_ok else
            f"fold counts diverged: {per_car} != {expected}"),
        Invariant(
            "compaction_reclaimed",
            removed > 0 and rebuilt_records <= updates_before_kill,
            f"compaction removed {removed} shadowed changelog records; "
            f"rebuild replayed {rebuilt_records} (service had emitted "
            f"{updates_before_kill})"),
        Invariant(
            "retired_stay_retired",
            retired_car not in {c for c in rebuilt_snapshot},
            f"tombstoned car {retired_car!r} absent from the rebuild"
            if retired_car not in rebuilt_snapshot else
            f"tombstoned car {retired_car!r} RESURRECTED by the rebuild"),
        Invariant(
            "rest_serves_twin",
            bool(rest_ok),
            "GET /twin/<car_id> served latest state + rolling aggregates "
            "over the connect REST surface" if rest_ok else
            f"REST twin query failed or incomplete: {rest_doc}"),
    ]
    return TwinDrillReport(
        seed=seed, records=records, published=published,
        cars=len(per_car), rebuilt_records=rebuilt_records,
        compaction_removed=removed, invariants=invariants)
