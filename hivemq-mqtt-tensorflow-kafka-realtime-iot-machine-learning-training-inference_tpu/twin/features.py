"""TwinFeatureStore — the twin table as per-car model features.

The scorer's live window says what a car looks like *right now*; the
twin says what it has looked like *lately*.  Joining the two is the
classic feature-store enrichment (PAPERS: feature stores / tf.data
input pipelines): per-car historical features are concatenated onto
each live row before it enters the model, so an autoencoder trained on
the joined layout learns per-car context (a reading that is normal for
the fleet but abnormal *for this car* becomes visible).

Feature vector layout (dim = F + 2, F = sensor fields):

    [0:F]  normalized rolling-window MEAN per sensor field — same
           Normalizer the live rows go through, so both halves of the
           joined input live on the same scale;
    [F]    tanh-squashed record count (how much history backs this car);
    [F+1]  lifetime failure rate.

Unknown cars get the zero vector — exactly the "no history" null the
model sees for a car's first records, so cold-start scoring degrades
gracefully instead of erroring.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..core.normalize import CAR_NORMALIZER, Normalizer
from .state import TwinTable


class TwinFeatureStore:
    """Vector view over a TwinTable (or a TwinService's table)."""

    def __init__(self, source, normalizer: Normalizer = CAR_NORMALIZER):
        # accepts a TwinService (joins its live table) or a bare TwinTable
        self.table: TwinTable = getattr(source, "table", source)
        self.normalizer = normalizer
        self.dim = len(normalizer.scale) + 2

    def vector(self, key: Optional[bytes]) -> np.ndarray:
        """[dim] float32 features for one car key (zeros = no history)."""
        out = np.zeros((self.dim,), np.float32)
        if key is None:
            return out
        twin = self.table.get(key.decode() if isinstance(key, bytes)
                              else str(key))
        if twin is None or not twin.window:
            return out
        mean = np.mean(np.asarray(twin.window, np.float64), axis=0)
        out[:self.dim - 2] = self.normalizer.np(mean)
        out[self.dim - 2] = math.tanh(twin.count / 100.0)
        out[self.dim - 1] = twin.failures / twin.count
        return out

    def matrix(self, keys, n: int) -> np.ndarray:
        """[n, dim] float32 rows for a batch's keys array (None keys and
        padding rows beyond len(keys) are zero — the no-history null)."""
        out = np.zeros((n, self.dim), np.float32)
        if keys is None:
            return out
        for i, k in enumerate(keys[:n]):
            if k:
                out[i] = self.vector(k)
        return out
