"""TwinService — materialise the per-car digital twin from the stream.

Dataflow (the Kafka Streams state-store pattern over iotml primitives)::

    SENSOR_DATA_S_AVRO ──poll──> TwinTable (fold) ──changelog──> CAR_TWIN
            ▲                         │                       (compacted)
            │                         └──> REST /twin/<car>, feature joins
            └── source offsets committed AFTER the changelog lands

``CAR_TWIN`` is created with ``cleanup.policy=compact`` and keyed by car
id, so the store's key-based compaction bounds it at ~one record per car
no matter how long the service runs — and a crashed service rebuilds its
whole table by replaying that changelog (latest record per key wins,
tombstone = retired car), then resumes the source from the provenance
stamped inside the rebuilt states.  Rebuild-equals-snapshot is drilled
live (``python -m iotml.twin drill``), not asserted.

Sharding: a service instance owns a set of source partitions and
changelogs into the SAME partition numbers, so N instances (one per
partition group, e.g. one per cluster shard) materialise the fleet in
parallel with no cross-talk — car keys are partition-stable, so each
car's twin lives in exactly one shard's table.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from ..core.schema import KSQL_CAR_SCHEMA, RecordSchema
from ..obs import metrics as obs_metrics
from ..obs import watermark
from ..ops.avro import AvroCodec
from ..ops.framing import strip_frame
from ..stream.broker import OffsetOutOfRangeError
from ..stream.consumer import StreamConsumer
from .state import DEFAULT_WINDOW, TwinTable

#: the compacted changelog topic — the twin's durable form.  Writes to it
#: belong to this package alone (lint R12), the way the AVRO leg belongs
#: to streamproc (R5): a foreign writer could corrupt every rebuild.
CHANGELOG_TOPIC = "CAR_TWIN"

twin_applied = obs_metrics.default_registry.counter(
    "iotml_twin_applied_records_total",
    "source records folded into the twin table")
twin_changelog = obs_metrics.default_registry.counter(
    "iotml_twin_changelog_records_total",
    "state records published to the CAR_TWIN changelog")
twin_rebuild = obs_metrics.default_registry.counter(
    "iotml_twin_rebuild_records_total",
    "changelog records replayed during table rebuilds")
twin_cars = obs_metrics.default_registry.gauge(
    "iotml_twin_cars", "cars materialised in this twin table")
twin_query_seconds = obs_metrics.default_registry.histogram(
    "iotml_twin_query_seconds", "GET /twin/<car_id> handler latency")


class TwinService:
    """One twin materialiser over one broker (see module docstring).

    Args:
      broker: Broker duck-type (in-memory, durable, wire or routed).
      source_topic: the keyed sensor stream (framed Avro in `schema`).
      partitions: source partitions this instance owns (None = all).
      group: consumer group for source-offset commits.
      window: rolling-window depth per car.
      changelog: False disables changelog emission (a read-only tap —
        used by feature-store consumers that follow someone else's
        changelog instead of writing their own).
      table: an already-warm TwinTable to ADOPT instead of building an
        empty one — the standby-promotion path (iotml.gateway): a
        standby that followed the changelog continuously hands its
        table over and only the delta past `rebuild_from` replays.
      rebuild_from: per-partition changelog offsets the adopted table
        has already applied through (TwinTable.changelog positions);
        replay starts there instead of the log beginning.  Ignored
        offsets behind the compacted log's begin are safe — fetch
        resets to earliest and replay stays idempotent (latest record
        per key wins).
    """

    def __init__(self, broker, source_topic: str = "SENSOR_DATA_S_AVRO",
                 partitions: Optional[Sequence[int]] = None,
                 group: str = "iotml-twin",
                 schema: RecordSchema = KSQL_CAR_SCHEMA,
                 window: int = DEFAULT_WINDOW,
                 changelog_topic: str = CHANGELOG_TOPIC,
                 changelog: bool = True,
                 table: Optional[TwinTable] = None,
                 rebuild_from: Optional[Dict[int, int]] = None):
        self.broker = broker
        self.source_topic = source_topic
        self.group = group
        self.schema = schema
        self.codec = AvroCodec(schema)
        self._fields = [f.name for f in schema.sensor_fields]
        self._label = schema.label_field
        self.changelog_topic = changelog_topic
        self.changelog = changelog
        broker.create_topic(source_topic)
        n_parts = broker.topic(source_topic).partitions
        self.partitions = sorted(int(p) for p in (
            partitions if partitions is not None else range(n_parts)))
        # the changelog mirrors the source's partitioning so shard
        # ownership carries over 1:1 (same car -> same partition number)
        broker.create_topic(changelog_topic, partitions=n_parts,
                            cleanup_policy="compact")
        self.table = table if table is not None else TwinTable(window=window)
        self.rebuilt_records = self._rebuild(start=rebuild_from)
        self.consumer = self._make_consumer()
        self.applied = 0
        self.emitted = 0
        # serializes the two changelog writers — the pump thread's
        # emission and a REST-thread retire() — so a stale state record
        # can never land AFTER a tombstone (the table re-check and the
        # produce must be one atomic step)
        self._changelog_lock = threading.Lock()

    # ----------------------------------------------------------- rebuild
    def _rebuild(self, start: Optional[Dict[int, int]] = None) -> int:
        """Replay the compacted changelog into the table: latest record
        per key wins (compaction already dropped most of the rest),
        tombstones delete.  Returns records replayed.  `start` gives
        per-partition offsets an adopted warm table already holds —
        replay covers only the delta from there."""
        start = start or {}
        replayed = 0
        for p in self.partitions:
            try:
                off = self.broker.begin_offset(self.changelog_topic, p)
            except KeyError:
                continue
            off = max(off, start.get(p, 0))
            end = self.broker.end_offset(self.changelog_topic, p)
            while off < end:
                try:
                    batch = self.broker.fetch(self.changelog_topic, p, off,
                                              4096)
                except OffsetOutOfRangeError as e:
                    off = e.earliest
                    continue
                if not batch:
                    # compaction holes between segments end a batch early;
                    # past the last record the log is simply drained
                    break
                for m in batch:
                    if m.key is None:
                        continue
                    self.table.apply_changelog(m.key.decode(), m.value)
                    replayed += 1
                off = batch[-1].offset + 1
        if replayed:
            twin_rebuild.inc(replayed)
        twin_cars.set(len(self.table))
        return replayed

    def _make_consumer(self) -> StreamConsumer:
        """Source cursors: the rebuilt states' provenance wins over the
        committed group offsets when it is FRESHER (changelog landed,
        commit didn't — the crash window), else committed; never behind
        either, so nothing is re-folded and nothing is skipped."""
        resume = self.table.resume_offsets()
        specs = []
        for p in self.partitions:
            committed = self.broker.committed(self.group,
                                              self.source_topic, p)
            off = max(committed if committed is not None else 0,
                      resume.get(p, 0))
            specs.append(f"{self.source_topic}:{p}:{off}")
        return StreamConsumer(self.broker, specs, group=self.group,
                              eof=False)

    # -------------------------------------------------------------- pump
    def pump_once(self, max_messages: int = 4096) -> int:
        """One deterministic pass: poll, fold, changelog, commit.

        Changelog-before-commit ordering makes the crash window safe:
        dying between the two re-delivers source records whose effects
        the changelog already holds, and the provenance dedup
        (TwinTable.apply) folds them to a no-op."""
        msgs = self.consumer.poll(max_messages)
        if not msgs:
            return 0
        dirty: Dict[int, Dict[str, None]] = {}
        applied = 0
        for m in msgs:
            if m.key is None or m.value is None:
                continue  # unkeyed: no car identity to materialise
            try:
                doc = self.codec.decode(strip_frame(m.value))
            except (ValueError, IndexError, KeyError):
                continue  # poisoned frame: the streamproc DLQ's concern
            values = [float(doc.get(n) or 0.0) for n in self._fields]
            failure = self._label is not None and \
                str(doc.get(self._label)).lower() == "true"
            car = m.key.decode()
            if self.table.apply(car, m.partition, m.offset, values,
                                m.timestamp_ms, failure):
                applied += 1
                dirty.setdefault(m.partition, {})[car] = None
        self.applied += applied
        if applied:
            twin_applied.inc(applied)
            twin_cars.set(len(self.table))
        if self.changelog and dirty:
            with self._changelog_lock:
                for p, cars in sorted(dirty.items()):
                    # one coalesced state record per dirty car per pass
                    # — the compaction-friendly shape (latest, keyed)
                    entries = []
                    for car in cars:
                        twin = self.table.get(car)
                        if twin is None:
                            # a REST DELETE (retire() runs on the
                            # connect server's thread) won the race
                            # between this pass's fold and its emission:
                            # its tombstone already changelogs the
                            # delete — emitting the stale fold would
                            # resurrect the car on every rebuild.  The
                            # lock makes this re-check + produce atomic
                            # against retire's pop + tombstone.
                            continue
                        entries.append((car.encode(), twin.encode(),
                                        twin.ts))
                    if not entries:
                        continue
                    self.broker.produce_many(self.changelog_topic,
                                             entries, partition=p)
                    self.emitted += len(entries)
                    twin_changelog.inc(len(entries))
        self.consumer.commit()
        # fold + changelog + commit done: the pass's event-time ranges
        # become the ingest→twin watermark (ISSUE 13) — how stale the
        # digital twin's knowledge of the fleet is, in event time
        watermark.observe_taken("twin", self.consumer.take_event_time(),
                                group=self.group)
        return len(msgs)

    def retire(self, car: str) -> bool:
        """Tombstone a car out of the twin (device decommissioned — the
        MQTT LWT consumer's hook): the changelog carries a null value,
        compaction erases the key after the grace window, rebuilds
        never resurrect it.  Refused on a read-only tap
        (``changelog=False``): producing a tombstone into a changelog
        someone else owns is the two-writer corruption R12 exists to
        prevent — the owner's table would keep serving the car while
        every REBUILD deletes it."""
        if not self.changelog:
            raise RuntimeError(
                "retire() on a read-only twin tap (changelog=False): "
                "the changelog's owning TwinService must issue the "
                "tombstone")
        # pop + tombstone as ONE atomic step against the pump thread's
        # emission (it re-checks the table under the same lock), so a
        # stale state record can never land AFTER the tombstone
        with self._changelog_lock:
            twin = self.table.get(car)
            if twin is None:
                return False
            self.table.apply_changelog(car, None)
            # stamp the tombstone NOW (record-time), not with the car's
            # last reading: an idle car's final reading can already be
            # older than the grace window, and a grace-expired-at-birth
            # tombstone would be dropped by the very first compaction
            # pass — before slow readers (a lagging follower) ever
            # observed the delete
            self.broker.produce(self.changelog_topic, None,
                                key=car.encode(),
                                partition=twin.partition,
                                timestamp_ms=max(twin.ts,
                                                 int(time.time() * 1000)))
        twin_cars.set(len(self.table))
        return True

    # ------------------------------------------------------------ queries
    def get(self, car: str) -> Optional[dict]:
        with twin_query_seconds.time():
            twin = self.table.get(car)
            return None if twin is None else twin.to_doc(self.schema)

    def cars(self, prefix: str = "") -> List[str]:
        cars = self.table.cars()
        return [c for c in cars if c.startswith(prefix)] if prefix else cars

    def count(self) -> int:
        return len(self.table)

    # ---------------------------------------------------------- lifecycle
    def run_forever(self, poll_interval_s: float = 0.2,
                    should_stop=None) -> None:
        while not (should_stop and should_stop()):
            try:
                n = self.pump_once()
            except ConnectionError:
                self.consumer.rewind_to_committed()
                n = 0
            if n == 0:
                time.sleep(poll_interval_s)


class TwinDriver:
    """Background pump thread for one TwinService (R8-supervised)."""

    def __init__(self, service: TwinService, poll_interval_s: float = 0.05):
        self.service = service
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TwinDriver":
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=lambda: self.service.run_forever(
                self.poll_interval_s, should_stop=self._stop.is_set),
            daemon=True, name="iotml-twin-driver"))
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
