"""Per-car twin state: latest reading + rolling-window aggregates.

One `CarTwin` is one car's materialised state — the document the
reference's MongoDB sink upserts per car (mongodb-connector-configmap
HoistField$Key: latest state wins), grown into what a feature store
needs: a bounded window of recent readings and the aggregates derived
from it (mean/min/max over the window, an EMA, lifetime counts).

The state is a PURE FOLD over the car's source records, and the fold is
made idempotent by provenance: each twin remembers the (partition,
offset) of the last record it absorbed, and `TwinTable.apply` drops
anything at or behind it.  Per-car records are totally ordered within
one partition (keyed partitioning), so at-least-once redelivery after a
crash folds to exactly the same state — which is what lets the service
commit source offsets lazily and still pass the rebuild-equals-snapshot
drill.

Serialization is canonical JSON (sorted keys, repr-roundtrip floats):
the changelog record for a car is byte-deterministic given its state,
so compacted changelog reads stay byte-stable across rebuilds.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from ..core.schema import KSQL_CAR_SCHEMA, RecordSchema

#: rolling-window depth (records per car) and the EMA fold constant
DEFAULT_WINDOW = 8
EMA_ALPHA = 0.125


class CarTwin:
    """One car's materialised state (see the module docstring)."""

    __slots__ = ("car", "partition", "offset", "ts", "count", "failures",
                 "last", "window", "ema")

    def __init__(self, car: str, partition: int = 0):
        self.car = car
        self.partition = int(partition)
        self.offset = -1       # source offset of the last absorbed record
        self.ts = 0            # its timestamp
        self.count = 0         # lifetime records absorbed
        self.failures = 0      # lifetime records labeled as failures
        self.last: List[float] = []    # latest raw sensor row [F]
        self.window: List[List[float]] = []  # last W raw rows, oldest first
        self.ema: List[float] = []     # EMA over the raw rows [F]

    # ------------------------------------------------------------- fold
    def absorb(self, values: List[float], ts: int, offset: int,
               failure: bool, window: int = DEFAULT_WINDOW) -> None:
        """Fold one source record into the state (caller dedups via
        `offset` — see TwinTable.apply)."""
        self.last = list(values)
        self.window.append(self.last)
        if len(self.window) > window:
            del self.window[: len(self.window) - window]
        if not self.ema:
            self.ema = list(values)
        else:
            a = EMA_ALPHA
            self.ema = [e + a * (v - e) for e, v in zip(self.ema, values)]
        self.count += 1
        if failure:
            self.failures += 1
        self.ts = int(ts)
        self.offset = int(offset)

    # ------------------------------------------------------- aggregates
    def aggregates(self) -> dict:
        """Rolling-window aggregates — the queryable feature block."""
        if not self.window:
            return {"count": 0, "failures": 0, "failure_rate": 0.0,
                    "window_len": 0, "mean": [], "min": [], "max": [],
                    "ema": []}
        cols = list(zip(*self.window))
        return {
            "count": self.count,
            "failures": self.failures,
            "failure_rate": self.failures / self.count,
            "window_len": len(self.window),
            "mean": [sum(c) / len(c) for c in cols],
            "min": [min(c) for c in cols],
            "max": [max(c) for c in cols],
            "ema": list(self.ema),
        }

    def to_doc(self, schema: RecordSchema = KSQL_CAR_SCHEMA) -> dict:
        """The REST document: latest state (named fields) + aggregates."""
        names = [f.name for f in schema.sensor_fields]
        return {
            "car": self.car,
            "partition": self.partition,
            "offset": self.offset,
            "timestamp_ms": self.ts,
            "latest": dict(zip(names, self.last)),
            "aggregates": self.aggregates(),
        }

    # ---------------------------------------------------- changelog form
    def encode(self) -> bytes:
        """Canonical byte form for the CAR_TWIN changelog record."""
        return json.dumps(
            {"car": self.car, "partition": self.partition,
             "offset": self.offset, "ts": self.ts, "count": self.count,
             "failures": self.failures, "last": self.last,
             "window": self.window, "ema": self.ema},
            sort_keys=True, separators=(",", ":")).encode()

    @classmethod
    def decode(cls, blob: bytes) -> "CarTwin":
        doc = json.loads(blob)
        t = cls(doc["car"], doc["partition"])
        t.offset = int(doc["offset"])
        t.ts = int(doc["ts"])
        t.count = int(doc["count"])
        t.failures = int(doc["failures"])
        t.last = [float(v) for v in doc["last"]]
        t.window = [[float(v) for v in row] for row in doc["window"]]
        t.ema = [float(v) for v in doc["ema"]]
        return t


class TwinTable:
    """car id → CarTwin, with the idempotent-fold discipline.

    `apply` folds a decoded source record; `apply_changelog` installs a
    rebuilt state (latest changelog record wins; a tombstone deletes the
    car).  Both are what make the table a pure function of the log."""

    def __init__(self, window: int = DEFAULT_WINDOW):
        self.window = int(window)
        self.twins: Dict[str, CarTwin] = {}

    def __len__(self) -> int:
        return len(self.twins)

    def get(self, car: str) -> Optional[CarTwin]:
        return self.twins.get(car)

    def cars(self) -> List[str]:
        return sorted(self.twins)

    def apply(self, car: str, partition: int, offset: int,
              values: List[float], ts: int, failure: bool) -> bool:
        """Fold one source record; returns False when the record is at or
        behind the twin's provenance (an at-least-once redelivery) and
        was dropped — the exactly-once-effect dedup."""
        twin = self.twins.get(car)
        if twin is None:
            twin = self.twins[car] = CarTwin(car, partition)
        elif twin.partition == int(partition) and offset <= twin.offset:
            return False
        twin.absorb(values, ts, offset, failure, window=self.window)
        return True

    def apply_changelog(self, car: str, value: Optional[bytes]) -> None:
        if value is None:
            self.twins.pop(car, None)  # tombstone: the car is retired
        else:
            self.twins[car] = CarTwin.decode(value)

    def resume_offsets(self) -> Dict[int, int]:
        """{partition: next source offset} implied by the rebuilt states'
        provenance — where a restarted service resumes its source
        cursors so no record is re-folded or skipped."""
        out: Dict[int, int] = {}
        for twin in self.twins.values():
            nxt = twin.offset + 1
            if nxt > out.get(twin.partition, 0):
                out[twin.partition] = nxt
        return out

    def snapshot(self) -> Dict[str, bytes]:
        """{car: canonical byte state} — what drills diff before/after a
        kill+rebuild (byte equality is state equality by construction)."""
        return {car: twin.encode() for car, twin in self.twins.items()}
