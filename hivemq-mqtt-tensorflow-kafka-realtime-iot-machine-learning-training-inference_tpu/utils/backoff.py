"""Bounded exponential backoff with jitter for reconnect/retry loops.

The stream stack's redelivery loops (the scorer's rewind-on-
ConnectionError, the follower's reconnect-to-leader) used to retry at
a fixed interval — harmless for a transient blip, a busy-spin against
a leader that stays dead, and a synchronized thundering herd the
moment it comes back.  `ExpBackoff` is the standard cure: delays grow
exponentially from `base_s` to a hard `cap_s` (~2 s here — these are
LAN-scale in-process services, not WAN clients), each multiplied by a
uniform jitter in [0.5, 1.0] so a fleet of retriers decorrelates.

The jitter source is injectable (`rng`) so tests pin exact sequences;
delay *schedules* never feed back into pipeline state, so chaos-run
determinism is unaffected by the default process-seeded source.
"""

from __future__ import annotations

import random
from typing import Optional


class ExpBackoff:
    """delay_n = min(cap_s, base_s * factor**n) * uniform(0.5, 1.0)."""

    def __init__(self, base_s: float = 0.05, cap_s: float = 2.0,
                 factor: float = 2.0,
                 rng: Optional[random.Random] = None):
        if base_s <= 0 or cap_s < base_s or factor <= 1.0:
            raise ValueError(
                f"need 0 < base_s <= cap_s and factor > 1, got "
                f"base_s={base_s} cap_s={cap_s} factor={factor}")
        self.base_s = base_s
        self.cap_s = cap_s
        self.factor = factor
        self._rng = rng if rng is not None else random.Random()
        self._n = 0

    def next_delay(self) -> float:
        """The next sleep, advancing the schedule."""
        raw = min(self.cap_s, self.base_s * self.factor ** self._n)
        self._n += 1
        return raw * (0.5 + 0.5 * self._rng.random())

    def reset(self) -> None:
        """Back to `base_s` — call after a successful round."""
        self._n = 0

    @property
    def attempt(self) -> int:
        """Consecutive failures so far (0 after reset)."""
        return self._n
