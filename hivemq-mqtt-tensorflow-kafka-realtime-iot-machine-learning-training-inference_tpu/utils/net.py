"""Shared socket primitives for the wire-protocol layers (MQTT, Kafka)."""

from __future__ import annotations


def recv_exact(sock, n: int, closed_msg: str = "peer closed") -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(closed_msg)
        buf += chunk
    return buf
