"""Shared socket primitives for the wire-protocol layers (MQTT, Kafka)."""

from __future__ import annotations

from typing import List, Tuple


def parse_bootstrap(servers: str, default_port: int = 9092
                    ) -> List[Tuple[str, int]]:
    """bootstrap.servers string → [(host, port)], skipping malformed
    entries (one typo'd port must not defeat the rest of the list).
    Understands "host", "host:port", and bracketed IPv6 "[::1]:port"."""
    out: List[Tuple[str, int]] = []
    for entry in servers.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if entry.startswith("["):  # [v6addr]:port
            addr, _, rest = entry[1:].partition("]")
            port_s = rest.lstrip(":")
        else:
            addr, _, port_s = entry.partition(":")
        try:
            port = int(port_s) if port_s else default_port
        except ValueError:
            continue  # malformed entry: try the others
        if addr:
            out.append((addr, port))
    return out


def recv_exact(sock, n: int, closed_msg: str = "peer closed") -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(closed_msg)
        buf += chunk
    return buf
