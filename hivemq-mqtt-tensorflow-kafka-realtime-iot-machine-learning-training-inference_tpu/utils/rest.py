"""Minimal routed JSON-over-HTTP server scaffold (stdlib only).

Three control-plane services in the reference are REST APIs the rebuild
must speak: the Schema Registry (`register_schema.py:20-31`), Kafka Connect
(`mongodb/README.md:139-171`), and KSQL (`01_installConfluentPlatform.sh`).
This scaffold gives them one tiny routing layer: regex routes, JSON bodies,
JSON replies, threaded serving — nothing more.

Two serving disciplines every mounted surface inherits (ISSUE 20):

* **Per-request observability** — every dispatch lands in
  ``iotml_rest_requests_total{route,code}`` and the matched route's
  ``iotml_rest_request_seconds`` series.  The route label is the
  registered PATTERN string (a closed set — one series per route, never
  per path), so a 100k-car query storm costs the same scrape it always
  did.
* **Bounded concurrency** — ThreadingHTTPServer spawns one handler
  thread per connection with no ceiling, which under storm load turns
  into unbounded thread creation exactly when the box is least able to
  afford it.  Connections past ``max_concurrency`` are answered with a
  raw ``503`` and closed BEFORE a handler thread exists; admitted
  handler threads are daemon, named, and registered per lint R8.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Tuple

from ..obs.metrics import default_registry

#: A handler takes (match, body_dict) and returns (status_code, json_obj).
Route = Tuple[str, "re.Pattern", Callable]

#: connection-concurrency ceiling when the constructor doesn't pick one
#: (env IOTML_REST_MAX_CONCURRENCY; registered in config.non_config).
DEFAULT_MAX_CONCURRENCY = 64

rest_requests = default_registry.counter(
    "iotml_rest_requests_total",
    "REST requests served, by registered route pattern and status code "
    "(route='(guard)' counts connections shed by the concurrency bound)")
rest_request_seconds = default_registry.histogram(
    "iotml_rest_request_seconds",
    "REST request handler latency by registered route pattern")


def _max_concurrency_default() -> int:
    raw = os.environ.get("IOTML_REST_MAX_CONCURRENCY")
    if raw is None:
        return DEFAULT_MAX_CONCURRENCY
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"IOTML_REST_MAX_CONCURRENCY={raw!r} is not an integer")
    if v < 1:
        raise ValueError(
            f"IOTML_REST_MAX_CONCURRENCY={v} must be >= 1: a zero bound "
            f"sheds every connection")
    return v


class RestError(Exception):
    """Raise from a route handler to produce an error reply."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class _BoundedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a concurrent-connection ceiling and
    R8-compliant handler threads (daemon, named, registered)."""

    daemon_threads = True

    def __init__(self, addr, handler_cls, *, name: str, max_concurrency: int):
        super().__init__(addr, handler_cls)
        self.rest_name = name
        self.max_concurrency = max_concurrency
        self._guard_lock = threading.Lock()
        self._active = 0
        self._hseq = 0
        self._live: set = set()

    def active_connections(self) -> int:
        with self._guard_lock:
            return self._active

    def process_request(self, request, client_address):
        with self._guard_lock:
            if self._active >= self.max_concurrency:
                admitted = False
            else:
                admitted = True
                self._active += 1
                self._hseq += 1
                seq = self._hseq
                self._live.add(request)
        if not admitted:
            # shed BEFORE a handler thread exists: a raw one-shot 503 on
            # the accepted socket is the whole cost of an over-limit
            # connection — the storm can't grow the thread count
            body = (b'{"error_code":503,"message":'
                    b'"connection limit reached, retry"}')
            try:
                request.sendall(
                    b"HTTP/1.1 503 Service Unavailable\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + str(len(body)).encode() +
                    b"\r\nConnection: close\r\n\r\n" + body)
            except OSError:
                pass
            self.shutdown_request(request)
            rest_requests.inc(route="(guard)", code=503)
            return
        from ..supervise.registry import register_thread

        t = register_thread(threading.Thread(
            target=self._handle_admitted, args=(request, client_address),
            daemon=True, name=f"{self.rest_name}-h{seq}"))
        t.start()

    def _handle_admitted(self, request, client_address):
        try:
            self.finish_request(request, client_address)
        except (ConnectionResetError, BrokenPipeError):
            pass  # client vanished / connection severed: routine, not an error
        except Exception:
            self.handle_error(request, client_address)
        finally:
            self.shutdown_request(request)
            with self._guard_lock:
                self._active -= 1
                self._live.discard(request)

    def close_connections(self) -> None:
        """Sever every established keep-alive connection.  shutdown()
        only stops the accept loop — admitted handler threads keep
        answering on their open sockets, which a dead process would
        not; a crash-shaped stop (a killed serving shard) must look
        like one to clients holding persistent connections."""
        import socket as _socket

        with self._guard_lock:
            conns = list(self._live)
        for c in conns:
            try:
                c.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class RestServer:
    """Routed threaded HTTP server; subclass or compose with `route()`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 name: str = "iotml-rest",
                 max_concurrency: Optional[int] = None):
        self.name = name
        self.max_concurrency = (_max_concurrency_default()
                                if max_concurrency is None
                                else int(max_concurrency))
        if self.max_concurrency < 1:
            raise ValueError(f"max_concurrency={self.max_concurrency} "
                             f"must be >= 1")
        self._routes: List[Route] = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = name
            # replies go out as two writes (header flush, then body);
            # with Nagle on, the body segment waits for the client's
            # delayed ACK — a flat ~40ms tax on every point lookup
            disable_nagle_algorithm = True

            def _dispatch(self, method: str):
                t0 = time.perf_counter()
                route_label = "(unmatched)"
                code = 404
                try:
                    route_label, code = self._dispatch_inner(method)
                finally:
                    rest_requests.inc(route=route_label, code=code)
                    rest_request_seconds.observe(
                        time.perf_counter() - t0, route=route_label)

            def _dispatch_inner(self, method: str) -> Tuple[str, int]:
                """Route + run a handler; returns (route_label, code)
                for the per-request metrics."""
                body = {}
                n = int(self.headers.get("Content-Length", 0) or 0)
                if n:
                    try:
                        body = json.loads(self.rfile.read(n))
                    except ValueError:
                        self._send(400, {"error_code": 400,
                                         "message": "malformed JSON body"})
                        return "(unmatched)", 400
                # routes match the bare path; query-string params merge
                # into the body dict (first value wins, body takes
                # precedence) so GET endpoints can take parameters —
                # the TSDB query surface (`/query?query=...`) reads
                # them exactly like a POSTed JSON field
                path, _, qs = self.path.partition("?")
                if qs:
                    from urllib.parse import parse_qs

                    for k, vs in parse_qs(qs).items():
                        body.setdefault(k, vs[0])
                for m, pat, fn in outer._routes:
                    if m != method:
                        continue
                    match = pat.fullmatch(path)
                    if match:
                        try:
                            result = fn(match, body)
                            if len(result) == 3:  # (code, raw bytes, ctype)
                                self._send_raw(*result)
                                return pat.pattern, result[0]
                            code, obj = result
                        except RestError as e:
                            code, obj = e.code, {"error_code": e.code,
                                                 "message": e.message}
                        except Exception as e:  # route bug: 500, keep serving
                            code, obj = 500, {"error_code": 500, "message":
                                              f"{type(e).__name__}: {e}"}
                        self._send(code, obj)
                        return pat.pattern, code
                self._send(404, {"error_code": 404,
                                 "message": f"no route for {method} {self.path}"})
                return "(unmatched)", 404

            def _send(self, code: int, obj):
                self.send_response(code)
                if code == 204:  # No Content: a body would corrupt keep-alive
                    self.end_headers()
                    return
                payload = json.dumps(obj, default=str).encode()
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _send_raw(self, code: int, payload: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def log_message(self, *a):  # quiet
                pass

        self.httpd = _BoundedThreadingHTTPServer(
            (host, port), Handler, name=name,
            max_concurrency=self.max_concurrency)
        self.host, self.port = self.httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def route(self, method: str, pattern: str, fn: Callable) -> None:
        """Register `fn(match, body) -> (code, obj)` for `method pattern`."""
        self._routes.append((method, re.compile(pattern), fn))

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def active_connections(self) -> int:
        """Handler threads currently admitted (below max_concurrency)."""
        return self.httpd.active_connections()

    def start(self):
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name=f"iotml-rest-{self.port}"))
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    def kill(self):
        """Crash-shaped stop: accept loop down AND every established
        connection severed, so clients on keep-alive sockets observe
        exactly what a crashed server looks like (connection error →
        their refresh-and-retry path) instead of being answered by a
        zombie."""
        self.httpd.shutdown()
        self.httpd.close_connections()
        self.httpd.server_close()
