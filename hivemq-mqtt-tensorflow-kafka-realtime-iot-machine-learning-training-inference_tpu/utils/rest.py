"""Minimal routed JSON-over-HTTP server scaffold (stdlib only).

Three control-plane services in the reference are REST APIs the rebuild
must speak: the Schema Registry (`register_schema.py:20-31`), Kafka Connect
(`mongodb/README.md:139-171`), and KSQL (`01_installConfluentPlatform.sh`).
This scaffold gives them one tiny routing layer: regex routes, JSON bodies,
JSON replies, threaded serving — nothing more.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Tuple

#: A handler takes (match, body_dict) and returns (status_code, json_obj).
Route = Tuple[str, "re.Pattern", Callable]


class RestError(Exception):
    """Raise from a route handler to produce an error reply."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class RestServer:
    """Routed threaded HTTP server; subclass or compose with `route()`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 name: str = "iotml-rest"):
        self.name = name
        self._routes: List[Route] = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = name

            def _dispatch(self, method: str):
                body = {}
                n = int(self.headers.get("Content-Length", 0) or 0)
                if n:
                    try:
                        body = json.loads(self.rfile.read(n))
                    except ValueError:
                        self._send(400, {"error_code": 400,
                                         "message": "malformed JSON body"})
                        return
                # routes match the bare path; query-string params merge
                # into the body dict (first value wins, body takes
                # precedence) so GET endpoints can take parameters —
                # the TSDB query surface (`/query?query=...`) reads
                # them exactly like a POSTed JSON field
                path, _, qs = self.path.partition("?")
                if qs:
                    from urllib.parse import parse_qs

                    for k, vs in parse_qs(qs).items():
                        body.setdefault(k, vs[0])
                for m, pat, fn in outer._routes:
                    if m != method:
                        continue
                    match = pat.fullmatch(path)
                    if match:
                        try:
                            result = fn(match, body)
                            if len(result) == 3:  # (code, raw bytes, ctype)
                                self._send_raw(*result)
                                return
                            code, obj = result
                        except RestError as e:
                            code, obj = e.code, {"error_code": e.code,
                                                 "message": e.message}
                        except Exception as e:  # route bug: 500, keep serving
                            code, obj = 500, {"error_code": 500, "message":
                                              f"{type(e).__name__}: {e}"}
                        self._send(code, obj)
                        return
                self._send(404, {"error_code": 404,
                                 "message": f"no route for {method} {self.path}"})

            def _send(self, code: int, obj):
                self.send_response(code)
                if code == 204:  # No Content: a body would corrupt keep-alive
                    self.end_headers()
                    return
                payload = json.dumps(obj, default=str).encode()
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _send_raw(self, code: int, payload: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def log_message(self, *a):  # quiet
                pass

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self.httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def route(self, method: str, pattern: str, fn: Callable) -> None:
        """Register `fn(match, body) -> (code, obj)` for `method pattern`."""
        self._routes.append((method, re.compile(pattern), fn))

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        from ..supervise.registry import register_thread

        self._thread = register_thread(threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name=f"iotml-rest-{self.port}"))
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
