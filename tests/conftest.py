"""Test environment: force JAX onto CPU with 8 virtual devices.

Mirrors the reference's simulator-as-cluster trick (SURVEY §4.4): multi-chip
code paths are exercised on a virtual 8-device CPU mesh, no TPU required.
Must run before jax initializes, hence env vars at import time.
"""

import os
import sys

# Force CPU even when the image points JAX at a TPU tunnel (the axon
# sitecustomize calls jax.config.update("jax_platforms", "axon,cpu") at
# interpreter start, overriding the JAX_PLATFORMS env var): unit tests must
# be hermetic and fast; TPU execution is the bench/driver's job.  Override
# with IOTML_TEST_PLATFORM=tpu to run the suite on chip.
_platform = os.environ.get("IOTML_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if _platform == "cpu":
    jax.config.update("jax_platforms", "cpu")

# Repo root on sys.path so `import iotml` works without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_ROOT = "/root/reference"


def reference_available() -> bool:
    return os.path.isdir(REFERENCE_ROOT)


requires_reference = pytest.mark.skipif(
    not reference_available(),
    reason="read-only reference checkout not mounted")

# IOTML_LOCKCHECK=1: run the whole suite under the runtime lock-order &
# race detector (iotml.analysis.lockcheck).  Installed at import time —
# before any test constructs a broker/server — so every lock the stream
# stack creates is instrumented; the registered plugin reports at session
# end and FAILS the run on lock-order cycles.  Equivalent to
# `pytest -p iotml.analysis.pytest_plugin`.
# IOTML_TRACECHECK=1: arm the JAX recompile guard over the known hot
# loops — a warmed loop that re-traces fails its test (same plugin,
# independently gated; see iotml.analysis.pytest_plugin).
if os.environ.get("IOTML_LOCKCHECK", "") not in ("", "0") \
        or os.environ.get("IOTML_TRACECHECK", "") not in ("", "0"):
    if os.environ.get("IOTML_LOCKCHECK", "") not in ("", "0"):
        from iotml.analysis import lockcheck as _lockcheck

        _lockcheck.install()

    def pytest_configure(config):
        if not config.pluginmanager.has_plugin("iotml-lockcheck"):
            from iotml.analysis import pytest_plugin

            config.pluginmanager.register(pytest_plugin, "iotml-lockcheck")


@pytest.fixture
def rng():
    return np.random.default_rng(42)
