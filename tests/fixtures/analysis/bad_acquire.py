"""Seeded R3 violation: bare .acquire() instead of a context manager."""

import threading

_lock = threading.Lock()
_items = []


def push(item):
    _lock.acquire()                             # R3: bare acquire
    try:
        _items.append(item)
    finally:
        _lock.release()


def push_ok(item):
    with _lock:
        _items.append(item)
