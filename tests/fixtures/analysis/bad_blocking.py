"""Seeded R4 violation: blocking socket I/O while a lock is held —
directly and through a module-local helper chain."""

import threading


class Pump:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self._sock = sock

    def _read_frame(self):
        return self._sock.recv(4096)

    def _next(self):
        return self._read_frame()

    def step_direct(self):
        with self._lock:
            return self._sock.recv(4096)            # R4: recv under lock

    def step_transitive(self):
        with self._lock:
            return self._next()                     # R4: blocks 2 frames down

    def step_outside(self):
        frame = self._read_frame()                  # clean: lock not held
        with self._lock:
            return len(frame)
