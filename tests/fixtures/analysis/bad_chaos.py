"""Seeded R7 violations: chaos machinery leaking outside the faultpoint
allowlist.  This module is NOT on CHAOS_ALLOWED_MODULES, so both the
imports and the shim call below must be flagged — and the scenarios
import would be flagged even on an allowlisted module (only the shim
`faults` may cross into production code)."""

from iotml.chaos import scenarios  # noqa: F401  (R7: not the shim)
from iotml.chaos import faults as chaos  # R7: shim outside the allowlist


def hot_path(consumer):
    chaos.point("broker.fetch")  # R7: faultpoint outside the allowlist
    return consumer.poll()
