"""R11 fixture: naked model-registry writes, every way to get it
wrong — a builtin open() on a registry path (1 finding), an os.open on
a version dir (1 finding), an atomic_write landing a manifest by hand
(1 finding) — plus the clean shapes: a registry READ through the
ModelRegistry API, an open() on an unrelated path, and a justified
suppression (0 findings)."""

import os


def hand_rolled_publish(registry_dir):
    # flagged: the manifest is the COMMIT MARKER — writing it by hand
    # skips the staged rename, the checksums and the fsync, so a crash
    # can leave a manifest that lies about its artifacts
    with open(os.path.join(registry_dir, "versions", "v42",
                           "manifest.json"), "w") as fh:
        fh.write("{}")


def poke_version_dir(version_dir):
    # flagged: registry version dirs are immutable once committed
    fd = os.open(os.path.join(version_dir, "model.h5"), os.O_WRONLY)
    os.close(fd)


def atomic_but_still_wrong(registry_root, atomic_write):
    # flagged: atomicity is not the point — ONE writer is; this blob
    # has no manifest entry, no checksum, no lineage
    atomic_write(os.path.join(registry_root, "versions", "v7",
                              "extra.bin"), b"orphan artifact")


def reading_is_fine(registry):
    # the API is the boundary, not the disk: reads go through it
    return registry.load_bytes(registry.latest(), "model.h5")


def unrelated_write_is_fine(tmp_dir):
    with open(os.path.join(tmp_dir, "manifest.txt"), "w") as fh:
        fh.write("not a registry manifest: no finding")


def justified(registry_dir):
    # lint-ok: R11 read-only existence probe; opens nothing for writing
    return os.path.exists(os.path.join(registry_dir, "versions"))
