"""R10 fixture: direct broker-instance addressing outside
iotml/cluster/ — a ShardBroker built by hand (1 finding) and controller
collections subscripted for a specific instance (2 findings) — plus the
clean shapes: routing through the client/map and a justified
suppression (0 findings)."""


def hand_built_shard(pmap):
    from iotml.cluster import ShardBroker

    # flagged: broker instances belong to the ClusterController
    return ShardBroker(lambda t, p: True, shard_id=0)


def pick_a_broker(controller):
    # both flagged: indexing a specific instance bypasses PartitionMap
    # routing (NOT_LEADER re-route + epoch fencing never run)
    b = controller.brokers[2]
    controller.serving[0].produce("t", b"oops", partition=3)
    return b


def routed_is_fine(controller):
    client = controller.client()
    client.produce("t", b"routed", key=b"car-1")
    servers, epoch = controller.pmap.resolve("t", 3)
    return servers, epoch


def justified(controller):
    # lint-ok: R10 drill harness assertion reads the victim's end offset
    return controller.serving[1].end_offset("t", 1)
