"""Seeded registry drift (D1/D2/D3) the drift pass must fully convict
when run fixture-scoped (``drift.analyze(paths=[this file])`` — the
registries it drifts FROM are the real tree's).

Expected findings: 2×D1, 2×D2, 1×D3 — and the suppressed knob read
staying SUPPRESSED (the round-trip check).
"""

import os


class _Reg:
    def counter(self, name):
        return self

    def inc(self, **labels):
        return None


class _FaultShim:
    @staticmethod
    def point(name):
        return None


reg = _Reg()
metrics = reg
fp = _FaultShim()

# declared here so the label check binds; its (absent)
# DECLARED_METRIC_LABELS row budgets no label keys at all
fixture_total = reg.counter("iotml_fixture_total")


def read_knobs():
    a = os.environ.get("IOTML_BOGUS_KNOB")  # D1: no config field
    b = os.getenv("IOTML_PHANTOM")  # D1: no non_config entry either
    return a, b


def record():
    metrics.fixture_total.inc(topic="t")  # D2: undeclared label key
    metrics.ghost_total.inc()  # D2: no declaration anywhere


def inject():
    fp.point("fixture.bogus_fault")  # D3: unregistered faultpoint


def suppressed_knob():
    # lint-ok: D1 fixture: the suppression round-trip — knob is
    # consumed by the harness alone, never by the config ladder
    return os.environ.get("IOTML_SUPPRESSED_KNOB")
