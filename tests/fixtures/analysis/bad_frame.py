"""R14 fixture: hand-rolled frame parsing outside iotml/store/ +
iotml/ops/framing.py — the [len|crc|attrs|offset|ts|key|value|headers]
layout has ONE parser."""

import struct

from iotml.store import segment as seg

_MY_HEAD = struct.Struct(">IBqqi")  # BAD: hand-rolled frame head


def sniff(buf: bytes):
    for rec in seg.scan_records(buf):  # BAD: store codec outside store/
        yield rec


def rewrite(offset, key, value):
    # BAD: frame encoding outside the store / framing helpers
    return seg.encode_record(offset, key, value, 0, None)


def frame_myself(lib, blob):
    # BAD: direct native frame-codec call outside stream/native.py —
    # a second frame ENCODER in disguise (ISSUE 12 write-path rule)
    return lib.iotml_frames_encode_values(blob, None, None, None, None,
                                          None, None, 0, 0, None, 0)
