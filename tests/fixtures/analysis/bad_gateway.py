"""R16 fixture: direct TwinTable access outside iotml/twin/ +
iotml/gateway/ — a TwinTable built by hand (1 finding), a foreign
changelog apply (1 finding), and reaching through a service's `.table`
for raw reads and a raw fold (3 findings) — plus the clean shapes:
querying through the owning service / feature store / gateway client
and a justified suppression (0 findings)."""


def hand_built_table(TwinTable):
    # flagged: the materialised twin is TwinService's (or the gateway
    # standby plane's) to build — this table has no changelog, so a
    # crash loses it and a rebuild disagrees with what it served
    return TwinTable(window=8)


def foreign_replay(table, record):
    # flagged: changelog replay belongs to the table owners; a foreign
    # apply forks state the changelog can never rebuild
    table.apply_changelog("car-7", record)


def raw_table_reads(svc):
    # all three flagged: serving raw table state bypasses the owner's
    # locking and the provenance dedup the crash story depends on
    snap = svc.table.snapshot()
    twins = svc.table.twins
    svc.table.apply("car-7", 0, 41, [0.5], False, 0)
    return snap, twins


def query_through_owner_is_fine(svc, feats, client):
    doc = svc.get("car-7")
    vec = feats.vector(b"car-7")
    remote = client.get("car-7")
    return doc, vec, remote


def justified(svc):
    # lint-ok: R16 drill assertion compares the victim's raw snapshot
    return svc.table.snapshot()
