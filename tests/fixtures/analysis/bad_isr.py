"""R15 fixture: ISR / quorum-HWM mutation-discipline breaches — a
drive-by eviction (1 finding), a foreign follower registration and
retirement (2 findings), and a position/quorum-wait ingress outside
the wire server (2 findings) — plus the clean shapes: reading ISR
state, the ReplicaSet orchestration API, and a justified suppression
(0 findings).
"""


def drive_by_eviction(state):
    # flagged: eviction decides what acks=all MEANS — a foreign caller
    # shrinking the ISR silently weakens every in-flight ack
    state.evict_stale()


def foreign_registration(state):
    # flagged: membership changes are iotml/replication/'s alone
    state.register_follower(99)
    state.unregister_follower(99)


def rogue_position_ingress(state, topic):
    # flagged: follower positions enter through the wire server's fetch
    # handlers only — a second ingress could admit a replica that
    # never fetched (its "position" would be fiction)
    state.observe_fetch(99, topic, 0, 10_000)


def rogue_quorum_wait(state, topic):
    # flagged: the acks=all wait (and the eviction scan inside it)
    # belongs to the produce handlers
    state.wait_replicated(topic, 0, 10_000)


def reading_is_fine(state, topic):
    # ISR state is everyone's to READ: gauges, drills, admin status
    return (state.isr_size(topic, 0), state.quorum_hwm(topic, 0),
            state.fetch_ceiling(topic, 0), state.positions(topic, 0))


def orchestration_is_fine(rset):
    # the ReplicaSet API (add_follower / retire_follower / promote) is
    # the public elasticity surface — it delegates to the one owner
    rid = rset.add_follower()
    rset.retire_follower(rid)


def justified(state):
    # lint-ok: R15 test harness evicts on purpose to prove re-admission
    state.evict_stale()
