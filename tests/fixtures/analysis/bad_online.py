"""R13 fixture: in-place model updates bypassing the registry — a
direct ``set_params`` poke on a serving scorer (1 finding) and the
same poke buried in a helper (1 finding) — plus the clean shapes: a
registry publish + watcher swap (the sanctioned path), an unrelated
``set_params``-free call, and a justified suppression (0 findings)."""


def hot_patch_scorer(scorer, params):
    # flagged: an unversioned deploy — no registry id, no rollback
    # target, no swap metric; /healthz keeps reporting the old version
    scorer.set_params(params)


def sneaky_patch(fleet, params):
    # flagged: same breach, fanned across a fleet by hand
    for member in fleet.members:
        member.scorer.set_params(params, version=None)


def sanctioned_deploy(registry, params_to_h5_bytes, params):
    # the one path: publish a version; attached watchers swap it with
    # version identity, gate protection and metrics
    m = registry.publish({"model.h5": params_to_h5_bytes(params)})
    registry.promote(m.version)
    return m.version


def unrelated_call_is_fine(estimator, grid):
    # a set_params-free API on some other object: no finding
    return estimator.configure(grid)


def justified(scorer, params):
    # lint-ok: R13 test harness pins swap mechanics against a scorer
    # it owns; nothing is serving
    scorer.set_params(params)
