"""Seeded R5 violation: producing to an engine-owned topic from outside
streamproc/ (this fixture is not under a streamproc/ path)."""


def inject(broker, payload: bytes):
    broker.produce("SENSOR_DATA_S_AVRO", payload)   # R5: engine-owned topic


def observe(broker):
    return broker.fetch("SENSOR_DATA_S_AVRO", 0, 0)  # reads stay open
