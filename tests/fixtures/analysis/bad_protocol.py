"""Seeded wire-protocol drift: a self-contained mini wire module the
protocol pass (``protocol.check_wire``) must fully convict.

Expected findings:
  P1  API_ORPHAN supported but unhandled; API_GHOST handled but
      disowned; the PRODUCE handler's bare numeric code 41.
  P2  probe() requesting the undefined API_MYSTERY; API_ORPHAN with no
      encoder.
  P3  produce() never typing ERR_MESSAGE_TOO_LARGE.
  P5  IDEMPOTENT_APIS classifying the unsupported API_GHOST.
  P6  fetch() reaching no chaos faultpoint (and suppressed_probe()'s
      identical shape staying SUPPRESSED — the round-trip check).
"""

API_PRODUCE = 0
API_FETCH = 1
API_ORPHAN = 7
API_GHOST = 9

ERR_NONE = 0
ERR_UNKNOWN_TOPIC = 3
ERR_MESSAGE_TOO_LARGE = 10

_SUPPORTED = {API_PRODUCE: (0, 0), API_FETCH: (0, 0), API_ORPHAN: (0, 0)}

IDEMPOTENT_APIS = frozenset({API_FETCH, API_GHOST})


class _FaultShim:
    @staticmethod
    def point(name):
        return None


fp = _FaultShim()


class _MiniServer:
    def handle(self, api_key, rd, w):
        if api_key == API_PRODUCE:
            w.i16(ERR_MESSAGE_TOO_LARGE)
            w.i16(ERR_UNKNOWN_TOPIC)
            w.i16(41)
            w.i16(ERR_NONE)
        elif api_key == API_FETCH:
            w.i16(ERR_NONE)
        elif api_key == API_GHOST:
            w.i16(ERR_NONE)


class _MiniClient:
    def _request(self, api, version, payload):
        raise NotImplementedError

    def produce(self, topic, value):
        fp.point("wire.send")
        # retry-ok: fixture stub — the mini client never executes
        r = self._request(API_PRODUCE, 0, value)
        err = r.i16()
        if err == ERR_UNKNOWN_TOPIC:
            raise KeyError(topic)
        if err != ERR_NONE:
            raise RuntimeError("produce failed")
        return r

    def fetch(self, topic):
        # retry-ok: fixture stub — the mini client never executes
        r = self._request(API_FETCH, 0, topic.encode())
        err = r.i16()
        if err != ERR_NONE:
            raise RuntimeError("fetch failed")
        return r

    def probe(self):
        fp.point("wire.send")
        # retry-ok: fixture stub — the mini client never executes
        r = self._request(API_MYSTERY, 0, b"")  # noqa: F821
        return r.i16() == ERR_NONE

    def suppressed_probe(self):
        # retry-ok: fixture stub — the mini client never executes
        # lint-ok: P6 fixture: the suppression round-trip — this
        # exchange is covered by produce()'s injected socket path
        r = self._request(API_FETCH, 0, b"")
        return r.i16() == ERR_NONE
