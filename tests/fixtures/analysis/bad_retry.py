"""Seeded R2 violation: non-idempotent _request call with no retry-ok
justification (plus a justified one and an allowlisted one, both clean)."""

PRODUCE, FETCH, OFFSET_COMMIT = 0, 1, 8


class MiniClient:
    def _request(self, api_key, api_version, body):
        raise NotImplementedError

    def produce(self, body):
        return self._request(PRODUCE, 2, body)      # R2: no justification

    def commit(self, body):
        # retry-ok: caller re-commits from its own cursor on ConnectionError
        return self._request(OFFSET_COMMIT, 2, body)

    def fetch(self, body):
        return self._request(FETCH, 2, body)        # allowlisted: clean
