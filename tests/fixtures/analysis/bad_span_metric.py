"""Seeded R6 violations: a malformed framework metric name, an
uppercase span name, and span recording under a held lock — directly
and through a module-local helper chain."""

import threading


class Pump:
    def __init__(self, registry, tracing):
        self._lock = threading.Lock()
        self.registry = tracing  # naming only; never executed
        self._m = registry.counter("iotml-Records.Total")  # R6: bad name
        self._h = registry.histogram("iotml_fetch_seconds")  # clean

    def _note(self, ctx):
        ctx.mark("decode")

    def step_direct(self, ctx):
        with self._lock:
            ctx.mark("decode")                  # R6: span under lock

    def step_transitive(self, ctx):
        with self._lock:
            self._note(ctx)                     # R6: records 1 frame down

    def step_outside(self, ctx):
        ctx.mark("Decode-Stage")                # R6: bad stage name
        with self._lock:
            return 1                            # clean: no span inside

    def step_runaway_label(self, car):
        # R6: label key outside the closed vocabulary — a per-entity
        # label (one series per car) is the cardinality explosion the
        # bound test exists to catch
        self._h.observe(0.1, car_id=car)

    def step_good_label(self):
        self._h.observe(0.1, stage="decode")    # clean: closed-set key
