"""R9 fixture: naked writes under a store directory, every way to get
it wrong — a raw os.open + os.fsync pair (2 findings), a builtin open()
on a store path (1 finding) — plus the clean shapes: an open() on an
unrelated path and a justified suppression (0 findings)."""

import os


def torn_write_by_hand(store_dir):
    # both halves flagged: the bytes bypass SegmentWriter's framing/CRC,
    # and the fsync bypasses its durability accounting
    fd = os.open(os.path.join(store_dir, "00000000000000000000.log"),
                 os.O_WRONLY | os.O_APPEND)
    os.fsync(fd)
    os.close(fd)


def naked_segment_append(store_dir):
    with open(os.path.join(store_dir, "segments", "t", "0", "x.log"),
              "ab") as fh:
        fh.write(b"unframed bytes recovery cannot checksum")


def unrelated_write_is_fine(tmp_dir):
    with open(os.path.join(tmp_dir, "notes.txt"), "w") as fh:
        fh.write("not a store path: no finding")


def justified(store_dir):
    # lint-ok: R9 read-only introspection; os.open with O_RDONLY writes nothing
    fd = os.open(os.path.join(store_dir, "offsets"), os.O_RDONLY)
    os.close(fd)
