"""Seeded meta-violation: a suppression comment with no justification is
itself a finding (justifications are the point of the mechanism)."""

import threading

_lock = threading.Lock()


def grab():
    _lock.acquire()  # lint-ok: R3
    _lock.release()
