"""R8 fixture: unsupervised thread construction, every way to get it
wrong — an anonymous non-daemon fire-and-forget thread (3 findings on
one call), a named daemon thread that skips the registry (1 finding),
the module-alias evasion (1 finding), and the compliant form plus a
justified suppression (0 findings)."""

import threading
import threading as t

from iotml.supervise.registry import register_thread


def target():
    pass


def fire_and_forget():
    # all three violations at once: not daemon, unnamed, unregistered
    t = threading.Thread(target=target)
    t.start()


def named_but_unregistered():
    t = threading.Thread(target=target, daemon=True, name="worker")
    t.start()


def aliased_evasion():
    # aliasing the module must not dodge the rule
    t.Thread(target=target, daemon=True, name="sneaky").start()


def compliant():
    t = register_thread(threading.Thread(target=target, daemon=True,
                                         name="iotml-good-worker"))
    t.start()


def justified():
    # lint-ok: R8 short-lived join()ed helper entirely owned by this call
    t = threading.Thread(target=target, daemon=True, name="scratch")
    t.start()
    t.join()
