"""R9 (remote tier) fixture: tier writes outside iotml/store/, every
way to get it wrong — a direct upload_segment() call (1 finding), a
naked upload() of a tiered/ blob (1 finding), a put_text() on the tier
manifest (1 finding), an open() on a .stage intent marker (1 finding)
— plus the clean shapes: an upload to a non-tier artifact name and a
text write to an unrelated path (0 findings)."""


def bypass_the_uploader(tier, seg):
    # flagged: segment blob uploads are RemoteTier.upload_segment's
    # alone, and that lives in iotml/store/remote.py
    tier.upload_segment(seg.path, seg.index, seg.timeindex,
                        base=0, next_offset=10, max_ts=99)


def naked_blob_upload(store, path):
    store.upload(path, "tiered/T/0/00000000000000000000.log")


def hand_rolled_commit(store):
    store.put_text("tiered/T/0/manifest.json", "{}")


def forged_stage_marker(tmp):
    with open(tmp + "/00000000000000000000.stage", "w") as fh:
        fh.write("{}")


def plain_artifact_upload_is_fine(store, path):
    store.upload(path, "models/anomaly/v3/weights.msgpack")


def unrelated_text_write_is_fine(store):
    store.put_text("reports/daily.json", "{}")
