"""Seeded JAX trace-discipline hazards the tracecheck pass must fully
convict — plus the static idioms that must stay CLEAN (shape branches,
factories, module-level jit, the suppression round-trip).

Expected findings: 1×T1, 4×T2, 2×T3, 2×T4.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def branch_on_traced(x, flag):
    if flag:  # T1: python branch on a traced value
        return x + 1.0
    return x


@jax.jit
def float_sync(x):
    total = float(x.sum())  # T2: host sync via float()
    return x * total


@jax.jit
def item_sync(x):
    return x.mean().item()  # T2: host sync via .item()


@jax.jit
def asarray_sync(x):
    return np.asarray(x)  # T2: host pull via np.asarray


@jax.jit
def tolist_sync(x):
    return x.tolist()  # T2: host sync via .tolist()


def per_call_jit(x):
    return jax.jit(lambda y: y * 2.0)(x)  # T3: invoked immediately


def _double(y):
    return y * 2.0


def leaked_jit(x):
    f = jax.jit(_double)  # T3: neither returned, stored, nor a factory
    return f(x)


@jax.jit
def traced_shape(x, n):
    return jnp.zeros(n) + x  # T4: traced value as a shape


@jax.jit
def traced_reshape(x, n):
    return x.reshape(n)  # T4: traced reshape target


# ---- clean shapes: none of these may fire ---------------------------
@jax.jit
def static_branches(x, mode=None):
    if mode is None:  # `is` compare: resolved at trace time
        mode = "raw"
    if x.shape[0] > 4:  # attribute access: static under trace
        return x[:4]
    return x


@jax.jit
def static_arg_branch(x, scale, *, debug=False):
    del debug
    return x * scale


def make_step(scale):
    @jax.jit
    def step(x):
        return x * scale

    return step  # factory: the caller owns the compiled callable


normalize = jax.jit(lambda v: (v - v.mean()) / (v.std() + 1e-6))


class _Loop:
    def __init__(self):
        self._step = jax.jit(_double)  # stored on self: compiled once


@jax.jit
def suppressed_sync(x):
    # lint-ok: T2 fixture: the suppression round-trip — this sync is
    # the deliberate epoch-boundary readback
    return x.mean().item()
