"""R12 fixture: compaction / twin-changelog write-discipline breaches,
every way to get it wrong — a produce straight into the CAR_TWIN
changelog by literal (1 finding) and via the imported CHANGELOG_TOPIC
constant (1 finding), a direct compact_log() call (1 finding), and a
SegmentWriter opened on a .cleaned rewrite path (1 finding) — plus the
clean shapes: reading the changelog, triggering compaction through
Broker.run_compaction, and a justified suppression (0 findings).
"""


def foreign_changelog_writer(broker, state):
    # flagged: CAR_TWIN has ONE writer (TwinService).  A foreign record
    # is replayed by every rebuild — it corrupts the twin forever.
    broker.produce("CAR_TWIN", state, key=b"car-7")


def foreign_writer_via_constant(broker, CHANGELOG_TOPIC, state):
    # flagged: same breach through the named constant
    broker.produce_many(CHANGELOG_TOPIC, [(b"car-7", state, 0)])


def hand_rolled_compaction(slog, compact_log):
    # flagged: the swap protocol (durable tmp, atomic replace, sweep)
    # lives in the store; callers go through Broker.run_compaction
    return compact_log(slog, grace_ms=0)


def rewrite_tmp_by_hand(SegmentWriter, segment_path):
    # flagged: a .cleaned file outside the store's swap protocol is a
    # crash artifact recovery will sweep — or worse, trust
    w = SegmentWriter(segment_path + ".cleaned", fsync="never")
    w.close()


def reading_is_fine(broker):
    # the changelog is everyone's to READ — that is the point of it
    return broker.fetch("CAR_TWIN", 0, 0, 100)


def sanctioned_trigger_is_fine(broker):
    # the one public entry point: lock discipline + dirty-ratio gate
    return broker.run_compaction()


def justified(broker):
    # lint-ok: R12 test harness seeds a poisoned changelog on purpose
    broker.produce("CAR_TWIN", b"{}", key=b"seeded")
