// Seeded drifted C++ wire snippet: swapped in for cpp/kafka_client.cc
// by the P4 conformance test (protocol.analyze(cpp=<this file>)).
//
// Expected findings: 3×P4 — API_FETCH value skew (41 vs python's 1),
// ERR_UNKNOWN_TOPIC value skew (77 vs 3), and a request() claim on
// API_LIST_OFFSETS with no constant defining it.  API_PRODUCE = 0
// matches python and must stay clean.

#include <cstdint>

constexpr int16_t API_PRODUCE = 0, API_FETCH = 41;
constexpr int16_t ERR_UNKNOWN_TOPIC = 77;

static bool poll_once(Conn &c, const Buf &body, Resp &resp) {
  if (!request(c, API_FETCH, 2, body, resp)) return false;
  if (!request(c, API_LIST_OFFSETS, 1, body, resp)) return false;
  return true;
}
