"""Runtime fixture for the lockcheck detector: a seeded lock-order cycle
(A→B in one thread, B→A in another) and a consistent-order twin that
must stay clean.  Locks are created inside the functions so they are
instrumented when the caller installs lockcheck first."""

import threading


def run_cycle() -> None:
    """Two threads acquire two locks in opposite orders — the classic
    deadlock shape, sequenced with events so it never actually deadlocks
    (the detector works on acquisition ORDER, not on a stuck runtime)."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    first_done = threading.Event()

    def ab():
        with lock_a:
            with lock_b:
                pass
        first_done.set()

    def ba():
        first_done.wait(5)
        with lock_b:
            with lock_a:
                pass

    # lint-ok: R8 short-lived join()ed fixture threads owned by this call
    t1 = threading.Thread(target=ab)
    # lint-ok: R8 short-lived join()ed fixture threads owned by this call
    t2 = threading.Thread(target=ba)
    t1.start(); t2.start()
    t1.join(5); t2.join(5)


def run_consistent() -> None:
    """Same two locks, same nesting — but every thread honors one global
    order, so the graph stays acyclic."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def ab():
        with lock_a:
            with lock_b:
                pass

    # lint-ok: R8 short-lived join()ed fixture threads owned by this call
    threads = [threading.Thread(target=ab) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5)
