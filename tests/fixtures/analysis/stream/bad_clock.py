"""Seeded R1 violation: wall-clock deadline in a 'stream' path.

Never imported — parsed by tests/test_analysis.py to pin that the lint
flags `time.time()` timeout arithmetic in stream/mqtt modules, and that
a justified wallclock-ok read stays clean.
"""

import time


def wait_for_flag(flag, timeout_s: float = 5.0) -> bool:
    deadline = time.time() + timeout_s          # R1: non-monotonic timeout
    while time.time() < deadline:               # R1
        if flag.is_set():
            return True
        time.sleep(0.01)
    return False


def stamp_record() -> int:
    return int(time.time() * 1000)  # wallclock-ok: record timestamp, not a timeout
