"""The checker checked: iotml.analysis lint rules against seeded
violation fixtures (tests/fixtures/analysis/) and a clean tree, the
whole-program passes (protocol P1-P7, tracecheck T1-T4, drift D1-D4)
against their fixture corpora plus surface-removal sensitivity, the
static lock-order extractor and its runtime preseed, the recompile
guard's warm/retrace semantics, and the runtime lock-order/race
detector against a seeded cycle."""

import os
import subprocess
import sys
import threading
import time

import pytest

from iotml.analysis import lint as lint_mod
from iotml.analysis import lockcheck
from iotml.analysis.lint import lint_file, lint_paths

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "analysis")


def _rules_by_file(findings):
    out = {}
    for f in findings:
        out.setdefault(os.path.basename(f.path), set()).add(f.rule)
    return out


# ------------------------------------------------------------------ lint
def test_lint_flags_every_seeded_violation():
    by_file = _rules_by_file(lint_paths([FIXTURES]))
    assert by_file.get("bad_clock.py") == {"R1"}
    assert by_file.get("bad_acquire.py") == {"R3"}
    assert by_file.get("bad_retry.py") == {"R2"}
    assert by_file.get("bad_blocking.py") == {"R4"}
    assert by_file.get("bad_owned_topic.py") == {"R5"}
    assert by_file.get("bad_span_metric.py") == {"R6"}
    assert by_file.get("bad_chaos.py") == {"R7"}
    assert by_file.get("bad_store.py") == {"R9"}
    assert by_file.get("bad_tier.py") == {"R9"}
    assert by_file.get("bad_cluster.py") == {"R10"}
    assert by_file.get("bad_ckpt.py") == {"R11"}
    assert by_file.get("bad_twin.py") == {"R12"}
    assert by_file.get("bad_online.py") == {"R13"}
    assert by_file.get("bad_isr.py") == {"R15"}
    assert by_file.get("bad_gateway.py") == {"R16"}
    # a reason-less suppression is itself a finding AND does not suppress
    assert by_file.get("bad_suppression.py") == {"R3"}
    # the runtime fixture is lint-clean (locks held via `with` only)
    assert "lock_cycle.py" not in by_file


def test_lint_finding_lines_and_count():
    path = os.path.join(FIXTURES, "stream", "bad_clock.py")
    findings = lint_file(path)
    # the two deadline reads flagged; the wallclock-ok timestamp is not
    assert [f.rule for f in findings] == ["R1", "R1"]
    assert [f.line for f in findings] == [12, 13]
    assert all(str(f).startswith(f"{path}:") for f in findings)


def test_lint_r4_direct_and_transitive_but_not_outside():
    path = os.path.join(FIXTURES, "bad_blocking.py")
    findings = lint_file(path)
    assert [f.rule for f in findings] == ["R4", "R4"]
    # one direct recv, one through the _next -> _read_frame chain;
    # step_outside's recv (lock not held) stays clean
    assert "recv" in findings[0].message
    assert "_next" in findings[1].message or "recv" in findings[1].message


def test_lint_r6_naming_and_span_under_lock():
    """R6 both halves: naming convention (metric + stage literals) and
    span recording under a held lock, direct and through the module
    call-graph walk (reused from R4)."""
    path = os.path.join(FIXTURES, "bad_span_metric.py")
    findings = lint_file(path)
    assert [f.rule for f in findings] == ["R6"] * 5
    assert [f.line for f in findings] == [12, 20, 24, 27, 35]
    msgs = [f.message for f in findings]
    assert "iotml-Records.Total" in msgs[0]          # malformed family name
    assert "while holding _lock" in msgs[1]          # direct mark under lock
    assert "_note()" in msgs[2]                      # transitive chain named
    assert "Decode-Stage" in msgs[3]                 # malformed stage name
    assert "car_id" in msgs[4]                       # runaway label key
    assert "vocabulary" in msgs[4]
    # the lint mirror and the runtime bound test must enforce ONE
    # vocabulary — a key added to either set alone silently forks the
    # closed label discipline
    from iotml.analysis.lint import _ALLOWED_METRIC_LABELS
    from iotml.obs.metrics import ALLOWED_LABEL_KEYS

    assert _ALLOWED_METRIC_LABELS == ALLOWED_LABEL_KEYS
    # clean shapes stay clean: a conforming iotml_ name, a mark with no
    # lock held, and a closed-vocabulary label produced no findings
    # (exactly the 5 above)


def test_lint_r7_chaos_allowlist_and_shim_discipline(tmp_path):
    """R7 all three shapes: a non-shim chaos import, a shim import
    outside the allowlist, and a chaos.point() call outside the
    allowlist — plus the only-the-shim rule holding ON an allowlisted
    module."""
    path = os.path.join(FIXTURES, "bad_chaos.py")
    findings = lint_file(path)
    assert [f.rule for f in findings] == ["R7"] * 3
    assert [f.line for f in findings] == [7, 8, 12]
    assert "allowlist" in findings[1].message
    assert "broker.fetch" in findings[2].message
    # an allowlisted module importing scenario machinery is still flagged
    bad = tmp_path / "broker.py"
    bad.write_text("from ..chaos import scenarios\n")
    findings = lint_file(str(bad), rel="iotml/stream/broker.py")
    assert [f.rule for f in findings] == ["R7"]
    assert "shim" in findings[0].message
    # the evasion form — the package via the alias list, not the module
    # path — is flagged everywhere, allowlisted or not
    for rel in ("iotml/stream/broker.py", "iotml/serve/live.py"):
        for stmt in ("from iotml import chaos\n", "from .. import chaos\n"):
            evade = tmp_path / "evade.py"
            evade.write_text(stmt)
            findings = lint_file(str(evade), rel=rel)
            assert [f.rule for f in findings] == ["R7"], (rel, stmt)
    # while the real allowlisted shim import form stays clean
    ok = tmp_path / "ok_broker.py"
    ok.write_text("from ..chaos import faults as chaos\n"
                  "def fetch():\n    chaos.point('broker.fetch')\n")
    assert lint_file(str(ok), rel="iotml/stream/broker.py") == []


def test_r7_allowlist_matches_the_tree():
    """Every module on CHAOS_ALLOWED_MODULES actually compiles in a
    faultpoint, and every compiled-in faultpoint name is registered —
    the allowlist and the registry cannot drift from the code."""
    import re

    from iotml.chaos import faults

    root = lint_mod.default_root()
    used = set()
    for pkg, fn in lint_mod.CHAOS_ALLOWED_MODULES:
        src = open(os.path.join(root, pkg, fn)).read()
        names = re.findall(r"chaos\.point\(\"([^\"]+)\"\)", src)
        assert names, f"{pkg}/{fn} is allowlisted but has no faultpoint"
        used.update(names)
    assert used == set(faults.KNOWN_POINTS), (
        "faultpoint registry out of sync with compiled-in sites")


def test_lint_clean_on_the_tree():
    findings = lint_paths([lint_mod.default_root()])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_lint_cli_exit_codes():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    clean = subprocess.run(
        [sys.executable, "-m", "iotml.analysis", "lint", "--quiet"],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(lint_mod.default_root()))
    assert clean.returncode == 0, clean.stdout + clean.stderr
    seeded = subprocess.run(
        [sys.executable, "-m", "iotml.analysis", "lint", "--quiet", FIXTURES],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(lint_mod.default_root()))
    assert seeded.returncode == 1
    # file:line findings on stdout, machine-parseable
    assert any(":12: R1" in ln for ln in seeded.stdout.splitlines())


def test_r2_allowlist_pinned_to_the_wire_client():
    """The lint's name allowlist and the client's api-key allowlist are
    the same set — a drift would let the lint pass call sites the client
    no longer auto-retries (or vice versa)."""
    from iotml.stream import kafka_wire as kw

    lint_keys = {getattr(kw, name) for name in lint_mod.IDEMPOTENT_API_NAMES}
    assert lint_keys == set(kw.IDEMPOTENT_APIS)


# -------------------------------------------------------------- lockcheck
@pytest.fixture
def fresh_lockcheck():
    """Isolated install: skips if a session-level lockcheck is already
    live (IOTML_LOCKCHECK=1 runs), since its State is shared."""
    if lockcheck.state() is not None:
        pytest.skip("session-level lockcheck active")
    st = lockcheck.install()
    try:
        yield st
    finally:
        lockcheck.uninstall()


def test_lockcheck_flags_seeded_cycle(fresh_lockcheck):
    sys.modules.pop("tests.fixtures.analysis.lock_cycle", None)
    sys.path.insert(0, FIXTURES)
    try:
        import lock_cycle
    finally:
        sys.path.remove(FIXTURES)
    lock_cycle.run_consistent()
    assert fresh_lockcheck.cycles() == []
    lock_cycle.run_cycle()
    cycles = fresh_lockcheck.cycles()
    assert len(cycles) == 1
    assert "lock_cycle.py" in cycles[0].message


def test_lockcheck_flags_sleep_under_lock(fresh_lockcheck):
    time.sleep(0)  # no lock held: clean
    assert not any(v.kind == "io-under-lock"
                   for v in fresh_lockcheck.violations)
    lk = threading.Lock()
    with lk:
        time.sleep(0)
    kinds = [v.kind for v in fresh_lockcheck.violations]
    assert "io-under-lock" in kinds
    assert fresh_lockcheck.cycles() == []


def test_lockcheck_watched_dict_lock_and_owner_modes(fresh_lockcheck):
    lk = threading.Lock()
    table = lockcheck.WatchedDict({}, "t.guarded", lock=lk)
    with lk:
        table["ok"] = 1
    assert not fresh_lockcheck.violations
    table["bad"] = 2
    assert any(v.kind == "unguarded-mutation" and "t.guarded" in v.message
               for v in fresh_lockcheck.violations)

    owned = lockcheck.WatchedDict({}, "t.owned")
    owned["claims-ownership"] = 1            # first mutator becomes owner
    t = threading.Thread(target=owned.__setitem__, args=("other", 2))
    t.start(); t.join(5)
    assert any(v.kind == "unguarded-mutation" and "t.owned" in v.message
               for v in fresh_lockcheck.violations)


def test_lockcheck_broker_commit_is_guarded(fresh_lockcheck):
    """The Broker created under lockcheck gets watched tables, and the
    whole public mutation surface holds the broker lock — including
    commit(), which the detector originally caught writing the group
    table lock-free."""
    from iotml.stream.broker import Broker

    b = Broker()
    assert isinstance(b._group_offsets, lockcheck.WatchedDict)
    b.create_topic("t", partitions=2)
    b.produce("t", b"v")
    b.commit("g", "t", 0, 7)
    assert b.committed("g", "t", 0) == 7
    bad = [v for v in fresh_lockcheck.violations
           if v.kind == "unguarded-mutation"]
    assert bad == [], bad


def test_lockcheck_uninstall_restores_everything():
    if lockcheck.state() is not None:
        pytest.skip("session-level lockcheck active")
    lockcheck.install()
    assert isinstance(threading.Lock(), lockcheck.CheckedLock)
    lockcheck.uninstall()
    assert threading.Lock is lockcheck._REAL_LOCK
    assert time.sleep is lockcheck._REAL_SLEEP
    assert type(threading.Lock()).__module__ == "_thread"


def test_lockcheck_condition_integration(fresh_lockcheck):
    """Condition/Event built over checked locks must keep the held-stack
    truthful across wait() (RLock _release_save/_acquire_restore)."""
    cv = threading.Condition()           # RLock() -> CheckedRLock
    done = []

    def waiter():
        with cv:
            cv.wait(timeout=5)
            done.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify()
    t.join(5)
    assert done == [True]
    ev = threading.Event()
    threading.Thread(target=ev.set).start()
    assert ev.wait(5)
    assert fresh_lockcheck.cycles() == []


# ----------------------------------------------- whole-program passes
from iotml.analysis import drift as drift_mod  # noqa: E402
from iotml.analysis import lockorder  # noqa: E402
from iotml.analysis import protocol as protocol_mod  # noqa: E402
from iotml.analysis import tracecheck as trace_mod  # noqa: E402


def _rule_counts(findings):
    out = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out


def test_protocol_clean_on_the_tree():
    findings = protocol_mod.analyze()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_tracecheck_clean_on_the_tree():
    findings = trace_mod.analyze()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_drift_clean_on_the_tree():
    findings = drift_mod.analyze()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_protocol_fixture_catches_every_seeded_skew():
    findings = protocol_mod.check_wire(
        os.path.join(FIXTURES, "bad_protocol.py"))
    assert _rule_counts(findings) == {"P1": 3, "P2": 2, "P3": 1,
                                      "P5": 1, "P6": 1}
    msgs = " ".join(f.message for f in findings)
    assert "API_ORPHAN" in msgs          # P1 unhandled + P2 unencoded
    assert "API_GHOST" in msgs           # P1 disowned branch + P5
    assert "API_MYSTERY" in msgs         # P2 unknown constant
    assert "ERR_MESSAGE_TOO_LARGE" in msgs   # P3 untyped code
    assert "bare error code 41" in msgs  # P1 unnamed numeric code
    # the justified '# lint-ok: P6' site round-trips as suppressed
    assert "suppressed_probe" not in msgs


def test_tracecheck_fixture_catches_every_seeded_hazard():
    findings = trace_mod.analyze(
        paths=[os.path.join(FIXTURES, "bad_trace.py")])
    assert _rule_counts(findings) == {"T1": 1, "T2": 4, "T3": 2, "T4": 2}
    msgs = " ".join(f.message for f in findings)
    assert "'flag'" in msgs                       # T1 names the value
    assert "float()" in msgs and ".item()" in msgs
    assert "np.asarray" in msgs and ".tolist()" in msgs
    assert "invoked immediately" in msgs          # T3 per-call jit
    assert "'leaked_jit'" in msgs                 # T3 leaked closure
    assert "zeros()" in msgs and "reshape()" in msgs
    # the clean idioms stayed clean: factory, module-level jit,
    # self-stored jit, shape/is-None branches, and the suppressed sync
    flagged = {f.line for f in findings}
    lines = open(os.path.join(FIXTURES, "bad_trace.py")).read().splitlines()
    clean_from = next(i for i, ln in enumerate(lines, start=1)
                      if "clean shapes" in ln)
    assert not [ln for ln in flagged if ln > clean_from]


def test_drift_fixture_catches_every_seeded_drift():
    findings = drift_mod.analyze(
        paths=[os.path.join(FIXTURES, "bad_drift.py")])
    assert _rule_counts(findings) == {"D1": 2, "D2": 2, "D3": 1}
    msgs = " ".join(f.message for f in findings)
    assert "IOTML_BOGUS_KNOB" in msgs and "IOTML_PHANTOM" in msgs
    assert "fixture_total" in msgs and "ghost_total" in msgs
    assert "fixture.bogus_fault" in msgs
    # the justified '# lint-ok: D1' knob read stayed suppressed
    assert "IOTML_SUPPRESSED_KNOB" not in msgs


def test_protocol_cpp_skew_fixture():
    """The skewed C++ snippet against the REAL python wire: value
    drift both ways plus a claim with no constant, all P4."""
    findings = protocol_mod.analyze(
        cpp=os.path.join(FIXTURES, "bad_wire.cc"))
    assert _rule_counts(findings) == {"P4": 3}
    msgs = " ".join(f.message for f in findings)
    assert "API_FETCH = 41" in msgs
    assert "ERR_UNKNOWN_TOPIC = 77" in msgs
    assert "API_LIST_OFFSETS" in msgs


def _mutated(tmp_path, src_path, old, new, name):
    src = open(src_path).read()
    assert old in src, f"mutation anchor vanished from {src_path}"
    p = tmp_path / name
    p.write_text(src.replace(old, new, 1))
    return str(p)


def test_protocol_is_four_surface_sensitive(tmp_path):
    """Removing any ONE api mapping from any surface makes the pass
    fail — the cross-check provably covers server, client, cluster
    router, C++ client, and the lint mirror."""
    root = lint_mod.default_root()
    wire = os.path.join(root, "stream", "kafka_wire.py")
    cluster = os.path.join(root, "cluster", "client.py")

    # server surface: drop FETCH from _SUPPORTED -> its dispatch
    # branch is orphaned (P1)
    skewed = _mutated(tmp_path, wire, "FETCH: (2, 2),", "", "w1.py")
    rules = {f.rule for f in protocol_mod.analyze(wire=skewed)}
    assert "P1" in rules

    # client surface: neuter produce_many's typed compare against
    # INVALID_REQUIRED_ACKS -> the server-emittable code loses its
    # mapping (P3)
    skewed = _mutated(tmp_path, wire,
                      "err == ERR_INVALID_REQUIRED_ACKS",
                      "err == ERR_NONE", "w2.py")
    findings = protocol_mod.analyze(wire=skewed)
    assert any(f.rule == "P3"
               and "ERR_INVALID_REQUIRED_ACKS" in f.message
               for f in findings)

    # cluster surface: point a delegation at a method the wire client
    # does not define (P2)
    skewed = _mutated(tmp_path, cluster, "c.heartbeat_group(",
                      "c.heartbeat_missing(", "c1.py")
    findings = protocol_mod.analyze(cluster=skewed)
    assert any(f.rule == "P2" and "heartbeat_missing" in f.message
               for f in findings)

    # lint-mirror surface: drop FETCH from the idempotency mirror (P5)
    trimmed = [n for n in lint_mod.IDEMPOTENT_API_NAMES if n != "FETCH"]
    findings = protocol_mod.analyze(lint_idempotent=trimmed)
    assert any(f.rule == "P5" and "FETCH" in f.message
               for f in findings)

    # (C++ surface sensitivity: test_protocol_cpp_skew_fixture above)


def test_drift_d4_flags_missing_doc_rows(tmp_path):
    """A doc with only P1's row: every other rule id is a D4."""
    doc = tmp_path / "ARCH.md"
    doc.write_text("| Rule | Contract |\n|---|---|\n| P1 | covered |\n")
    findings = drift_mod.analyze(paths=[], architecture=str(doc))
    assert findings and {f.rule for f in findings} == {"D4"}
    missing = " ".join(f.message for f in findings)
    for rid in ("P2", "T1", "T4", "D1", "D4", "R1"):
        assert f"rule {rid} " in missing
    assert "rule P1 " not in missing


def test_recompile_guard_counts_and_hot_loop_wrap():
    import jax
    import jax.numpy as jnp

    trace_mod.reset_warm()
    x = jnp.ones((4,), jnp.float32)
    f = jax.jit(lambda v: v * 2.0)
    f(x)  # warm-up trace
    with trace_mod.expect_no_recompile("warmed jit"):
        f(x)
    with pytest.raises(trace_mod.RecompileError):
        with trace_mod.expect_no_recompile("cold jit"):
            jax.jit(lambda v: v * 3.0)(x)  # fresh closure: compiles

    class Good:
        def __init__(self):
            self._step = jax.jit(lambda v: v + 1.0)

        def step(self, v):
            return self._step(v)

    Good.step = trace_mod.guard_hot_loop(Good.step, "Good.step")
    g = Good()
    g.step(x)   # warm-up
    g.step(x)   # cached: no compile, no error
    g.step(jnp.ones((8,), jnp.float32))  # new signature: legal compile

    class Bad:
        def step(self, v):
            return jax.jit(lambda q: q * 2.0)(v)  # fresh jit per call

    Bad.step = trace_mod.guard_hot_loop(Bad.step, "Bad.step")
    b = Bad()
    b.step(x)   # warm-up call is allowed to compile
    with pytest.raises(trace_mod.RecompileError):
        b.step(x)  # identical signature retraced: the guard fails it
    trace_mod.reset_warm()


def test_runtime_guard_targets_exist():
    """Every _GUARD_TARGETS row resolves to a real method — a rename
    would silently disarm the IOTML_TRACECHECK=1 guard."""
    import importlib

    for mod_name, cls_name, meth in trace_mod._GUARD_TARGETS:
        cls = getattr(importlib.import_module(mod_name), cls_name)
        assert meth in cls.__dict__, (cls_name, meth)
    if os.environ.get("IOTML_TRACECHECK"):
        pytest.skip("session-level traceguard active")
    patched = trace_mod.install_runtime_guard()
    try:
        assert set(patched) == {f"{c}.{m}"
                                for _, c, m in trace_mod._GUARD_TARGETS}
        # idempotent: a second install patches nothing new
        assert trace_mod.install_runtime_guard() == []
    finally:
        # unwrap so the guard doesn't leak into unrelated tests (no
        # per-test reset_warm runs outside IOTML_TRACECHECK sessions)
        for mod_name, cls_name, meth in trace_mod._GUARD_TARGETS:
            cls = getattr(importlib.import_module(mod_name), cls_name)
            fn = cls.__dict__[meth]
            if getattr(fn, "__iotml_traceguard__", False):
                setattr(cls, meth, fn.__wrapped__)
        trace_mod.reset_warm()


def test_lockorder_extracts_real_edges():
    edges = lockorder.analyze()
    assert any("stream/broker.py" in a and "stream/broker.py" in b
               for a, b, _ in edges), edges
    # the live tree must stay acyclic
    assert lockorder.cycles_among(edges) == []


_LOCK_CYCLE_SRC = '''\
import threading


class T:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def _inner_b(self):
        with self._b:
            pass

    def ab(self):
        with self._a:
            self._inner_b()

    def ba(self):
        with self._b, self._a:
            pass
'''


def test_lockorder_static_cycle_detected(tmp_path):
    p = tmp_path / "cycle_mod.py"
    p.write_text(_LOCK_CYCLE_SRC)
    edges = lockorder.analyze(paths=[str(p)])
    # a->b through the called method, b->a through the multi-item with
    assert len(edges) == 2, edges
    cycles = lockorder.cycles_among(edges)
    assert len(cycles) == 1, cycles


def test_lockorder_preseed_static(fresh_lockcheck):
    n = lockorder.preseed(state=fresh_lockcheck,
                          edges=[("f.py:1", "f.py:2", "f.py:10")])
    assert n == 1
    assert fresh_lockcheck.violations == []
    # the opposite static edge closes a cycle: surfaced as a warning
    # kind, NOT a hard 'cycle' (strict mode promotes it)
    n = lockorder.preseed(state=fresh_lockcheck,
                          edges=[("f.py:2", "f.py:1", "f.py:20")])
    assert n == 1
    kinds = [v.kind for v in fresh_lockcheck.violations]
    assert kinds == ["static-cycle"]
    assert fresh_lockcheck.cycles() == []
    # re-seeding the same edge is a no-op
    assert lockorder.preseed(state=fresh_lockcheck,
                             edges=[("f.py:1", "f.py:2", "f.py:10")]) == 0


def test_analysis_cli_all_shares_one_parse():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "iotml.analysis", "all"],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(lint_mod.default_root()))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "parsed once" in r.stderr
    # one parse per file: the summary's file count equals the walk,
    # not rules x files
    assert "0 finding(s)" in r.stderr


def test_rule_tables_are_disjoint_and_documented():
    families = [lint_mod.RULES, protocol_mod.PASS_RULES,
                trace_mod.PASS_RULES, drift_mod.PASS_RULES]
    seen = set()
    for table in families:
        assert not (set(table) & seen)
        seen |= set(table)
    assert {"P1", "P7", "T1", "T4", "D1", "D4"} <= seen
    # and the tree's own doc carries every row (D4 clean = tested above
    # via test_drift_clean_on_the_tree)
