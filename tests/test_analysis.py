"""The checker checked: iotml.analysis lint rules R1-R5 against seeded
violation fixtures (tests/fixtures/analysis/) and a clean tree, the
runtime lock-order/race detector against a seeded cycle, and the
allowlist the R2 lint enforces pinned to the client that implements it."""

import os
import subprocess
import sys
import threading
import time

import pytest

from iotml.analysis import lint as lint_mod
from iotml.analysis import lockcheck
from iotml.analysis.lint import lint_file, lint_paths

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "analysis")


def _rules_by_file(findings):
    out = {}
    for f in findings:
        out.setdefault(os.path.basename(f.path), set()).add(f.rule)
    return out


# ------------------------------------------------------------------ lint
def test_lint_flags_every_seeded_violation():
    by_file = _rules_by_file(lint_paths([FIXTURES]))
    assert by_file.get("bad_clock.py") == {"R1"}
    assert by_file.get("bad_acquire.py") == {"R3"}
    assert by_file.get("bad_retry.py") == {"R2"}
    assert by_file.get("bad_blocking.py") == {"R4"}
    assert by_file.get("bad_owned_topic.py") == {"R5"}
    assert by_file.get("bad_span_metric.py") == {"R6"}
    assert by_file.get("bad_chaos.py") == {"R7"}
    assert by_file.get("bad_store.py") == {"R9"}
    assert by_file.get("bad_cluster.py") == {"R10"}
    assert by_file.get("bad_ckpt.py") == {"R11"}
    assert by_file.get("bad_twin.py") == {"R12"}
    assert by_file.get("bad_online.py") == {"R13"}
    assert by_file.get("bad_isr.py") == {"R15"}
    # a reason-less suppression is itself a finding AND does not suppress
    assert by_file.get("bad_suppression.py") == {"R3"}
    # the runtime fixture is lint-clean (locks held via `with` only)
    assert "lock_cycle.py" not in by_file


def test_lint_finding_lines_and_count():
    path = os.path.join(FIXTURES, "stream", "bad_clock.py")
    findings = lint_file(path)
    # the two deadline reads flagged; the wallclock-ok timestamp is not
    assert [f.rule for f in findings] == ["R1", "R1"]
    assert [f.line for f in findings] == [12, 13]
    assert all(str(f).startswith(f"{path}:") for f in findings)


def test_lint_r4_direct_and_transitive_but_not_outside():
    path = os.path.join(FIXTURES, "bad_blocking.py")
    findings = lint_file(path)
    assert [f.rule for f in findings] == ["R4", "R4"]
    # one direct recv, one through the _next -> _read_frame chain;
    # step_outside's recv (lock not held) stays clean
    assert "recv" in findings[0].message
    assert "_next" in findings[1].message or "recv" in findings[1].message


def test_lint_r6_naming_and_span_under_lock():
    """R6 both halves: naming convention (metric + stage literals) and
    span recording under a held lock, direct and through the module
    call-graph walk (reused from R4)."""
    path = os.path.join(FIXTURES, "bad_span_metric.py")
    findings = lint_file(path)
    assert [f.rule for f in findings] == ["R6"] * 5
    assert [f.line for f in findings] == [12, 20, 24, 27, 35]
    msgs = [f.message for f in findings]
    assert "iotml-Records.Total" in msgs[0]          # malformed family name
    assert "while holding _lock" in msgs[1]          # direct mark under lock
    assert "_note()" in msgs[2]                      # transitive chain named
    assert "Decode-Stage" in msgs[3]                 # malformed stage name
    assert "car_id" in msgs[4]                       # runaway label key
    assert "vocabulary" in msgs[4]
    # the lint mirror and the runtime bound test must enforce ONE
    # vocabulary — a key added to either set alone silently forks the
    # closed label discipline
    from iotml.analysis.lint import _ALLOWED_METRIC_LABELS
    from iotml.obs.metrics import ALLOWED_LABEL_KEYS

    assert _ALLOWED_METRIC_LABELS == ALLOWED_LABEL_KEYS
    # clean shapes stay clean: a conforming iotml_ name, a mark with no
    # lock held, and a closed-vocabulary label produced no findings
    # (exactly the 5 above)


def test_lint_r7_chaos_allowlist_and_shim_discipline(tmp_path):
    """R7 all three shapes: a non-shim chaos import, a shim import
    outside the allowlist, and a chaos.point() call outside the
    allowlist — plus the only-the-shim rule holding ON an allowlisted
    module."""
    path = os.path.join(FIXTURES, "bad_chaos.py")
    findings = lint_file(path)
    assert [f.rule for f in findings] == ["R7"] * 3
    assert [f.line for f in findings] == [7, 8, 12]
    assert "allowlist" in findings[1].message
    assert "broker.fetch" in findings[2].message
    # an allowlisted module importing scenario machinery is still flagged
    bad = tmp_path / "broker.py"
    bad.write_text("from ..chaos import scenarios\n")
    findings = lint_file(str(bad), rel="iotml/stream/broker.py")
    assert [f.rule for f in findings] == ["R7"]
    assert "shim" in findings[0].message
    # the evasion form — the package via the alias list, not the module
    # path — is flagged everywhere, allowlisted or not
    for rel in ("iotml/stream/broker.py", "iotml/serve/live.py"):
        for stmt in ("from iotml import chaos\n", "from .. import chaos\n"):
            evade = tmp_path / "evade.py"
            evade.write_text(stmt)
            findings = lint_file(str(evade), rel=rel)
            assert [f.rule for f in findings] == ["R7"], (rel, stmt)
    # while the real allowlisted shim import form stays clean
    ok = tmp_path / "ok_broker.py"
    ok.write_text("from ..chaos import faults as chaos\n"
                  "def fetch():\n    chaos.point('broker.fetch')\n")
    assert lint_file(str(ok), rel="iotml/stream/broker.py") == []


def test_r7_allowlist_matches_the_tree():
    """Every module on CHAOS_ALLOWED_MODULES actually compiles in a
    faultpoint, and every compiled-in faultpoint name is registered —
    the allowlist and the registry cannot drift from the code."""
    import re

    from iotml.chaos import faults

    root = lint_mod.default_root()
    used = set()
    for pkg, fn in lint_mod.CHAOS_ALLOWED_MODULES:
        src = open(os.path.join(root, pkg, fn)).read()
        names = re.findall(r"chaos\.point\(\"([^\"]+)\"\)", src)
        assert names, f"{pkg}/{fn} is allowlisted but has no faultpoint"
        used.update(names)
    assert used == set(faults.KNOWN_POINTS), (
        "faultpoint registry out of sync with compiled-in sites")


def test_lint_clean_on_the_tree():
    findings = lint_paths([lint_mod.default_root()])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_lint_cli_exit_codes():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    clean = subprocess.run(
        [sys.executable, "-m", "iotml.analysis", "lint", "--quiet"],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(lint_mod.default_root()))
    assert clean.returncode == 0, clean.stdout + clean.stderr
    seeded = subprocess.run(
        [sys.executable, "-m", "iotml.analysis", "lint", "--quiet", FIXTURES],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(lint_mod.default_root()))
    assert seeded.returncode == 1
    # file:line findings on stdout, machine-parseable
    assert any(":12: R1" in ln for ln in seeded.stdout.splitlines())


def test_r2_allowlist_pinned_to_the_wire_client():
    """The lint's name allowlist and the client's api-key allowlist are
    the same set — a drift would let the lint pass call sites the client
    no longer auto-retries (or vice versa)."""
    from iotml.stream import kafka_wire as kw

    lint_keys = {getattr(kw, name) for name in lint_mod.IDEMPOTENT_API_NAMES}
    assert lint_keys == set(kw.IDEMPOTENT_APIS)


# -------------------------------------------------------------- lockcheck
@pytest.fixture
def fresh_lockcheck():
    """Isolated install: skips if a session-level lockcheck is already
    live (IOTML_LOCKCHECK=1 runs), since its State is shared."""
    if lockcheck.state() is not None:
        pytest.skip("session-level lockcheck active")
    st = lockcheck.install()
    try:
        yield st
    finally:
        lockcheck.uninstall()


def test_lockcheck_flags_seeded_cycle(fresh_lockcheck):
    sys.modules.pop("tests.fixtures.analysis.lock_cycle", None)
    sys.path.insert(0, FIXTURES)
    try:
        import lock_cycle
    finally:
        sys.path.remove(FIXTURES)
    lock_cycle.run_consistent()
    assert fresh_lockcheck.cycles() == []
    lock_cycle.run_cycle()
    cycles = fresh_lockcheck.cycles()
    assert len(cycles) == 1
    assert "lock_cycle.py" in cycles[0].message


def test_lockcheck_flags_sleep_under_lock(fresh_lockcheck):
    time.sleep(0)  # no lock held: clean
    assert not any(v.kind == "io-under-lock"
                   for v in fresh_lockcheck.violations)
    lk = threading.Lock()
    with lk:
        time.sleep(0)
    kinds = [v.kind for v in fresh_lockcheck.violations]
    assert "io-under-lock" in kinds
    assert fresh_lockcheck.cycles() == []


def test_lockcheck_watched_dict_lock_and_owner_modes(fresh_lockcheck):
    lk = threading.Lock()
    table = lockcheck.WatchedDict({}, "t.guarded", lock=lk)
    with lk:
        table["ok"] = 1
    assert not fresh_lockcheck.violations
    table["bad"] = 2
    assert any(v.kind == "unguarded-mutation" and "t.guarded" in v.message
               for v in fresh_lockcheck.violations)

    owned = lockcheck.WatchedDict({}, "t.owned")
    owned["claims-ownership"] = 1            # first mutator becomes owner
    t = threading.Thread(target=owned.__setitem__, args=("other", 2))
    t.start(); t.join(5)
    assert any(v.kind == "unguarded-mutation" and "t.owned" in v.message
               for v in fresh_lockcheck.violations)


def test_lockcheck_broker_commit_is_guarded(fresh_lockcheck):
    """The Broker created under lockcheck gets watched tables, and the
    whole public mutation surface holds the broker lock — including
    commit(), which the detector originally caught writing the group
    table lock-free."""
    from iotml.stream.broker import Broker

    b = Broker()
    assert isinstance(b._group_offsets, lockcheck.WatchedDict)
    b.create_topic("t", partitions=2)
    b.produce("t", b"v")
    b.commit("g", "t", 0, 7)
    assert b.committed("g", "t", 0) == 7
    bad = [v for v in fresh_lockcheck.violations
           if v.kind == "unguarded-mutation"]
    assert bad == [], bad


def test_lockcheck_uninstall_restores_everything():
    if lockcheck.state() is not None:
        pytest.skip("session-level lockcheck active")
    lockcheck.install()
    assert isinstance(threading.Lock(), lockcheck.CheckedLock)
    lockcheck.uninstall()
    assert threading.Lock is lockcheck._REAL_LOCK
    assert time.sleep is lockcheck._REAL_SLEEP
    assert type(threading.Lock()).__module__ == "_thread"


def test_lockcheck_condition_integration(fresh_lockcheck):
    """Condition/Event built over checked locks must keep the held-stack
    truthful across wait() (RLock _release_save/_acquire_restore)."""
    cv = threading.Condition()           # RLock() -> CheckedRLock
    done = []

    def waiter():
        with cv:
            cv.wait(timeout=5)
            done.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify()
    t.join(5)
    assert done == [True]
    ev = threading.Event()
    threading.Thread(target=ev.set).start()
    assert ev.wait(5)
    assert fresh_lockcheck.cycles() == []
