"""Attention stack: flash kernel (interpreted) and ring attention must match
the jnp reference exactly, including causal masking across shards."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iotml.ops.attention import (attention_reference, blockwise_update,
                                 finalize_blockwise, flash_attention)
from iotml.parallel.mesh import make_mesh
from iotml.parallel.ring_attention import make_ring_attention


def _qkv(B=2, T=32, H=2, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)  # noqa: E731
    return mk(), mk(), mk()


def test_reference_attention_is_causal():
    q, k, v = _qkv()
    out = attention_reference(q, k, v, causal=True)
    # changing future keys must not affect past outputs
    k2 = k.at[:, 20:].set(0.0)
    v2 = v.at[:, 20:].set(0.0)
    out2 = attention_reference(q, k2, v2, causal=True)
    np.testing.assert_allclose(out[:, :20], out2[:, :20], rtol=1e-6, atol=1e-6)


def test_blockwise_update_equals_reference():
    """Folding KV in 4 blocks through the online softmax == full softmax."""
    q, k, v = _qkv(T=32)
    B, T, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    o = jnp.zeros((B, T, H, D), jnp.float32)
    m = jnp.full((B, H, T), -1e30, jnp.float32)
    l = jnp.zeros((B, H, T), jnp.float32)
    qpos = np.arange(T)
    for blk in range(4):
        sl = slice(blk * 8, (blk + 1) * 8)
        kpos = np.arange(T)[sl]
        mask = jnp.asarray(qpos[:, None] >= kpos[None, :])
        o, m, l = blockwise_update(o, m, l, q, k[:, sl], v[:, sl], scale, mask)
    got = finalize_blockwise(o, l)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("T,block", [(32, 16), (40, 16)])
def test_flash_attention_interpreted_matches_reference(T, block):
    q, k, v = _qkv(T=T)
    got = flash_attention(q, k, v, causal=True, block_q=block, block_k=block,
                          interpret=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_grads_match_reference():
    """Custom VJP (blockwise recompute backward) vs dense autodiff."""
    q, k, v = _qkv(T=40)
    f = lambda q, k, v: jnp.sum(  # noqa: E731
        jnp.sin(flash_attention(q, k, v, True, 16, 16, True)))
    r = lambda q, k, v: jnp.sum(  # noqa: E731
        jnp.sin(attention_reference(q, k, v, causal=True)))
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ring_attention_matches_reference():
    mesh = make_mesh((8,), ("seq",))
    q, k, v = _qkv(T=64)
    ring = make_ring_attention(mesh, "seq", causal=True)
    got = ring(q, k, v)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_non_causal():
    mesh = make_mesh((4,), ("seq",), devices=jax.devices()[:4])
    q, k, v = _qkv(T=32, seed=3)
    ring = make_ring_attention(mesh, "seq", causal=False)
    got = ring(q, k, v)
    want = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_output_is_seq_sharded():
    mesh = make_mesh((8,), ("seq",))
    q, k, v = _qkv(T=64)
    ring = make_ring_attention(mesh, "seq")
    out = ring(q, k, v)
    assert len(out.sharding.device_set) == 8
