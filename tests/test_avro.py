"""Avro codec: roundtrip, nullable unions, framing, columnar decode."""

import numpy as np
import pytest

from iotml.core.schema import CAR_SCHEMA, KSQL_CAR_SCHEMA
from iotml.ops.avro import AvroCodec, zigzag_encode, zigzag_decode
from iotml.ops.framing import frame, unframe, strip_frame


def test_zigzag():
    for n in [0, 1, -1, 2, -2, 63, 64, -64, 100000, -100000, 2**40, -(2**40)]:
        enc = zigzag_encode(n)
        dec, pos = zigzag_decode(enc, 0)
        assert dec == n and pos == len(enc)


def _sample_record(schema, label="false"):
    rec = {}
    for i, f in enumerate(schema.fields):
        if schema.label_field and f.name == schema.label_field:
            rec[f.name] = label
        elif f.avro_type in ("int", "long"):
            rec[f.name] = 20 + i
        else:
            rec[f.name] = float(i) + 0.5
    return rec


@pytest.mark.parametrize("schema", [CAR_SCHEMA, KSQL_CAR_SCHEMA],
                         ids=["producer", "ksql"])
def test_roundtrip(schema):
    codec = AvroCodec(schema)
    rec = _sample_record(schema)
    out = codec.decode(codec.encode(rec))
    for f in schema.fields:
        if f.avro_type == "float":
            assert out[f.name] == pytest.approx(rec[f.name], rel=1e-6)
        else:
            assert out[f.name] == rec[f.name]


def test_nulls_roundtrip():
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    rec = {f.name: None for f in KSQL_CAR_SCHEMA.fields}
    assert codec.decode(codec.encode(rec)) == rec


def test_avro_interop_with_fastavro_if_present():
    """Cross-check our wire bytes against an independent Avro implementation."""
    fastavro = pytest.importorskip("fastavro")
    import io, json  # noqa: E401

    codec = AvroCodec(KSQL_CAR_SCHEMA)
    rec = _sample_record(KSQL_CAR_SCHEMA)
    parsed = fastavro.parse_schema(json.loads(KSQL_CAR_SCHEMA.avro_json()))
    buf = io.BytesIO()
    fastavro.schemaless_writer(buf, parsed, rec)
    theirs = buf.getvalue()
    assert codec.encode(rec) == theirs
    assert codec.decode(theirs) == rec


def test_framing():
    payload = b"\x01\x02\x03"
    framed = frame(payload, schema_id=7)
    assert len(framed) == 8
    sid, body = unframe(framed)
    assert sid == 7 and body == payload
    assert strip_frame(framed) == payload
    with pytest.raises(ValueError):
        unframe(b"\x01" + b"\x00" * 7)


def test_decode_batch_columnar():
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    msgs = [codec.encode(_sample_record(KSQL_CAR_SCHEMA, label=l))
            for l in ("false", "true", "")]
    cols = codec.decode_batch(msgs)
    assert cols["FAILURE_OCCURRED"].tolist() == ["false", "true", ""]
    assert cols["SPEED"].dtype == np.float64
    assert cols["SPEED"].shape == (3,)
    mat = codec.sensor_matrix(cols)
    assert mat.shape == (3, 18)
    # column order is schema order
    assert mat[0, 0] == cols["COOLANT_TEMP"][0]
