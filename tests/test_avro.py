"""Avro codec: roundtrip, nullable unions, framing, columnar decode."""

import numpy as np
import pytest

from iotml.core.schema import CAR_SCHEMA, KSQL_CAR_SCHEMA
from iotml.ops.avro import AvroCodec, zigzag_encode, zigzag_decode
from iotml.ops.framing import frame, unframe, strip_frame


def test_zigzag():
    for n in [0, 1, -1, 2, -2, 63, 64, -64, 100000, -100000, 2**40, -(2**40)]:
        enc = zigzag_encode(n)
        dec, pos = zigzag_decode(enc, 0)
        assert dec == n and pos == len(enc)


def _sample_record(schema, label="false"):
    rec = {}
    for i, f in enumerate(schema.fields):
        if schema.label_field and f.name == schema.label_field:
            rec[f.name] = label
        elif f.avro_type in ("int", "long"):
            rec[f.name] = 20 + i
        else:
            rec[f.name] = float(i) + 0.5
    return rec


@pytest.mark.parametrize("schema", [CAR_SCHEMA, KSQL_CAR_SCHEMA],
                         ids=["producer", "ksql"])
def test_roundtrip(schema):
    codec = AvroCodec(schema)
    rec = _sample_record(schema)
    out = codec.decode(codec.encode(rec))
    for f in schema.fields:
        if f.avro_type == "float":
            assert out[f.name] == pytest.approx(rec[f.name], rel=1e-6)
        else:
            assert out[f.name] == rec[f.name]


def test_nulls_roundtrip():
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    rec = {f.name: None for f in KSQL_CAR_SCHEMA.fields}
    assert codec.decode(codec.encode(rec)) == rec


def test_avro_interop_with_fastavro_if_present():
    """Cross-check our wire bytes against an independent Avro implementation."""
    fastavro = pytest.importorskip("fastavro")
    import io, json  # noqa: E401

    codec = AvroCodec(KSQL_CAR_SCHEMA)
    rec = _sample_record(KSQL_CAR_SCHEMA)
    parsed = fastavro.parse_schema(json.loads(KSQL_CAR_SCHEMA.avro_json()))
    buf = io.BytesIO()
    fastavro.schemaless_writer(buf, parsed, rec)
    theirs = buf.getvalue()
    assert codec.encode(rec) == theirs
    assert codec.decode(theirs) == rec


def test_framing():
    payload = b"\x01\x02\x03"
    framed = frame(payload, schema_id=7)
    assert len(framed) == 8
    sid, body = unframe(framed)
    assert sid == 7 and body == payload
    assert strip_frame(framed) == payload
    with pytest.raises(ValueError):
        unframe(b"\x01" + b"\x00" * 7)


def test_decode_batch_columnar():
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    msgs = [codec.encode(_sample_record(KSQL_CAR_SCHEMA, label=l))
            for l in ("false", "true", "")]
    cols = codec.decode_batch(msgs)
    assert cols["FAILURE_OCCURRED"].tolist() == ["false", "true", ""]
    assert cols["SPEED"].dtype == np.float64
    assert cols["SPEED"].shape == (3,)
    mat = codec.sensor_matrix(cols)
    assert mat.shape == (3, 18)
    # column order is schema order
    assert mat[0, 0] == cols["COOLANT_TEMP"][0]


# ---------------------------------------------------- schema evolution
def test_v2_writer_resolves_against_v1_reader():
    """Writer-schema v2 (REGION added BEFORE the label — the KSQL
    regeneration shape) must resolve by NAME against the v1 reader;
    the positional decode this replaces reads REGION's bytes as the
    label."""
    from iotml.core.schema import (CAR_SCHEMA_V2_ID,
                                  KSQL_CAR_SCHEMA_V2, WRITER_SCHEMAS)
    from iotml.ops.avro import (ResolvingCodec, needs_resolution,
                                resolve_record)

    assert WRITER_SCHEMAS[1] is KSQL_CAR_SCHEMA
    assert WRITER_SCHEMAS[CAR_SCHEMA_V2_ID] is KSQL_CAR_SCHEMA_V2
    # v2 = v1 + REGION, label still last, REGION excluded from sensors
    assert KSQL_CAR_SCHEMA_V2.num_sensors == KSQL_CAR_SCHEMA.num_sensors
    assert KSQL_CAR_SCHEMA_V2.field_names[-2:] == ("REGION",
                                                   "FAILURE_OCCURRED")

    rec = _sample_record(KSQL_CAR_SCHEMA_V2, label="true")
    rec["REGION"] = "region-3"
    v2 = AvroCodec(KSQL_CAR_SCHEMA_V2)
    framed = frame(v2.encode(rec), CAR_SCHEMA_V2_ID)
    assert needs_resolution(framed)
    assert not needs_resolution(frame(b"x", 1))
    assert not needs_resolution(frame(b"x", 99))   # unknown id: legacy
    assert not needs_resolution(b"\x01\x00\x00\x00\x02rest")  # bad magic

    # positional v1 decode mis-reads: the label comes back as REGION
    positional = AvroCodec(KSQL_CAR_SCHEMA).decode(framed[5:])
    assert positional["FAILURE_OCCURRED"] == "region-3"
    # the resolving decode projects by name: label correct, REGION gone
    resolved = ResolvingCodec(KSQL_CAR_SCHEMA).decode_framed(framed)
    assert resolved["FAILURE_OCCURRED"] == "true"
    assert "REGION" not in resolved
    assert resolved["SPEED"] == rec["SPEED"]

    # a v1 record read through a v2 reader takes the null default
    v1_framed = frame(AvroCodec(KSQL_CAR_SCHEMA).encode(
        _sample_record(KSQL_CAR_SCHEMA)), 1)
    up = ResolvingCodec(KSQL_CAR_SCHEMA_V2).decode_framed(v1_framed)
    assert up["REGION"] is None

    # incompatible evolution fails loudly: required reader field the
    # writer never had
    from iotml.core.schema import Field, RecordSchema

    strict = RecordSchema("R", "ns", (Field("MISSING", "double"),))
    with pytest.raises(ValueError):
        resolve_record({"SPEED": 1.0}, strict)


def test_resolving_codec_batch_and_unknown_id():
    from iotml.core.schema import CAR_SCHEMA_V2_ID, KSQL_CAR_SCHEMA_V2
    from iotml.ops.avro import ResolvingCodec

    v1 = AvroCodec(KSQL_CAR_SCHEMA)
    v2 = AvroCodec(KSQL_CAR_SCHEMA_V2)
    msgs = []
    for i in range(6):
        rec = _sample_record(KSQL_CAR_SCHEMA, label="false")
        if i % 2:
            rec = dict(rec, REGION=f"region-{i}")
            msgs.append(frame(v2.encode(rec), CAR_SCHEMA_V2_ID))
        else:
            msgs.append(frame(v1.encode(rec), 1))
    rc = ResolvingCodec(KSQL_CAR_SCHEMA)
    cols = rc.decode_batch_framed(msgs)
    assert cols["SPEED"].shape == (6,)
    assert set(cols["FAILURE_OCCURRED"].tolist()) == {"false"}
    with pytest.raises(ValueError):
        rc.decode_framed(frame(b"junk", 42))
