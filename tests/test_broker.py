"""Broker emulator, consumer cursors, ordered producer."""

import pytest

from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer, parse_spec
from iotml.stream.producer import OutputSequence


def test_produce_fetch_offsets():
    b = Broker()
    b.create_topic("t", partitions=2)
    offs = [b.produce("t", f"m{i}".encode(), partition=0) for i in range(5)]
    assert offs == [0, 1, 2, 3, 4]
    msgs = b.fetch("t", 0, 0)
    assert [m.value for m in msgs] == [f"m{i}".encode() for i in range(5)]
    assert b.end_offset("t", 0) == 5
    assert b.end_offset("t", 1) == 0
    assert b.fetch("t", 0, 3)[0].offset == 3


def test_keyed_partitioning_is_stable():
    b = Broker()
    b.create_topic("t", partitions=10)
    for _ in range(3):
        b.produce("t", b"v", key=b"car42")
    # all three copies on the same partition
    parts = [p for p in range(10) if b.end_offset("t", p) > 0]
    assert len(parts) == 1
    assert b.end_offset("t", parts[0]) == 3


def test_retention_trims_and_offsets_stay_absolute():
    from iotml.stream.broker import OffsetOutOfRangeError

    b = Broker()
    b.create_topic("t", retention_messages=10)
    for i in range(25):
        b.produce("t", str(i).encode(), partition=0)
    assert b.begin_offset("t", 0) == 15
    assert b.end_offset("t", 0) == 25
    # a fetch below the retained base is an explicit signal, not a
    # silent clamp (trimmed history must be distinguishable from
    # delivered history); the error names the earliest retained offset
    with pytest.raises(OffsetOutOfRangeError) as ei:
        b.fetch("t", 0, 0)
    assert ei.value.earliest == 15
    assert b.fetch("t", 0, 15)[0].offset == 15


def test_retention_by_bytes_and_time():
    from iotml.stream.broker import OffsetOutOfRangeError

    b = Broker()
    b.create_topic("tb", retention_bytes=100)
    for i in range(30):
        b.produce("tb", b"x" * 10, partition=0)
    assert b.end_offset("tb", 0) == 30
    assert b.begin_offset("tb", 0) >= 19  # ~100 bytes of 10-byte records
    # time retention ages against the NEWEST record timestamp
    b.create_topic("tt", retention_ms=1000)
    for i in range(10):
        b.produce("tt", str(i).encode(), partition=0, timestamp_ms=1000 + i)
    assert b.begin_offset("tt", 0) == 0  # all within the window
    b.produce("tt", b"new", partition=0, timestamp_ms=5000)
    assert b.begin_offset("tt", 0) == 10  # 1000-era records aged out
    # negative knobs rejected like the count knob
    for kw in ({"retention_bytes": -1}, {"retention_ms": -5},
               {"retention_messages": -2}):
        with pytest.raises(ValueError):
            b.create_topic("bad", **kw)
    # untimestamped (ts=0) streams never age out
    b.create_topic("t0", retention_ms=1)
    for i in range(5):
        b.produce("t0", str(i).encode(), partition=0)
    assert b.begin_offset("t0", 0) == 0
    with pytest.raises(OffsetOutOfRangeError):
        b.fetch("tt", 0, 3)


def test_consumer_auto_resets_to_earliest_after_trim():
    """The documented auto.offset.reset=earliest behavior: a cursor
    stranded below the retained base resumes at the earliest retained
    record instead of erroring forever or silently skipping."""
    b = Broker()
    b.create_topic("t", retention_messages=5)
    for i in range(3):
        b.produce("t", str(i).encode(), partition=0)
    c = StreamConsumer(b, ["t:0:0"], group="g", eof=False)
    assert [m.value for m in c.poll()] == [b"0", b"1", b"2"]
    c2 = StreamConsumer(b, ["t:0:0"], group="g2", eof=False)  # lags at 0
    for i in range(3, 20):
        b.produce("t", str(i).encode(), partition=0)
    assert b.begin_offset("t", 0) == 15
    msgs = c2.poll()
    assert [m.offset for m in msgs] == [15, 16, 17, 18, 19]
    from iotml.obs import metrics as obs_metrics

    assert obs_metrics.consumer_autoresets.value(topic="t") >= 1


def test_parse_spec():
    assert parse_spec("topic:3:500") == ("topic", 3, 500)
    assert parse_spec("topic:3") == ("topic", 3, 0)
    assert parse_spec("topic") == ("topic", 0, 0)


def test_consumer_eof_and_seek():
    b = Broker()
    b.create_topic("t")
    for i in range(7):
        b.produce("t", str(i).encode(), partition=0)
    c = StreamConsumer(b, ["t:0:2"])
    vals = [m.value for m in c]
    assert vals == [b"2", b"3", b"4", b"5", b"6"]
    assert c.at_end()
    c.seek_to_start()
    assert [m.value for m in c][0] == b"2"


def test_consumer_multi_partition_round_robin():
    b = Broker()
    b.create_topic("t", partitions=3)
    for p in range(3):
        for i in range(4):
            b.produce("t", f"p{p}m{i}".encode(), partition=p)
    c = StreamConsumer(b, [f"t:{p}:0" for p in range(3)])
    msgs = list(c)
    assert len(msgs) == 12
    assert {m.partition for m in msgs} == {0, 1, 2}


def test_consumer_commit_resume():
    b = Broker()
    b.create_topic("t")
    for i in range(10):
        b.produce("t", str(i).encode(), partition=0)
    c = StreamConsumer(b, ["t:0:0"], group="g")
    c.poll(4)
    c.commit()
    c2 = StreamConsumer.from_committed(b, "t", [0], group="g")
    assert c2.poll(1)[0].value == b"4"


def test_output_sequence_orders_and_detects_gaps():
    b = Broker()
    b.create_topic("out")
    seq = OutputSequence(b, "out", partition=0)
    seq.setitem(2, "two")
    seq.setitem(0, "zero")
    seq.setitem(1, "one")
    assert seq.flush() == 3
    assert [m.value for m in b.fetch("out", 0, 0)] == [b"zero", b"one", b"two"]

    seq.setitem(5, "five")
    seq.setitem(7, "seven")
    with pytest.raises(ValueError, match="gaps"):
        seq.flush()
    assert seq.flush(allow_gaps=True) == 2

    seq.setitem(9, "x")
    with pytest.raises(ValueError, match="duplicate"):
        seq.setitem(9, "again")


def test_produce_many_matches_per_message_produce():
    """Bulk append must land records on the same partitions (key hash) and
    apply the same retention trimming as produce()."""
    from iotml.stream.broker import Broker

    a, b = Broker(), Broker()
    for br in (a, b):
        br.create_topic("t", partitions=4, retention_messages=5)
    entries = [(f"k{i % 7}".encode(), f"v{i}".encode(), 9)
               for i in range(40)]
    last = -1
    for k, v, ts in entries:
        last = a.produce("t", v, key=k, timestamp_ms=ts)
    # same 3-tuple signature + last-offset return as the wire/native
    # clients' produce_many (the Broker duck-type contract)
    assert b.produce_many("t", entries) == last
    for p in range(4):
        assert a.end_offset("t", p) == b.end_offset("t", p)
        assert a.begin_offset("t", p) == b.begin_offset("t", p)
        ma = a.fetch("t", p, a.begin_offset("t", p), 100)
        mb = b.fetch("t", p, b.begin_offset("t", p), 100)
        assert [(m.key, m.value, m.timestamp_ms) for m in ma] == \
            [(m.key, m.value, m.timestamp_ms) for m in mb]


def test_engine_owned_topic_restriction():
    """restrict_topic: produces to the owned prefix require the owner's
    grant; reads, commits and other topics stay open (the invariant is
    write exclusivity, ADVICE.md round-5 trusted_passthrough hole)."""
    from iotml.stream.broker import Broker, TopicOwnershipError

    b = Broker()
    b.create_topic("SENSOR_DATA_S_AVRO", partitions=2)
    b.produce("SENSOR_DATA_S_AVRO", b"pre-restriction")  # open until marked
    token = b.restrict_topic("SENSOR_DATA_S_AVRO")
    with pytest.raises(TopicOwnershipError):
        b.produce("SENSOR_DATA_S_AVRO", b"external")
    with pytest.raises(TopicOwnershipError):
        b.produce_many("SENSOR_DATA_S_AVRO_REKEY",  # prefix match
                       [(None, b"external", 0)])
    with pytest.raises(TopicOwnershipError):
        b.produce_batch("SENSOR_DATA_S_AVRO", [b"x"])
    # nothing landed
    assert b.end_offset("SENSOR_DATA_S_AVRO", 0) + \
        b.end_offset("SENSOR_DATA_S_AVRO", 1) == 1
    # the owner produces under its grant; other topics need none
    with b.producer_grant(token):
        b.produce("SENSOR_DATA_S_AVRO", b"engine")
    b.produce("sensor-data", b"anyone")
    # grant is thread-local: it does not leak to other threads
    errs = []

    def other_thread():
        try:
            b.produce("SENSOR_DATA_S_AVRO", b"sneak")
        except TopicOwnershipError:
            errs.append("rejected")

    import threading

    with b.producer_grant(token):
        t = threading.Thread(target=other_thread)
        t.start(); t.join(5)
    assert errs == ["rejected"]
    # reads and commits unaffected
    assert b.committed("g", "SENSOR_DATA_S_AVRO", 0) is None
    b.commit("g", "SENSOR_DATA_S_AVRO", 0, 1)
    assert b.committed("g", "SENSOR_DATA_S_AVRO", 0) == 1


def test_sql_engine_pumps_under_owner_grant():
    """The platform wiring end to end: a restricted broker + an engine
    holding the owner token — the reference pipeline's AVRO leg still
    flows, while a direct external produce is rejected."""
    import json

    import pytest as _pytest

    from iotml.core.schema import KSQL_CAR_SCHEMA
    from iotml.stream.broker import Broker, TopicOwnershipError
    from iotml.streamproc import SqlEngine
    from iotml.streamproc.sql import install_reference_pipeline

    b = Broker()
    b.create_topic("sensor-data", partitions=2)
    token = b.restrict_topic("SENSOR_DATA_S_AVRO")
    engine = SqlEngine(b, trusted_passthrough=True, owner_token=token)
    install_reference_pipeline(engine)
    rec = {f.name: ("false" if f.name == "FAILURE_OCCURRED" else
                    "car1" if f.avro_type == "string" else 1)
           for f in KSQL_CAR_SCHEMA.fields}
    b.produce("sensor-data", json.dumps(rec).encode(), key=b"car1")
    assert engine.pump() > 0
    assert b.end_offset("SENSOR_DATA_S_AVRO", 0) + \
        b.end_offset("SENSOR_DATA_S_AVRO", 1) == 1
    with _pytest.raises(TopicOwnershipError):
        b.produce("SENSOR_DATA_S_AVRO", b"external")
