"""Per-car failure detection: key plumbing + EMA detector + alert feed.

The predictive-maintenance deliverable (reference README.md:7,19): a
failing CAR is flagged by name, not just anomalous rows.  Per-record
detection is noise-limited (AUC ~0.8-0.9 measured); per-car aggregation
separates near-totally because failures persist per car.
"""

import json

import numpy as np
import pytest

from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.models.autoencoder import CAR_AUTOENCODER
from iotml.serve.carhealth import CarHealthDetector
from iotml.serve.scorer import StreamScorer
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.stream.producer import OutputSequence
from iotml.train.loop import Trainer


# ------------------------------------------------------------- detector
def test_detector_alerts_after_min_records_and_clears_with_hysteresis():
    d = CarHealthDetector(threshold=0.5, alpha=0.5, min_records=10)
    bad, good = b"car-bad", b"car-good"
    # below min_records: no alert no matter how high the error
    out = d.update(np.array([bad] * 5, "S16"), np.full(5, 9.0))
    assert out == [] and d.alerted == {}
    out = d.update(np.array([bad] * 5 + [good] * 20, "S16"),
                   np.concatenate([np.full(5, 9.0), np.full(20, 0.1)]))
    assert [(k, s) for _, k, s, *_ in out] == [(bad, "ALERT")]
    assert bad in d.alerted and good not in d.alerted
    # recovery: EMA must fall below threshold*clear_ratio, not just the
    # threshold (hysteresis)
    out = d.update(np.array([bad], "S16"), np.array([0.45]))
    assert out == []  # 0.45 > 0.35 = 0.5*0.7 — still alerted
    cleared = []
    for _ in range(8):
        cleared += d.update(np.array([bad], "S16"), np.array([0.0]))
    assert [(k, s) for _, k, s, *_ in cleared] == [(bad, "CLEAR")]
    assert d.alerted == {}
    assert [s for _, _, s, *_ in d.transitions] == ["ALERT", "CLEAR"]


def test_detector_ignores_keyless_rows_and_groups_vectorized():
    d = CarHealthDetector(threshold=0.5, alpha=1.0, min_records=1)
    keys = np.array([b"", b"a", b"b", b"a", b""], "S8")
    errs = np.array([9.0, 0.9, 0.1, 0.8, 9.0])
    out = d.update(keys, errs)
    assert sorted(k for _, k, s, *_ in out) == [b"a"]
    assert b"" not in d.ema
    # alpha=1.0 → EMA == last value per car, folded in order
    assert d.ema[b"a"] == pytest.approx(0.8)
    assert d.ema[b"b"] == pytest.approx(0.1)


def test_published_transition_carries_recorded_timestamp():
    """The alert record's `t` is the transition's own timestamp — the
    same value recorded in detector.transitions, never re-stamped at
    publish time (an operator correlating the twin feed against the
    detector's history must see one time, not two)."""
    d = CarHealthDetector(threshold=0.5, alpha=1.0, min_records=1)
    out = d.update(np.array([b"car-x"], "S16"), np.array([9.0]))
    assert len(out) == 1 and out[0] == d.transitions[0]

    class _Rec:
        def __init__(self):
            self.msgs = []

        def produce(self, topic, value, key=None):
            self.msgs.append((topic, value, key))

    rec = _Rec()
    d.publish_transitions(rec, "car-health", out)
    payload = json.loads(rec.msgs[0][1])
    assert payload["t"] == d.transitions[0][0]


# ----------------------------------------------- end-to-end with a model
def _trained_scorer_with_carhealth(broker, topic, partitions, det):
    c = StreamConsumer(broker, [f"{topic}:{p}:0" for p in range(partitions)],
                       group="train-ch")
    trainer = Trainer(CAR_AUTOENCODER)
    trainer.fit_compiled(SensorBatches(c, batch_size=100, only_normal=True),
                         epochs=10)
    broker.create_topic("preds", partitions=1)
    broker.create_topic("car-health", partitions=1)
    c2 = StreamConsumer(broker, [f"{topic}:{p}:0" for p in range(partitions)],
                        group="score-ch")
    return StreamScorer(
        CAR_AUTOENCODER, trainer.state.params,
        SensorBatches(c2, batch_size=100, keep_labels=True, keep_keys=True),
        OutputSequence(broker, "preds", partition=0),
        threshold=0.4, carhealth=det, carhealth_topic="car-health")


def _strong_failing(scenario, gen):
    """Cars whose injected mode is inside the detection envelope (mode 1,
    tire blowout — see serve/carhealth.py's measured envelope)."""
    return {scenario.car_id(i).encode()
            for i, m in enumerate(gen.failing) if m == 1}


def test_strong_faults_alerted_by_name_no_false_alerts():
    """Inject labeled failure modes; the detector must alert EVERY
    strong-mode car by name with ZERO false alerts (precision 1.0 — the
    operator-paging contract), and publish keyed ALERT records to the
    twin feed.  Subtle modes sitting inside the healthy EMA band are the
    documented envelope, not a regression."""
    broker = Broker()
    scenario = FleetScenario(num_cars=120, failure_rate=0.05, seed=3)
    gen = FleetGenerator(scenario)
    failing = {scenario.car_id(i).encode()
               for i, m in enumerate(gen.failing) if m >= 0}
    strong = _strong_failing(scenario, gen)
    assert strong  # seed 3 must inject at least one strong-mode car
    gen.publish(broker, "S", n_ticks=60, partitions=2)  # 7200 records

    det = CarHealthDetector()  # defaults: th 0.38, alpha 0.05, min 20
    scorer = _trained_scorer_with_carhealth(broker, "S", 2, det)
    n = scorer.score_available()
    assert n == 7200

    alerted = set(det.alerted)
    assert strong <= alerted, (sorted(alerted), sorted(strong))
    assert alerted <= failing, \
        ("false alerts", sorted(alerted - failing))
    # healthy cars sit below the threshold band
    healthy_emas = [e for k, e in det.ema.items() if k not in failing]
    assert max(healthy_emas) < det.threshold
    # the twin feed carries keyed JSON ALERT records for the alerted cars
    msgs = broker.fetch("car-health", 0, 0, 1000)
    recs = [json.loads(m.value) for m in msgs]
    assert {r["car"].encode() for r in recs if r["state"] == "ALERT"} \
        == alerted
    assert all(m.key in failing for m in msgs)


def test_carhealth_keys_survive_the_wire_fused_path():
    """Same detection through the TCP wire + C++ fused fetch_decode_keys:
    the key plumbing the fused path adds must agree with the in-process
    path's Message.key."""
    from iotml.stream import native
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.native_kafka import NativeKafkaBroker

    if native.load() is None:
        pytest.skip("native engine not built")
    broker = Broker()
    scenario = FleetScenario(num_cars=120, failure_rate=0.05, seed=3)
    gen = FleetGenerator(scenario)
    failing = {scenario.car_id(i).encode()
               for i, m in enumerate(gen.failing) if m >= 0}
    strong = _strong_failing(scenario, gen)
    gen.publish(broker, "S", n_ticks=60, partitions=2)

    det = CarHealthDetector()
    with KafkaWireServer(broker) as srv:
        client = NativeKafkaBroker(f"127.0.0.1:{srv.port}")
        try:
            scorer = _trained_scorer_with_carhealth(broker, "S", 2, det)
            # swap the scorer's input to the wire client (fused keys path)
            wire_c = StreamConsumer(client, [f"S:{p}:0" for p in range(2)],
                                    group="score-wire")
            scorer.batches = SensorBatches(wire_c, batch_size=100,
                                           keep_labels=True, keep_keys=True)
            scorer.scored = 0
            scorer.score_available()
            assert strong <= set(det.alerted) <= failing
        finally:
            client.close()


def test_failure_onset_labels_flip_mid_stream():
    """failure_onset_ticks: a failing car's records are labeled (and
    perturbed) only once its onset tick passes — the realistic
    predictive-maintenance stream shape."""
    scenario = FleetScenario(num_cars=40, failure_rate=0.2, seed=5,
                             failure_onset_ticks=(10, 10))
    gen = FleetGenerator(scenario)
    failing_idx = [i for i, m in enumerate(gen.failing) if m >= 0]
    assert failing_idx
    labels_by_tick = []
    for _ in range(20):
        cols = gen.step_columns()
        labels_by_tick.append(cols["failure_occurred"].copy())
    pre = np.stack(labels_by_tick[:10])
    post = np.stack(labels_by_tick[10:])
    assert np.all(pre == "false")
    for i in failing_idx:
        assert np.all(post[:, i] == "true")
    healthy = [i for i in range(40) if i not in failing_idx]
    assert np.all(post[:, healthy] == "false")


# --------------------------------------------------- per-feature heads
def test_feature_heads_catch_single_feature_outlier_no_false_alerts():
    """A car whose MEAN error sits inside the healthy band but whose ONE
    feature's error is a fleet outlier must alert via the feature head,
    with the firing feature named; healthy cars must never alert (the
    z-floor gates numerical-dust MADs)."""
    rng = np.random.default_rng(0)
    F = 6
    d = CarHealthDetector(threshold=5.0, alpha=0.2, min_records=10,
                          feature_heads=True, feature_z=8.0,
                          feature_floor=0.05,
                          feature_names=[f"f{j}" for j in range(F)])
    cars = [f"car-{i:03d}".encode() for i in range(30)]
    bad = cars[7]
    for _ in range(20):
        keys = np.repeat(np.array(cars, "S16"), 3)
        ferrs = rng.uniform(0.01, 0.03, (len(keys), F))
        # the bad car's feature 4 is elevated far beyond the fleet MAD,
        # but its MEAN error stays ~ (0.02*5 + 0.5)/6 ≈ 0.1 — far below
        # the 5.0 mean threshold, invisible to the MSE path
        bad_rows = keys == bad
        ferrs[bad_rows, 4] = rng.uniform(0.45, 0.55, bad_rows.sum())
        errs = ferrs.mean(axis=1)
        d.update(keys, errs, ferrs=ferrs)
    assert set(d.alerted) == {bad}
    assert d.alert_source[bad].startswith("feature:f4")
    # transitions carry the source; publishing includes it
    assert any(src.startswith("feature:f4")
               for *_, src in d.transitions)


def test_feature_heads_survive_fleetwide_error_shift():
    """Cross-sectional robustness: a model hot-swap shifts EVERY car's
    per-feature error together — the fleet median/MAD absorb it and no
    car alerts (the failure mode absolute per-feature thresholds died
    of, measured round 4)."""
    rng = np.random.default_rng(1)
    F = 4
    d = CarHealthDetector(threshold=5.0, alpha=0.3, min_records=5,
                          feature_heads=True, feature_z=8.0)
    cars = [f"car-{i:03d}".encode() for i in range(25)]
    for scale in (1.0, 4.0):  # epoch 2 = post-swap: 4x error everywhere
        for _ in range(15):
            keys = np.array(cars, "S16")
            ferrs = rng.uniform(0.01, 0.03, (len(keys), F)) * scale
            d.update(keys, ferrs.mean(axis=1), ferrs=ferrs)
    assert d.alerted == {}


def test_all_three_failure_modes_detected_with_full_normalization():
    """Every injected failure mode per car, end to end, zero false
    alerts.  Battery sag (mode 2) moves the 18-feature mean MSE by ~2%
    under PARITY normalization because its entire signature (voltage
    sag + current spike) lives in two fields the reference's TODO
    normalization zeroes — under FULL normalization the ERROR head
    names BATTERY_VOLTAGE at z≈700.  Engine vibration (mode 0) is
    invisible to the error head (the feature is inherently
    unpredictable, healthy error spread ≈ the fault's excess) — the
    model-free DRIFT head names it.  Tire blowout (mode 1) is caught by
    either.  See serve/carhealth.py's measured envelope."""
    from iotml.core.normalize import FULL_NORMALIZER
    from iotml.core.schema import KSQL_CAR_SCHEMA

    broker = Broker()
    scenario = FleetScenario(num_cars=120, failure_rate=0.0, seed=9)
    gen = FleetGenerator(scenario)
    gen.failing[:] = -1
    gen.failing[17] = 2   # battery fault (the weak mode)
    gen.failing[40] = 0   # engine vibration
    gen.failing[77] = 1   # tire blowout
    sag_car = scenario.car_id(17).encode()
    vib_car = scenario.car_id(40).encode()
    tire_car = scenario.car_id(77).encode()
    gen.publish(broker, "S", n_ticks=60, partitions=2)

    feat_names = [f.name for f in KSQL_CAR_SCHEMA.sensor_fields]
    # threshold 0.6: the full-normalization healthy mean-EMA band tops
    # out ~0.42 offline (module docstring envelope) — detection must
    # come from the per-feature heads, not a mistuned mean threshold
    det = CarHealthDetector(threshold=0.6, feature_heads=True,
                            feature_names=feat_names)
    c = StreamConsumer(broker, [f"S:{p}:0" for p in range(2)],
                       group="train-sag")
    trainer = Trainer(CAR_AUTOENCODER)
    trainer.fit_compiled(
        SensorBatches(c, batch_size=100, only_normal=True,
                      normalizer=FULL_NORMALIZER), epochs=10)
    broker.create_topic("preds", partitions=1)
    broker.create_topic("car-health", partitions=1)
    c2 = StreamConsumer(broker, [f"S:{p}:0" for p in range(2)],
                        group="score-sag")
    scorer = StreamScorer(
        CAR_AUTOENCODER, trainer.state.params,
        SensorBatches(c2, batch_size=100, keep_labels=True, keep_keys=True,
                      normalizer=FULL_NORMALIZER),
        OutputSequence(broker, "preds", partition=0),
        threshold=0.4, carhealth=det, carhealth_topic="car-health")
    scorer.score_available()
    assert set(det.alerted) == {sag_car, vib_car, tire_car}, det.summary()
    # the firing head names the physically right feature
    assert det.alert_source[sag_car].startswith("feature:BATTERY_VOLTAGE")
    assert det.alert_source[vib_car].startswith(
        "drift:ENGINE_VIBRATION_AMPLITUDE")
    assert "TIRE_PRESSURE" in det.alert_source[tire_car]
    # the twin feed records carry the firing source
    recs = [json.loads(m.value)
            for m in broker.fetch("car-health", 0, 0, 1000)]
    assert {r["car"].encode(): r["source"] for r in recs
            if r["state"] == "ALERT"}[sag_car].startswith(
        "feature:BATTERY_VOLTAGE")


def test_tail_guard_absorbs_heavy_tailed_feature_no_false_alerts():
    """The live failure mode of pure MAD-z scoring: a feature whose
    healthy per-car error spread is structurally heavy-tailed (battery %
    under continuous training: edge-of-distribution cars reconstruct
    persistently worse, z up to 235 on a MAD scale).  The tail guard —
    the alert bar also clears tail_k x the fleet's own p90 excess — must
    absorb it, while a genuinely out-of-family car still fires."""
    rng = np.random.default_rng(3)
    F = 5
    d = CarHealthDetector(threshold=5.0, alpha=0.3, min_records=5,
                          feature_heads=True, feature_z=30.0,
                          feature_tail_k=4.0)
    cars = [f"car-{i:03d}".encode() for i in range(40)]
    bad = cars[11]
    # feature 2 is heavy-tailed across healthy cars: per-car persistent
    # level drawn from a lognormal-ish spread (MAD small, tail wide)
    levels = np.concatenate([rng.uniform(0.01, 0.03, 30),
                             rng.uniform(0.2, 0.9, 10)])
    rng.shuffle(levels)
    for _ in range(20):
        keys = np.array(cars, "S16")
        ferrs = rng.uniform(0.01, 0.03, (len(cars), F))
        ferrs[:, 2] = levels * rng.uniform(0.9, 1.1, len(cars))
        # the bad car is out of family on feature 0 (tight healthy MAD)
        ferrs[11, 0] = 0.6
        d.update(keys, ferrs.mean(axis=1), ferrs=ferrs)
    assert set(d.alerted) == {bad}, d.summary()
    assert d.alert_source[bad].startswith("feature:0")


def test_head_alerted_car_clears_despite_elevated_mean_ema():
    """A car alerted via a feature head whose healthy mean-error EMA sits
    between threshold*clear_ratio and threshold must still CLEAR once the
    head goes quiet — the mse hysteresis bar belongs to the mse path
    only (requiring it unconditionally left such cars in ALERT forever)."""
    rng = np.random.default_rng(5)
    F = 10
    d = CarHealthDetector(threshold=0.5, alpha=0.5, min_records=5,
                          feature_heads=True, feature_z=8.0,
                          feature_floor=0.05, feature_tail_k=4.0)
    cars = [f"car-{i:03d}".encode() for i in range(30)]
    bad = cars[3]
    # healthy mean errors ~0.4: above clear bar 0.35, below threshold
    # 0.5; the fault feature keeps the MEAN under 0.5 so only the
    # feature head can fire
    def batch(fault):
        keys = np.array(cars, "S16")
        ferrs = rng.uniform(0.35, 0.45, (len(cars), F))
        if fault:
            ferrs[3, 1] = 0.9
        return keys, ferrs.mean(axis=1), ferrs
    for _ in range(15):
        d.update(*batch(fault=True)[:2], ferrs=batch(fault=True)[2])
    # re-drive deterministically: fault on until alerted
    tries = 0
    while bad not in d.alerted and tries < 30:
        k, e, f = batch(fault=True)
        d.update(k, e, ferrs=f)
        tries += 1
    assert bad in d.alerted and d.alert_source[bad].startswith("feature:")
    cleared = []
    for _ in range(40):
        k, e, f = batch(fault=False)
        cleared += [t for t in d.update(k, e, ferrs=f)
                    if t[2] == "CLEAR" and t[1] == bad]
        if cleared:
            break
    assert cleared, (d.alerted, d.alert_source)


def test_swap_notification_recalibrates_through_the_fold_transient():
    """The swap contract: notify_model_swap() opens a hot window that
    both recalibrates per-update AND suppresses new head alerts through
    the EMA fold transient — within one update the calibration is
    computed before the folds while z evaluates after them, so a large
    swap makes every freshly-folded car an apparent outlier against the
    pre-fold median.  A 4x fleetwide error shift landing mid-cadence,
    ABOVE the excess floor, must not page when the swap is notified."""
    rng = np.random.default_rng(7)
    F = 4
    d = CarHealthDetector(threshold=99.0, alpha=0.3, min_records=5,
                          feature_heads=True, feature_z=8.0,
                          feature_floor=0.01, feature_tail_k=4.0,
                          drift_z=1e9)
    cars = [f"car-{i:03d}".encode() for i in range(25)]

    def drive(n, scale):
        for _ in range(n):
            keys = np.array(cars, "S16")
            ferrs = rng.uniform(0.2, 0.3, (len(cars), F)) * scale
            d.update(keys, ferrs.mean(axis=1), ferrs=ferrs)

    drive(14, 1.0)   # 14 updates: the shift lands OFF the 4-cadence
    assert d._updates % d.RECAL_EVERY != 0
    d.notify_model_swap()
    assert d._recal_hot > 0
    drive(14, 4.0)   # post-swap: 4x errors everywhere, floor exceeded
    assert d.alerted == {}, d.summary()


def test_scorer_set_params_notifies_the_detector():
    """StreamScorer.set_params is the one production swap path — it must
    open the detector's recalibration hot window."""
    broker = Broker()
    broker.create_topic("in")
    broker.create_topic("out")
    det = CarHealthDetector(feature_heads=True)
    scorer = StreamScorer(
        CAR_AUTOENCODER, None,
        SensorBatches(StreamConsumer(broker, ["in:0:0"], group="g"),
                      batch_size=10),
        OutputSequence(broker, "out", partition=0), carhealth=det)
    assert det._recal_hot == 0
    scorer.set_params({"w": 1})
    assert det._recal_hot > 0


def test_hot_window_neither_pages_nor_holds_clears():
    """Symmetric suppression: during the post-swap hot window,
    head-sourced state is frozen (no new head alerts, no head-evidence
    holds), and a recovered head-alerted car clears promptly once the
    window expires."""
    rng = np.random.default_rng(9)
    F = 6
    d = CarHealthDetector(threshold=99.0, alpha=0.3, min_records=5,
                          feature_heads=True, feature_z=8.0,
                          feature_floor=0.01, drift_z=1e9)
    cars = [f"car-{i:03d}".encode() for i in range(25)]
    bad = cars[4]

    def drive(n, fault):
        outs = []
        for _ in range(n):
            keys = np.array(cars, "S16")
            ferrs = rng.uniform(0.02, 0.03, (len(cars), F))
            if fault:
                ferrs[4, 2] = 0.9
            outs += d.update(keys, ferrs.mean(axis=1), ferrs=ferrs)
        return outs

    drive(20, fault=True)
    assert bad in d.alerted and d.alert_source[bad].startswith("feature:")
    # the fault subsides; a swap lands — the hot window must not CLEAR
    # the car off frozen head state nor page anyone new
    d.notify_model_swap()
    hot_out = drive(3, fault=False)
    assert hot_out == [] and bad in d.alerted
    # window expires (alpha 0.3 → ~6 hot updates), heads quiet → clear
    cleared = drive(30, fault=False)
    assert any(k == bad and s == "CLEAR" for _, k, s, *_ in cleared)
    assert d.alerted == {}
