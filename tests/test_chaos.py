"""iotml.chaos: deterministic schedules, the disarmed no-op contract,
the injection engine's window/action/ledger semantics, reconnect
backoff (the rewind loops chaos blackouts exercise), and one
end-to-end invariant-checked run per built-in scenario — including the
seeded loss-bug fixture the checker must FAIL on."""

import subprocess
import sys
import threading
import time
import random

import pytest

from iotml.chaos import faults
from iotml.chaos.faults import Action, ChaosEngine
from iotml.chaos.scenarios import SCENARIOS, FaultEvent, build
from iotml.config import load_config
from iotml.utils.backoff import ExpBackoff


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with chaos disarmed (module global)."""
    faults.disarm()
    yield
    faults.disarm()


# ------------------------------------------------------------- schedules
def test_schedules_are_deterministic_and_seed_sensitive():
    for name in SCENARIOS:
        a = build(name, seed=11, records=500)
        b = build(name, seed=11, records=500)
        assert a.text() == b.text(), name  # byte-identical replay
        assert a.events, name
    # and the seed actually matters where the builder draws randomness
    assert build("mqtt-flap", seed=1, records=500).text() != \
        build("mqtt-flap", seed=2, records=500).text()


def test_schedule_cli_byte_identical():
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, "-m", "iotml.chaos", "schedule",
           "--scenario", "mqtt-flap", "--seed", "7", "--records", "400"]
    a = subprocess.run(cmd, capture_output=True, cwd=repo)
    b = subprocess.run(cmd, capture_output=True, cwd=repo)
    assert a.returncode == b.returncode == 0, a.stderr
    assert a.stdout == b.stdout
    assert b"mqtt.deliver" in a.stdout


def test_build_rejects_unknowns():
    with pytest.raises(KeyError):
        build("no-such-scenario", seed=1, records=100)
    with pytest.raises(ValueError):
        build("mqtt-flap", seed=1, records=3)  # below one fleet tick


def test_engine_rejects_unknown_faultpoint():
    with pytest.raises(ValueError, match="unknown faultpoint"):
        ChaosEngine([FaultEvent(1, "nope.nope", "drop")])


def test_engine_rejects_typoed_action_and_exception():
    """A typo'd action/exception must fail at build time — it would
    otherwise count as injected while doing nothing (a lying report)."""
    with pytest.raises(ValueError, match="does not interpret"):
        ChaosEngine([FaultEvent(1, "mqtt.deliver", "drip")])
    with pytest.raises(ValueError, match="does not interpret"):
        ChaosEngine([FaultEvent(1, "broker.fetch", "drop")])
    with pytest.raises(ValueError, match="unknown exception"):
        ChaosEngine([FaultEvent(1, "broker.fetch", "error",
                                params=(("exc", "ValurError"),))])


def test_engine_rejects_overlapping_site_actions():
    """A call site consumes ONE action per hit, so two non-delay events
    covering the same hit could not both execute — rejected at build
    (delays compose with anything and stay legal)."""
    with pytest.raises(ValueError, match="overlapping non-delay"):
        ChaosEngine([FaultEvent(5, "mqtt.deliver", "drop"),
                     FaultEvent(5, "mqtt.deliver", "dup")])
    with pytest.raises(ValueError, match="overlapping non-delay"):
        ChaosEngine([FaultEvent(3, "broker.fetch", "error", repeat=4),
                     FaultEvent(5, "broker.fetch", "error")])
    # delay + drop on the same hit is the legal composition
    ChaosEngine([FaultEvent(5, "mqtt.deliver", "delay", repeat=10),
                 FaultEvent(7, "mqtt.deliver", "drop")])


# ------------------------------------------------------ disarmed contract
def test_disarmed_point_is_noop():
    """The tier-1 contract: shims in place, chaos unset -> nothing
    happens.  (The rest of the suite runs the whole pipeline through
    these shims disarmed, which is the behavior-unchanged proof.)"""
    assert faults.engine() is None
    before = faults.chaos_injected.value(fault="broker.fetch:error")
    for name in faults.KNOWN_POINTS:
        assert faults.point(name) is None
    assert faults.engine() is None
    assert faults.chaos_injected.value(fault="broker.fetch:error") == before


def test_arm_from_env_gates_on_toggle():
    # only an explicit opt-in arms: every disable spelling the other
    # IOTML_ toggles accept must NOT arm chaos with a default scenario
    for off in ("", "0", "false", "no", "off", "False"):
        assert faults.arm_from_env({"IOTML_CHAOS": off}) is None, off
    eng = faults.arm_from_env({"IOTML_CHAOS": "1",
                               "IOTML_CHAOS_SCENARIO": "dup-storm",
                               "IOTML_CHAOS_SEED": "3"})
    assert eng is not None and faults.engine() is eng


def test_chaos_toggles_never_leak_into_config_tree():
    """IOTML_CHAOS* are process toggles in config's non_config set: the
    resolver must neither reject them (typo'd IOTML_ vars fail loudly
    by design) nor apply them anywhere in the config tree."""
    cfg, _ = load_config(argv=[], env={
        "IOTML_CHAOS": "1", "IOTML_CHAOS_SEED": "9",
        "IOTML_CHAOS_SCENARIO": "mqtt-flap",
        "IOTML_CHAOS_RECORDS": "500"})
    clean, _ = load_config(argv=[], env={})
    assert cfg.as_dict() == clean.as_dict()
    assert cfg.applied == set()


# ------------------------------------------------------------ the engine
def test_engine_windows_actions_and_ledger(monkeypatch):
    slept = []
    monkeypatch.setattr("iotml.chaos.faults.time.sleep", slept.append)
    eng = faults.arm(ChaosEngine([
        FaultEvent(2, "broker.fetch", "delay",
                   params=(("seconds", 0.5),), repeat=2),
        FaultEvent(5, "broker.fetch", "error",
                   params=(("exc", "OSError"),)),
        FaultEvent(1, "mqtt.deliver", "dup"),
        FaultEvent(2, "mqtt.deliver", "drop"),
        FaultEvent(3, "mqtt.deliver", "drop",
                   params=(("account", False),)),
    ]))
    assert faults.point("broker.fetch") is None          # hit 1: clean
    assert faults.point("broker.fetch") is None          # hit 2: delay
    assert faults.point("broker.fetch") is None          # hit 3: delay
    assert slept == [0.5, 0.5]
    assert faults.point("broker.fetch") is None          # hit 4: clean
    with pytest.raises(OSError):
        faults.point("broker.fetch")                     # hit 5: error
    assert faults.point("mqtt.deliver") == Action("dup", {})
    assert faults.point("mqtt.deliver") == \
        Action("drop", {})                               # accounted
    assert faults.point("mqtt.deliver") == \
        Action("drop", {"account": False})               # the seeded bug
    assert eng.dropped_count == 1  # only the accounted drop ledgered
    assert eng.injected == {"broker.fetch:delay": 2,
                            "broker.fetch:error": 1,
                            "mqtt.deliver:dup": 1,
                            "mqtt.deliver:drop": 2}


def test_engine_fires_every_overlapping_event(monkeypatch):
    """The schedule is ground truth: an event scheduled INSIDE another
    event's repeat-window must still fire (a drop inside a delay window
    both delays and drops), or the executed faults silently diverge
    from the canonical schedule text."""
    slept = []
    monkeypatch.setattr("iotml.chaos.faults.time.sleep", slept.append)
    eng = faults.arm(ChaosEngine([
        FaultEvent(11, "mqtt.deliver", "delay",
                   params=(("seconds", 0.25),), repeat=5),
        FaultEvent(12, "mqtt.deliver", "drop"),
    ]))
    actions = [faults.point("mqtt.deliver") for _ in range(16)]
    assert eng.injected == {"mqtt.deliver:delay": 5,
                            "mqtt.deliver:drop": 1}
    assert eng.dropped_count == 1
    assert slept == [0.25] * 5
    assert actions[11] == Action("drop", {})  # hit 12: delayed AND dropped
    assert [a for a in actions if a is not None] == [actions[11]]
    # replaying every hit of a full built schedule executes exactly the
    # events the canonical text lists (the review-found divergence case)
    eng = faults.arm(ChaosEngine(build("mqtt-flap", seed=2,
                                       records=100).events))
    for _ in range(100):
        faults.point("mqtt.deliver")
    assert eng.injected["mqtt.deliver:drop"] == 2  # both scheduled drops


def test_trainer_faultpoint_fires(tmp_path):
    """The trainer.poll shim is live: an armed delay fires once per
    run() iteration (the only faultpoint not driven by the runner)."""
    from iotml.stream.broker import Broker
    from iotml.train.artifacts import ArtifactStore
    from iotml.train.live import ContinuousTrainer

    broker = Broker()
    broker.create_topic("t", partitions=1)
    trainer = ContinuousTrainer(broker, "t",
                                ArtifactStore(str(tmp_path)),
                                take_batches=1)
    eng = faults.arm(ChaosEngine([
        FaultEvent(1, "trainer.poll", "delay",
                   params=(("seconds", 0.0),))]))
    calls = iter([False, True])
    trainer.run(stop=lambda: next(calls), poll_interval_s=0.0)
    assert eng.injected == {"trainer.poll:delay": 1}


# -------------------------------------------------------------- backoff
def test_expbackoff_envelope_and_reset():
    b = ExpBackoff(base_s=0.1, cap_s=2.0, factor=2.0,
                   rng=random.Random(0))
    delays = [b.next_delay() for _ in range(8)]
    raw = [min(2.0, 0.1 * 2 ** n) for n in range(8)]
    for d, r in zip(delays, raw):
        assert r / 2 <= d <= r  # jitter in [raw/2, raw]
    assert max(delays) <= 2.0
    assert b.attempt == 8
    b.reset()
    assert b.attempt == 0
    assert b.next_delay() <= 0.1
    with pytest.raises(ValueError):
        ExpBackoff(base_s=0.5, cap_s=0.1)
    with pytest.raises(ValueError):
        ExpBackoff(factor=1.0)


def test_scorer_rewind_loop_backs_off(monkeypatch):
    """run_forever's ConnectionError branch sleeps on the bounded
    exponential schedule, not the fixed poll interval (which a dead
    leader turned into a busy-spin)."""
    from iotml.serve.scorer import StreamScorer

    slept = []
    monkeypatch.setattr("iotml.serve.scorer.time.sleep", slept.append)

    class _Consumer:
        rewound = 0

        def rewind_to_committed(self):
            self.rewound += 1

    class _Batches:
        consumer = _Consumer()

    scorer = object.__new__(StreamScorer)
    scorer.batches = _Batches()

    def dead_leader(max_rows=None):
        raise ConnectionError("leader stays dead")

    scorer.score_available = dead_leader
    scorer.run_forever(poll_interval_s=0.01, max_rounds=6)
    assert scorer.batches.consumer.rewound == 6
    assert len(slept) == 6
    # poll_interval_s=0 (a legal busy-poll) must not crash the
    # failure-path backoff construction
    scorer.run_forever(poll_interval_s=0.0, max_rounds=2)
    assert scorer.batches.consumer.rewound == 8
    # envelope: starts at the poll interval, grows, never passes the cap
    assert slept[0] <= 0.01
    assert slept[5] >= min(2.0, 0.01 * 2 ** 5) / 2 > slept[0]
    assert max(slept) <= 2.0


def test_replica_reconnect_backs_off(monkeypatch):
    """A follower whose leader STAYS dead retries on the growing
    schedule (was: fixed interval*4 forever)."""
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.replica import FollowerReplica

    slept = []
    real_sleep = time.sleep  # the patch below hits the time module itself

    def fake_sleep(s):
        slept.append(s)
        real_sleep(0.001)

    monkeypatch.setattr("iotml.stream.replica.time.sleep", fake_sleep)
    broker = Broker()
    broker.create_topic("T", partitions=1)
    broker.produce("T", b"x")
    srv = KafkaWireServer(broker).start()
    rep = FollowerReplica(f"127.0.0.1:{srv.port}", topics=["T"],
                          poll_interval_s=0.01).start()
    try:
        deadline = time.monotonic() + 10
        while rep.rounds < 1 and time.monotonic() < deadline:
            real_sleep(0.01)
        srv.kill()
        while len(rep.sync_errors) < 6 and time.monotonic() < deadline:
            real_sleep(0.01)
        assert len(rep.sync_errors) >= 6
    finally:
        rep.stop()
    # backoff sleeps (base 0.02) dominate the idle sleeps (0.01): the
    # 6th consecutive failure sleeps >= min(2.0, 0.02*2^5)/2 = 0.32
    assert max(slept) >= 0.16
    assert max(slept) <= 2.0


# ------------------------------------------------- end-to-end scenarios
def _run(scenario, seed=7, records=100, tmp_path=None, **kw):
    from iotml.chaos.runner import ChaosRunner

    if tmp_path is not None and "span_path" not in kw:
        # keep test span logs under pytest's tmp dir, not /tmp litter
        kw["span_path"] = str(tmp_path / "spans.jsonl")
    return ChaosRunner(scenario, seed=seed, records=records, **kw).run()


def _failed(report):
    return [i.name for i in report.invariants if not i.ok]


@pytest.mark.parametrize("scenario", [
    "mqtt-flap", "slow-bridge", "dup-storm", "partition-blackout",
    "scorer-crash-resume"])
def test_inproc_scenarios_hold_the_invariants(scenario, tmp_path):
    report = _run(scenario, records=100, tmp_path=tmp_path)
    assert report.ok, _failed(report)
    assert sum(report.injected.values()) > 0
    assert report.published == 100
    if scenario == "mqtt-flap":
        assert report.dropped_accounted > 0
        assert report.scored == 100 - report.dropped_accounted
    if scenario == "dup-storm":
        assert report.scored > 100  # duplicates absorbed, not lost
    if scenario in ("partition-blackout", "scorer-crash-resume"):
        assert report.rewinds > 0  # redelivery actually exercised


def test_leader_kill_scenario_holds_the_invariants():
    report = _run("leader-kill-mid-drain", records=100)
    assert report.ok, _failed(report)
    assert report.injected.get("runner.kill_leader:kill_leader") == 1
    assert report.scored >= report.published == 100
    names = [i.name for i in report.invariants]
    assert "promotion_loss_bounded" in names


def test_broker_crash_recover_scenario_holds_the_invariants(tmp_path):
    """The store topology: durable broker killed mid-write (torn tail),
    remounted, invariants incl. the recovery-specific ones must hold."""
    report = _run("broker-crash-recover", records=100, tmp_path=tmp_path)
    assert report.ok, _failed(report)
    assert report.topology == "store"
    assert report.injected.get("runner.crash_broker:crash_broker") == 1
    assert report.published == 100
    by_name = {i.name: i for i in report.invariants}
    assert by_name["torn_tail_truncated"].ok
    assert by_name["replay_byte_identical"].ok
    assert by_name["consumer_resumed_from_committed"].ok


def test_rebalance_under_chaos_scenario_holds_the_invariants():
    """The cluster topology: a group member AND a shard leader die
    mid-epoch on a 3-broker cluster; every record must be scored
    exactly once across the rebalance + per-shard failover."""
    report = _run("rebalance-under-chaos", records=200)
    assert report.ok, _failed(report)
    assert report.topology == "cluster"
    assert report.injected.get("runner.kill_member:kill_member") == 1
    assert report.injected.get(
        "runner.kill_shard_leader:kill_shard_leader") == 1
    by_name = {i.name: i for i in report.invariants}
    assert by_name["zero_records_lost"].ok
    assert by_name["zero_double_scored"].ok
    assert by_name["member_death_rebalanced"].ok
    assert by_name["shard_failover_one_shard_only"].ok


def test_drift_storm_registry_and_topology():
    """The online topology's schedule: flap events riding beside the
    runner-seeded regional drift, deterministic and online-routed (the
    full run is CI's online.yml drill + the slow marker below)."""
    sched = build("drift-storm", seed=7, records=2000)
    assert sched.topology == "online"
    drops = [e for e in sched.events
             if e.point == "mqtt.deliver" and e.action == "drop"]
    assert len(drops) >= 2
    assert build("drift-storm", seed=7, records=2000).text() \
        == sched.text()


@pytest.mark.slow
def test_drift_storm_scenario_holds_the_invariants(tmp_path):
    """The online topology end to end: regional drift + mqtt-flap
    concurrently; the learner detects/adapts/converges, the adapted
    model hot-swaps the scorer, drops are accounted, nothing is lost
    or double-scored across the swap."""
    report = _run("drift-storm", records=2000, tmp_path=tmp_path)
    assert report.ok, _failed(report)
    assert report.topology == "online"
    assert report.dropped_accounted > 0
    by_name = {i.name: i for i in report.invariants}
    assert by_name["drift_detected"].ok
    assert by_name["adaptation_converged"].ok
    assert by_name["adapted_model_swapped"].ok


def test_loss_bug_fixture_fails_the_checker(tmp_path):
    """The checker checked: a committed-then-silently-dropped record
    (the seeded unledgered drop) must FAIL, naming the lost trace."""
    report = _run("loss-bug-fixture", records=100, tmp_path=tmp_path)
    assert not report.ok
    failed = _failed(report)
    assert "scored_or_accounted" in failed
    detail = next(i.detail for i in report.invariants
                  if i.name == "scored_or_accounted")
    assert "SILENTLY LOST" in detail


def test_same_seed_same_verdict(tmp_path):
    """Determinism end to end: schedule, fault counts, published/scored
    totals and every verdict replay exactly."""
    a = _run("mqtt-flap", seed=5, records=75,
             span_path=str(tmp_path / "a.jsonl"))
    b = _run("mqtt-flap", seed=5, records=75,
             span_path=str(tmp_path / "b.jsonl"))
    assert build("mqtt-flap", seed=5, records=75).text() == \
        build("mqtt-flap", seed=5, records=75).text()
    assert (a.published, a.scored, a.injected, a.dropped_accounted) == \
        (b.published, b.scored, b.injected, b.dropped_accounted)
    assert [(i.name, i.ok) for i in a.invariants] == \
        [(i.name, i.ok) for i in b.invariants]
    assert a.ok and b.ok


def test_runner_restores_tracing_state(tmp_path):
    from iotml.obs import tracing

    before = (tracing.ENABLED, tracing._SAMPLE, tracing._PATH)
    _run("dup-storm", records=50, tmp_path=tmp_path)
    assert (tracing.ENABLED, tracing._SAMPLE, tracing._PATH) == before
