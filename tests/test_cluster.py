"""iotml.cluster: partitioned multi-broker data plane.

The reference runs 10-partition topics on a 3-broker cluster
(PAPER.md L3); these tests prove the rebuild's equivalent — shard-aware
brokers, per-partition Metadata routing, NOT_LEADER bounces + cached-
metadata refresh, coordinator pinning, per-shard failover, and the
cluster edges the ISSUE names: stale-metadata handoff vs in-flight
fetch, coordinator death mid-generation, cold restart from store dirs,
and revocation committing before release.
"""

import os

import pytest

from iotml.cluster import ClusterClient, ClusterController, PartitionMap
from iotml.stream.group import GroupConsumer, GroupCoordinator
from iotml.stream.kafka_wire import (KafkaWireBroker,
                                     NotLeaderForPartitionError,
                                     RemoteGroupCoordinator)

TOPIC = "sensor-data"
PARTS = 6


@pytest.fixture
def cluster():
    ctl = ClusterController(brokers=3).start()
    ctl.create_topic(TOPIC, partitions=PARTS)
    yield ctl
    ctl.stop()


def fill(client, n=60):
    for i in range(n):
        client.produce(TOPIC, f"v{i}".encode(), key=f"car{i}".encode())


# ------------------------------------------------------------- sharding
def test_each_broker_materializes_only_its_partitions(cluster):
    cli = cluster.client()
    fill(cli)
    for i, b in enumerate(cluster.brokers):  # lint-not-applicable: tests
        owned = [p for p in range(PARTS) if b.owns(TOPIC, p)]
        assert owned == [p for p in range(PARTS) if p % 3 == i]
        for p in range(PARTS):
            if b.owns(TOPIC, p):
                b.end_offset(TOPIC, p)  # serves its own
            else:
                with pytest.raises(NotLeaderForPartitionError):
                    b.end_offset(TOPIC, p)
    # nothing lost in routing: all records across all shards
    assert sum(cli.end_offset(TOPIC, p) for p in range(PARTS)) == 60
    cli.close()


def test_keyed_routing_is_cross_client_stable(cluster):
    """The same key lands on the same partition through the cluster
    client and the plain wire client (per-key ordering invariant)."""
    cli = cluster.client()
    raw = KafkaWireBroker(cluster.pmap.leader(0))
    import zlib

    for key in (b"car1", b"car42", b"x"):
        expect = zlib.crc32(key) % PARTS
        assert cli._partition_for(TOPIC, key) == expect
        assert raw._partition_for(TOPIC, key) == expect
    raw.close()
    cli.close()


def test_metadata_carries_per_partition_leaders(cluster):
    raw = KafkaWireBroker(cluster.pmap.leader(1))
    meta = raw.cluster_metadata([TOPIC])
    assert {n for n, _h, _p, _r in meta["brokers"]} == {0, 1, 2}
    for p in range(PARTS):
        assert meta["leaders"][(TOPIC, p)] == p % 3
    raw.close()


def test_unowned_partition_bounces_error_6(cluster):
    raw = KafkaWireBroker(cluster.pmap.leader(0))
    with pytest.raises(NotLeaderForPartitionError):
        raw.fetch(TOPIC, 1, 0)  # partition 1 lives on broker 1
    with pytest.raises(NotLeaderForPartitionError):
        raw.produce(TOPIC, b"x", partition=2)
    raw.close()


# ------------------------------------------- stale metadata vs handoff
def test_stale_metadata_fetch_refreshes_and_reroutes(cluster):
    """The ISSUE edge: an in-flight consumer holding a STALE map fetches
    from the wrong broker, gets NOT_LEADER, refreshes its cached
    metadata and retries against the real owner — no error escapes."""
    seed = cluster.client()
    fill(seed)
    wc = ClusterClient(bootstrap=cluster.pmap.leader(0))
    before = wc.fetch(TOPIC, 1, 0, 100)
    assert before
    # poison the cache: claim partition 1 lives on node 0
    wc._leaders[(TOPIC, 1)] = 0
    again = wc.fetch(TOPIC, 1, 0, 100)
    assert [(m.offset, m.value) for m in again] == \
        [(m.offset, m.value) for m in before]
    # the bounce healed the cache
    assert wc._leaders[(TOPIC, 1)] == 1
    wc.close()
    seed.close()


def test_stale_metadata_produce_retries_without_duplication(cluster):
    wc = ClusterClient(bootstrap=cluster.pmap.leader(2))
    wc.produce(TOPIC, b"a", partition=1)
    wc._leaders[(TOPIC, 1)] = 2  # stale: wrong owner
    wc.produce(TOPIC, b"b", partition=1)
    msgs = wc.fetch(TOPIC, 1, 0, 10)
    # NOT_LEADER means nothing was appended on the bounce: exactly two
    assert [m.value for m in msgs] == [b"a", b"b"]
    wc.close()


def test_handoff_during_drain_keeps_offsets_identical():
    """Per-shard failover mid-drain: the promoted follower serves the
    SAME offsets, and the in-flight consumer resumes seamlessly."""
    ctl = ClusterController(brokers=3, replicated=True,
                            replica_sync="manual").start()
    try:
        ctl.create_topic(TOPIC, partitions=PARTS)
        cli = ctl.client()
        fill(cli, 90)
        # drain halfway
        cursors = {p: 0 for p in range(PARTS)}
        seen = []
        for p in range(PARTS):
            got = cli.fetch(TOPIC, p, 0, 5)
            seen.extend((m.partition, m.offset, m.value) for m in got)
            cursors[p] = got[-1].offset + 1 if got else 0
        ctl.sync_replicas_once()
        victim = 1
        pre_end = {p: cli.end_offset(TOPIC, p) for p in range(PARTS)}
        ctl.fail_shard(victim)
        assert ctl.pmap.epoch(victim) == 1
        # resume the drain through the SAME client: moved shard's
        # partitions serve at identical offsets from the follower
        for p in range(PARTS):
            got = cli.fetch(TOPIC, p, cursors[p], 1000)
            seen.extend((m.partition, m.offset, m.value) for m in got)
        assert len(seen) == len(set(seen)) == 90
        assert {p: cli.end_offset(TOPIC, p)
                for p in range(PARTS)} == pre_end
        cli.close()
    finally:
        ctl.stop()


# ------------------------------------------------ group over the wire
def test_group_members_split_partitions_across_shards(cluster):
    seed = cluster.client()
    fill(seed, 60)
    c1, c2 = cluster.client(), cluster.client()
    g1 = GroupConsumer(RemoteGroupCoordinator(c1, "g"), [TOPIC])
    g2 = GroupConsumer(RemoteGroupCoordinator(c2, "g"), [TOPIC])
    g1.poll(0)  # heartbeat: pick up the rebalance g2's join triggered
    assert sorted(g1.assignment + g2.assignment) == \
        [(TOPIC, p) for p in range(PARTS)]
    seen = []
    for gc in (g1, g2):
        while True:
            batch = gc.poll(1000)
            if not batch:
                break
            seen.extend((m.partition, m.offset) for m in batch)
        assert gc.commit() is True
    assert len(seen) == len(set(seen)) == 60
    for c in (c1, c2, seed):
        c.close()


def test_coordinator_death_mid_generation():
    """The ISSUE edge: the coordinator broker dies mid-generation.
    Members re-find the promoted coordinator, the group re-forms, and
    they resume from the MIRRORED committed offsets — nothing lost,
    nothing double-consumed after the committed frontier."""
    ctl = ClusterController(brokers=3, replicated=True,
                            replica_sync="manual",
                            mirror_groups=("g",)).start()
    try:
        ctl.create_topic(TOPIC, partitions=PARTS)
        seed = ctl.client()
        fill(seed, 60)
        c1, c2 = ctl.client(), ctl.client()
        g1 = GroupConsumer(RemoteGroupCoordinator(c1, "g"), [TOPIC])
        g2 = GroupConsumer(RemoteGroupCoordinator(c2, "g"), [TOPIC])
        seen = []
        for gc in (g1, g2):
            while True:
                batch = gc.poll(1000)
                if not batch:
                    break
                seen.extend((m.partition, m.offset) for m in batch)
            assert gc.commit() is True
        assert len(seen) == 60
        # 60 more records arrive, replication drains to zero lag
        # (the zero-loss handoff contract: async replication's loss
        # window is the lag at kill), THEN the coordinator dies
        fill(seed, 60)
        while ctl.sync_replicas_once() > 0:
            pass
        assert ctl.pmap.coordinator()[0] == 0
        ctl.fail_shard(0)
        # committed offsets survived the coordinator move
        assert seed.committed("g", TOPIC, 0) is not None
        # members heal: polls rejoin against the promoted coordinator
        seen2 = []
        for _ in range(30):
            for gc in (g1, g2):
                batch = gc.poll(1000)
                for m in batch:
                    seen2.append((m.partition, m.offset))
                if batch:
                    # commit-after-poll: the zero-duplicate discipline —
                    # a partition handed to the peer resumes at this
                    # member's committed (== scored) frontier
                    gc.commit()
            assigned = set()
            for gc in (g1, g2):
                assigned.update(gc.assignment)
            if len(seen2) >= 60 and \
                    assigned == {(TOPIC, p) for p in range(PARTS)}:
                break
        assert g1.rebalances + g2.rebalances > 0
        # every NEW record seen exactly once; nothing before the
        # mirrored frontier redelivered
        assert sorted(set(seen2)) == sorted(seen2)
        assert len(seen2) == 60
        assert not (set(seen2) & set(seen))
        for c in (c1, c2, seed):
            c.close()
    finally:
        ctl.stop()


# ------------------------------------------------------- cold restart
def test_cold_restart_resumes_every_shard_from_store(tmp_path):
    """The ISSUE edge: stop the whole cluster, boot a fresh controller
    on the same store root — every shard remounts its own partition
    dirs, offsets resume, and each broker dir holds ONLY its shard."""
    root = str(tmp_path / "cluster")
    ctl = ClusterController(brokers=3, store_root=root).start()
    ctl.create_topic(TOPIC, partitions=PARTS)
    cli = ctl.client()
    fill(cli, 60)
    ends = {p: cli.end_offset(TOPIC, p) for p in range(PARTS)}
    payload = {p: [m.value for m in cli.fetch(TOPIC, p, 0, 1000)]
               for p in range(PARTS)}
    cli.commit("g", TOPIC, 1, 4)
    cli.close()
    ctl.stop()
    # each broker dir materialized exactly its own partitions
    for i in range(3):
        pdir = os.path.join(root, f"broker-{i}", "segments", TOPIC)
        assert sorted(os.listdir(pdir)) == \
            sorted(str(p) for p in range(PARTS) if p % 3 == i)
    ctl2 = ClusterController(brokers=3, store_root=root).start()
    try:
        # the manifests re-created the topics cluster-wide
        assert ctl2.pmap.topics()[TOPIC] == PARTS
        cli2 = ctl2.client()
        assert {p: cli2.end_offset(TOPIC, p)
                for p in range(PARTS)} == ends
        assert {p: [m.value for m in cli2.fetch(TOPIC, p, 0, 1000)]
                for p in range(PARTS)} == payload
        # committed offsets persisted on the coordinator's store
        assert cli2.committed("g", TOPIC, 1) == 4
        cli2.close()
    finally:
        ctl2.stop()


# ------------------------------------- revocation commits before release
def test_revocation_commits_before_release(broker_10):
    """A member that polled-but-not-committed loses partitions in a
    rebalance: its pre-rejoin commit (inside the coordinator's grace
    window) hands the successor its REAL frontier — no redelivery of
    work already done."""
    coord = GroupCoordinator(broker_10, "g", session_timeout_s=30.0)
    c1 = GroupConsumer(coord, ["sensor-data"])
    for _ in range(3):
        c1.poll(40)  # progress WITHOUT an explicit commit
    polled = {p: off for _t, p, off in c1.positions()}
    c2 = GroupConsumer(coord, ["sensor-data"])  # rebalance: c1 fenced
    c1.poll(1)  # heartbeat fails -> grace commit -> rejoin
    # partitions c1 RELEASED to c2 start at c1's polled frontier
    for t, p in c2.assignment:
        committed = broker_10.committed("g", t, p)
        assert committed == polled[p], (p, committed, polled[p])


def test_revocation_grace_never_rewinds_successor(broker_10):
    clock = __import__("tests.test_group", fromlist=["FakeClock"]) \
        .FakeClock()
    coord = GroupCoordinator(broker_10, "g", session_timeout_s=30.0,
                             clock=clock)
    m1, gen1, _ = coord.join(["sensor-data"])
    # rebalance twice: m1 is pending at gen1
    coord.join(["sensor-data"])
    # the successor commits FURTHER than m1's stale cursor
    members = coord.members()
    m2 = [m for m in members if m != m1][0]
    _, gen2, assigned2 = coord.join(["sensor-data"], m2)
    t, p = assigned2[0]
    assert coord.fenced_commit(m2, gen2, [(t, p, 15)])
    # m1's grace commit with an OLDER offset must not rewind it
    owned_then = [(tt, pp, 3) for tt, pp in [(t, p)]]
    coord.fenced_commit(m1, gen1, owned_then)
    assert broker_10.committed("g", t, p) == 15


def test_expired_member_gets_no_grace(broker_10):
    from tests.test_group import FakeClock

    clock = FakeClock()
    coord = GroupCoordinator(broker_10, "g", session_timeout_s=5.0,
                             clock=clock)
    m1, gen1, assigned = coord.join(["sensor-data"])
    clock.t += 10.0
    coord.members()  # expiry sweep
    t, p = assigned[0]
    assert coord.fenced_commit(m1, gen1, [(t, p, 7)]) is False
    assert broker_10.committed("g", t, p) is None


# ----------------------------------------------------------- supervise
def test_supervised_per_shard_failover_moves_one_shard():
    ctl = ClusterController(brokers=3, replicated=True,
                            replica_sync="manual").start()
    try:
        ctl.create_topic(TOPIC, partitions=PARTS)
        cli = ctl.client()
        fill(cli, 30)
        ctl.sync_replicas_once()
        sup = ctl.supervised(poll_interval_s=0.02).start()
        try:
            before = {s: ctl.pmap.leader(s) for s in range(3)}
            ctl.kill_shard(2)
            assert ctl.await_failover(2, timeout_s=10.0)
            # exactly one shard moved
            assert ctl.pmap.leader(2) != before[2]
            assert ctl.pmap.leader(0) == before[0]
            assert ctl.pmap.leader(1) == before[1]
            assert ctl.pmap.epoch(2) == 1
        finally:
            sup.stop()
        # the moved shard serves; the others never blinked
        assert sum(cli.end_offset(TOPIC, p) for p in range(PARTS)) == 30
        cli.produce(TOPIC, b"post", partition=2)
        assert cli.fetch(TOPIC, 2, 0, 100)[-1].value == b"post"
        cli.close()
    finally:
        ctl.stop()


# ------------------------------------------------------------- fleets
def test_pump_fleet_rebalances_on_member_death():
    from iotml.cluster import PumpFleet
    from iotml.streamproc.tasks import StreamTask

    class Upper(StreamTask):
        def process(self, messages):
            return [(m.key, m.value.upper(), m.timestamp_ms)
                    for m in messages]

    ctl = ClusterController(brokers=3).start()
    try:
        ctl.create_topic("src", partitions=PARTS)
        seed = ctl.client()
        for i in range(40):
            seed.produce("src", f"r{i}".encode(), key=f"k{i}".encode())

        fleet = PumpFleet(
            lambda: ctl.client(),
            lambda client, consumer: Upper(client, "src", "dst",
                                           partitions=PARTS,
                                           consumer=consumer),
            n_members=2, src_topic="src", group="pumps",
            session_timeout_ms=400)
        for _ in range(5):
            fleet.pump_once()
        fleet.kill(0)
        import time as _t

        deadline = _t.monotonic() + 10.0
        while _t.monotonic() < deadline:
            fleet.pump_once()
            survivor = fleet.members[1].consumer
            if set(survivor.assignment) == \
                    {("src", p) for p in range(PARTS)}:
                break
            _t.sleep(0.05)
        # drain everything through the survivor
        for _ in range(10):
            fleet.pump_once()
        total = sum(seed.end_offset("dst", p) for p in range(PARTS))
        # exactly-once into dst across the rebalance: every src record
        # transformed once (commits fence the dead member's frontier)
        assert total == 40
        fleet.stop()
        seed.close()
    finally:
        ctl.stop()


@pytest.fixture
def broker_10():
    from iotml.stream.broker import Broker

    b = Broker()
    b.create_topic("sensor-data", partitions=10)
    for i in range(200):
        b.produce("sensor-data", f"r{i}".encode(), partition=i % 10)
    return b
