"""Key-based log compaction (iotml.store.compact): the keep/discard
rule, segment rewrites, dirty-ratio triggering, tombstone grace,
composition with retention/indexes/recovery/replication, and the
tombstone transport end to end (broker, wire, native client).

The ISSUE-8 checklist rows: dirty-ratio trigger, tombstone grace
expiry, compaction x retention interplay, index rebuild over compacted
segments, byte-stable remount."""

import os

import pytest

from iotml.store import SegmentedLog, StorePolicy
from iotml.store import compact as cp
from iotml.store import segment as seg
from iotml.stream.broker import Broker


def _pol(**kw):
    kw.setdefault("fsync", "never")
    kw.setdefault("segment_bytes", 10 ** 9)
    return StorePolicy(**kw)


def _offsets(log):
    return [r[0] for r in log.read_from(log.base_offset, 10 ** 6)]


def _records(log):
    return log.read_from(log.base_offset, 10 ** 6)


def _drain(b, topic, p=0):
    """Broker-level cursor read: fetch batches END at compaction holes
    (no internal gaps), so a full read walks batch by batch."""
    out, off = [], b.begin_offset(topic, p)
    end = b.end_offset(topic, p)
    while off < end:
        batch = b.fetch(topic, p, off, 10 ** 6)
        if not batch:
            break
        out += batch
        off = batch[-1].offset + 1
    return out


# ---------------------------------------------------------- the decision
def test_tombstone_frame_is_byte_distinct_from_empty():
    dead = seg.encode_record(5, b"k", None, 10, None)
    empty = seg.encode_record(5, b"k", b"", 10, None)
    assert dead != empty
    (_p, _e, _o, _k, v_dead, _t, _h), = seg.scan_records(dead)
    (_p, _e, _o, _k, v_empty, _t, _h), = seg.scan_records(empty)
    assert v_dead is None and v_empty == b""


def test_keep_rule_latest_per_key_unkeyed_and_grace():
    recs = [(0, b"a", b"1", 100, None), (1, None, b"x", 110, None),
            (2, b"a", b"2", 120, None), (3, b"b", None, 130, None)]
    latest = cp.latest_offsets(recs)
    assert latest == {b"a": 2, b"b": 3}
    newest = 130
    # shadowed value out, latest + unkeyed in
    assert not cp.keep(recs[0], latest, newest, grace_ms=10 ** 6)
    assert cp.keep(recs[1], latest, newest, grace_ms=10 ** 6)
    assert cp.keep(recs[2], latest, newest, grace_ms=10 ** 6)
    # the tombstone: kept inside grace, dropped past it, forever if None
    assert cp.keep(recs[3], latest, newest_ts=200, grace_ms=100)
    assert not cp.keep(recs[3], latest, newest_ts=300, grace_ms=100)
    assert cp.keep(recs[3], latest, newest_ts=10 ** 9, grace_ms=None)


# ----------------------------------------------------- segment compactor
def test_compact_keeps_latest_per_key_and_preserves_offsets(tmp_path):
    log = SegmentedLog(str(tmp_path), _pol(segment_bytes=256))
    for rnd in range(6):
        for k in range(4):
            log.append(f"k{k}".encode(), f"v{rnd}".encode(),
                       1000 + rnd * 10 + k)
    log.append(None, b"unkeyed", 2000)  # never compacted away
    log.roll()
    assert len(log._segments) > 2
    before = {r[0]: r for r in _records(log)}
    stats = log.compact()
    assert stats.records_removed > 0 and stats.bytes_reclaimed > 0
    after = _records(log)
    # offsets preserved: every survivor is its original byte-for-byte
    # record, never renumbered
    for r in after:
        assert before[r[0]] == r
    by_key = {}
    for off, key, value, ts, _h in after:
        if key is not None:
            by_key[key] = value
    assert by_key == {f"k{k}".encode(): b"v5" for k in range(4)}
    assert any(key is None for _o, key, _v, _t, _h in after)
    # the ACTIVE segment is never touched; a second pass is a no-op
    assert log.compact().segments_rewritten == 0


def test_dirty_ratio_trigger_and_broker_gate(tmp_path):
    b = Broker(store_dir=str(tmp_path),
               store_policy=_pol(segment_bytes=256,
                                 compact_min_dirty_ratio=0.5))
    b.create_topic("C", cleanup_policy="compact")
    b.create_topic("D")  # delete-policy topic: never compacted
    slog = b.store.log_for("C", 0)
    assert slog.dirty_ratio() == 0.0  # nothing sealed yet
    for rnd in range(8):
        for k in range(4):
            b.produce("C", f"v{rnd}".encode(), key=f"k{k}".encode(),
                      partition=0, timestamp_ms=1000 + rnd)
            b.produce("D", b"x", key=b"k", partition=0)
    slog.roll()
    assert slog.dirty_ratio() == 1.0  # all sealed bytes unclean
    out = b.run_compaction()
    assert ("C", 0) in out and ("D", 0) not in out
    assert slog.dirty_ratio() == 0.0
    # a little new data: below the 0.5 gate, the pass skips it
    b.produce("C", b"v9", key=b"k0", partition=0, timestamp_ms=2000)
    b.store.log_for("C", 0).roll()
    assert 0.0 < b.store.log_for("C", 0).dirty_ratio() < 0.5
    assert b.run_compaction() == {}
    assert b.run_compaction(force=True) != {}
    b.close()


def test_tombstone_grace_expiry(tmp_path):
    log = SegmentedLog(str(tmp_path), _pol())
    log.append(b"a", b"v1", 1000)
    log.append(b"a", None, 2000)     # delete a
    log.append(b"b", b"v2", 2500)    # newest record ts
    log.roll()
    # inside grace (2500-2000 <= 1000): the tombstone survives so slow
    # readers still observe the delete
    log.compact(grace_ms=1000)
    recs = _records(log)
    assert (1, b"a", None, 2000, None) in recs
    # past grace: the tombstone itself is reclaimed; the key is gone
    log.compact(grace_ms=100)
    recs = _records(log)
    assert [r[0] for r in recs] == [2]
    assert all(r[1] != b"a" for r in recs)


def test_compaction_composes_with_retention(tmp_path):
    b = Broker(store_dir=str(tmp_path), store_policy=_pol(segment_bytes=256))
    b.create_topic("C", cleanup_policy="compact", retention_messages=16)
    # 40 UNIQUE keys first: compaction has nothing to reclaim here, so
    # bounding the log is retention's job (whole head segments go as
    # the produce loop outgrows the cap)
    for k in range(40):
        b.produce("C", b"first", key=f"u{k:02d}".encode(),
                  partition=0, timestamp_ms=1000 + k)
    assert b.begin_offset("C", 0) > 0  # retention trimmed the head
    # then repeated UPDATES of a retained key: retention can't touch
    # the newest segments, so bounding those is compaction's job
    for rnd in range(8):
        b.produce("C", f"v{rnd}".encode(), key=b"hot", partition=0,
                  timestamp_ms=2000 + rnd)
    b.store.log_for("C", 0).roll()
    base_before = b.begin_offset("C", 0)
    out = b.run_compaction(force=True)
    assert out[("C", 0)].records_removed > 0
    # compaction never moves the base (the out-of-range contract is
    # retention's alone) and the key's latest value survives both
    assert b.begin_offset("C", 0) == base_before
    live = {m.key: m.value for m in _drain(b, "C")}
    assert live[b"hot"] == b"v7"
    assert sum(1 for k in live if k.startswith(b"u")) == len(live) - 1
    b.close()


def test_index_rebuild_and_reads_over_compacted_segments(tmp_path):
    pol = _pol(segment_bytes=256, index_interval_bytes=64)
    log = SegmentedLog(str(tmp_path), pol)
    for rnd in range(8):
        for k in range(4):
            log.append(f"k{k}".encode(), b"v%d" % rnd, 1000 + rnd * 10 + k)
    log.roll()
    log.compact()
    survivors = _offsets(log)
    # cursor reads across the holes: batches never carry internal gaps,
    # and a read starting INSIDE a hole lands on the next survivor
    got, off = [], 0
    while True:
        chunk = log.read_from(off, 3)
        if not chunk:
            break
        offs = [r[0] for r in chunk]
        assert offs == list(range(offs[0], offs[0] + len(offs)))
        got += offs
        off = offs[-1] + 1
    assert got == survivors
    # timestamp replay over the compacted log: first surviving record
    # at/after the timestamp
    ts_target = 1050
    off_for = log.offset_for_timestamp(ts_target)
    assert off_for in survivors or off_for == log.end_offset
    log.close()
    # remount: sidecar indexes rebuilt/trusted over the compacted
    # segments, same reads
    log2 = SegmentedLog(str(tmp_path), pol)
    assert _offsets(log2) == survivors
    assert log2.offset_for_timestamp(ts_target) == off_for
    log2.close()
    # index/log mismatch path: delete sidecars, full rescan, same reads
    for n in list(os.listdir(str(tmp_path))):
        if n.endswith((".index", ".timeindex")):
            os.remove(str(tmp_path / n))
    log3 = SegmentedLog(str(tmp_path), pol)
    assert _offsets(log3) == survivors
    log3.close()


def test_compacted_reads_byte_stable_across_remount(tmp_path):
    pol = _pol(segment_bytes=256)
    log = SegmentedLog(str(tmp_path), pol)
    for rnd in range(8):
        for k in range(4):
            log.append(f"k{k}".encode(), b"v%d" % rnd, 1000 + rnd)
    log.append(b"k0", None, 1100)  # a tombstone inside grace: kept
    log.roll()
    log.compact(grace_ms=10 ** 9)
    want = _records(log)
    names = sorted(n for n in os.listdir(str(tmp_path))
                   if n.endswith(".log"))
    # the max-named file is the EMPTY active segment the roll opened;
    # recovery legitimately drops it at remount, so byte-stability is a
    # sealed-segment contract
    files = {n: open(os.path.join(str(tmp_path), n), "rb").read()
             for n in names[:-1]}
    log.close()
    log2 = SegmentedLog(str(tmp_path), pol)
    # fetch-level byte stability: identical (offset, key, value, ts)
    assert _records(log2) == want
    # file-level too: a remount rewrites nothing
    for n, blob in files.items():
        assert open(os.path.join(str(tmp_path), n), "rb").read() == blob
    log2.close()


def test_fully_dead_segments_drop_but_head_keeps_base(tmp_path):
    log = SegmentedLog(str(tmp_path), _pol(segment_bytes=200))
    for rnd in range(12):
        log.append(b"one-key", b"v%02d" % rnd, 1000 + rnd)
    log.roll()
    n_before = len(log._segments)
    assert n_before > 3
    log.compact()
    # every sealed record except the last write is shadowed: non-head
    # dead segments are dropped outright, the head survives (possibly
    # empty) so base_offset — and the out-of-range contract — is
    # compaction-invariant
    assert len(log._segments) < n_before
    assert log.base_offset == 0
    assert [r[:3] for r in _records(log)] == [(11, b"one-key", b"v11")]
    log.close()
    log2 = SegmentedLog(str(tmp_path), _pol(segment_bytes=200))
    assert log2.base_offset == 0 and _offsets(log2) == [11]
    log2.close()


def test_stale_cleaned_tmp_swept_at_mount(tmp_path):
    pol = _pol()
    log = SegmentedLog(str(tmp_path), pol)
    log.append(b"k", b"v", 1)
    log.close()
    stale = os.path.join(str(tmp_path), "00000000000000000000.log"
                         + cp.CLEANED_SUFFIX)
    with open(stale, "wb") as fh:  # lint-ok: R9 seeding the crash artifact the mount must sweep
        fh.write(b"half-finished rewrite")
    log2 = SegmentedLog(str(tmp_path), pol)
    assert not os.path.exists(stale)
    assert _offsets(log2) == [0]
    log2.close()


# ------------------------------------------------- offsets-file migration
def test_offsets_file_routes_through_generic_compactor(tmp_path, monkeypatch):
    """The satellite: ONE compaction implementation.  OffsetsFile.compact
    must route its keep/discard decision through store.compact.keep."""
    from iotml.store import OffsetsFile

    calls = []
    real_keep = cp.keep

    def spy(record, latest, newest_ts, grace_ms):
        calls.append(record)
        return real_keep(record, latest, newest_ts, grace_ms)

    monkeypatch.setattr(cp, "keep", spy)
    f = OffsetsFile(str(tmp_path / "offsets"), fsync="never",
                    compact_ratio=10 ** 9)
    for i in range(20):
        f.commit("g", "t", 0, i)
    f.compact()
    assert calls, "OffsetsFile.compact bypassed the generic keep rule"
    assert f.table()[("g", "t", 0)] == 19
    f.close()
    # and the compacted file still reloads to the same table
    f2 = OffsetsFile(str(tmp_path / "offsets"), fsync="never")
    assert f2.table()[("g", "t", 0)] == 19
    f2.close()


# ----------------------------------------------- tombstone transport e2e
def test_tombstone_survives_durable_broker_remount(tmp_path):
    b = Broker(store_dir=str(tmp_path), store_policy=_pol())
    b.create_topic("C", cleanup_policy="compact")
    b.produce("C", b"v", key=b"k", partition=0, timestamp_ms=1)
    b.produce("C", None, key=b"k", partition=0, timestamp_ms=2)
    msgs = b.fetch("C", 0, 0, 10)
    assert [m.value for m in msgs] == [b"v", None]
    b.close()
    b2 = Broker(store_dir=str(tmp_path), store_policy=_pol())
    assert b2.topic("C").cleanup_policy == "compact"  # manifest carried it
    msgs = b2.fetch("C", 0, 0, 10)
    assert [m.value for m in msgs] == [b"v", None]
    assert msgs[1].value is not b"" and msgs[1].value is None
    b2.close()


def test_tombstone_and_cleanup_policy_over_the_wire():
    from iotml.stream.kafka_wire import KafkaWireBroker, KafkaWireServer

    b = Broker()
    with KafkaWireServer(b) as srv:
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        client.create_topic("C", partitions=1, cleanup_policy="compact")
        assert b.topic("C").cleanup_policy == "compact"
        with pytest.raises(ValueError):
            client.create_topic("bad", cleanup_policy="sometimes")
        client.produce("C", b"v", key=b"k", partition=0)
        client.produce("C", None, key=b"k", partition=0)
        got = client.fetch("C", 0, 0)
        assert [m.value for m in got] == [b"v", None]
        assert got[1].key == b"k"
        client.close()


def test_tombstone_through_native_client():
    from iotml.stream import native
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.native_kafka import NativeKafkaBroker

    if native.load() is None:
        pytest.skip("native engine not built")
    b = Broker()
    with KafkaWireServer(b) as srv:
        client = NativeKafkaBroker(f"127.0.0.1:{srv.port}")
        # the policy rides the native CreateTopics too (a TwinService
        # can own its changelog over the native client)
        client.create_topic("C", cleanup_policy="compact")
        assert b.topic("C").cleanup_policy == "compact"
        client.produce_many("C", [(b"k", b"v", 1), (b"k", None, 2),
                                  (b"j", b"w", 3)], partition=0)
        got = client.fetch("C", 0, 0)
        assert [(m.key, m.value) for m in got] == \
            [(b"k", b"v"), (b"k", None), (b"j", b"w")]
        client.close()


def test_replica_mirrors_compacted_topic_with_holes(tmp_path):
    """Compaction punches offset holes; a durable follower must mirror
    them offset-preserving (produce_at), never renumber."""
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.replica import FollowerReplica

    leader = Broker(store_dir=str(tmp_path / "leader"),
                    store_policy=_pol(segment_bytes=256))
    leader.create_topic("C", cleanup_policy="compact")
    for rnd in range(8):
        for k in range(4):
            leader.produce("C", f"v{rnd}".encode(), key=f"k{k}".encode(),
                           partition=0, timestamp_ms=1000 + rnd)
    leader.store.log_for("C", 0).roll()
    leader.run_compaction(force=True)
    want = [(m.offset, m.key, m.value, m.timestamp_ms)
            for m in leader.fetch("C", 0, 0, 10 ** 6)]
    assert [o for o, _k, _v, _t in want] != list(range(len(want)))  # holes
    with KafkaWireServer(leader) as srv:
        # the wire Metadata carries no topic configs, so a wire follower
        # is TOLD which topics mirror with compacted semantics — same
        # operator contract as its retention bound
        with FollowerReplica(f"127.0.0.1:{srv.port}", topics=["C"],
                             store_dir=str(tmp_path / "follower"),
                             compacted_topics=("C",)) as rep:
            assert rep.caught_up(timeout_s=15)
            rep.pause()  # round barrier: no in-flight sync while we read
            assert rep.sync_errors == []
            got = [(m.offset, m.key, m.value, m.timestamp_ms)
                   for m in rep.local.fetch("C", 0, 0, 10 ** 6)]
            assert got == want  # identical offsets, identical holes
            assert rep.local.topic("C").cleanup_policy == "compact"
    leader.close()


def test_in_memory_tombstone_and_compact_policy_metadata():
    b = Broker()
    spec = b.create_topic("C", cleanup_policy="compact")
    assert spec.cleanup_policy == "compact"
    with pytest.raises(ValueError):
        b.create_topic("bad", cleanup_policy="compact,delete")
    b.produce("C", None, key=b"k", partition=0)
    (m,) = b.fetch("C", 0, 0, 10)
    assert m.value is None
    assert b.run_compaction() == {}  # nothing durable to reclaim
