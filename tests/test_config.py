"""Typed config: layering order, coercion, error reporting."""

import json

import pytest

from iotml.config import Config, load_config


def test_defaults_match_reference_knobs():
    cfg, rest = load_config([], env={})
    assert rest == []
    assert cfg.train.epochs == 20 and cfg.train.batch_size == 100
    assert cfg.stream.topic == "SENSOR_DATA_S_AVRO"
    assert cfg.broker.partitions == 10
    assert cfg.scenario.num_cars == 25  # evaluation scenario scale


def test_layering_file_env_cli(tmp_path):
    path = str(tmp_path / "cfg.json")
    json.dump({"train": {"epochs": 5, "batch_size": 64},
               "artifacts": {"root": "/data"}}, open(path, "w"))
    cfg, rest = load_config(
        ["positional", "--train.epochs=7", "--mesh.data", "4", "pos2"],
        env={"IOTML_TRAIN_EPOCHS": "6", "IOTML_SERVE_POLL_INTERVAL_S": "2.5"},
        path=path)
    # file < env < CLI
    assert cfg.train.epochs == 7
    assert cfg.train.batch_size == 64        # from file, untouched by others
    assert cfg.serve.poll_interval_s == 2.5  # env, float-coerced
    assert cfg.mesh.data == 4                # CLI space-separated form
    assert cfg.artifacts.root == "/data"
    assert rest == ["positional", "pos2"]    # positionals pass through


def test_env_ignored_without_prefix_and_config_pointer(tmp_path):
    path = str(tmp_path / "cfg.json")
    json.dump({"train": {"epochs": 3}}, open(path, "w"))
    cfg, _ = load_config([], env={"IOTML_CONFIG": path, "TRAIN_EPOCHS": "9"})
    assert cfg.train.epochs == 3


def test_bool_coercion_and_errors():
    cfg, _ = load_config(["--train.only_normal=false"], env={})
    assert cfg.train.only_normal is False
    cfg, _ = load_config([], env={"IOTML_TRAIN_ONLY_NORMAL": "yes"})
    assert cfg.train.only_normal is True
    with pytest.raises(ValueError, match="bool"):
        load_config(["--train.only_normal=maybe"], env={})
    with pytest.raises(ValueError, match="unknown config key"):
        load_config(["--train.epoch=3"], env={})
    with pytest.raises(ValueError, match="unknown config section"):
        load_config(["--trane.epochs=3"], env={})
    with pytest.raises(ValueError, match="cannot parse"):
        load_config(["--train.epochs=ten"], env={})
    # a typo'd *section* in an IOTML_ env var fails as loudly as a field
    with pytest.raises(ValueError, match="unknown config section"):
        load_config([], env={"IOTML_SREVE_POLL_INTERVAL_S": "5"})


def test_applied_keys_tracked():
    # IOTML_MESH_DATA is claimed by the multichip PROCESS knob since
    # ISSUE 15 (data/pipeline.py, non_config) — mesh.data stays
    # settable via flags/file; the env probe uses mesh.model instead
    cfg, _ = load_config(["--train.epochs=7"],
                         env={"IOTML_MESH_MODEL": "4"})
    assert "train.epochs" in cfg.applied
    assert "mesh.model" in cfg.applied
    assert "train.batch_size" not in cfg.applied
    cfg2, _ = load_config(["--mesh.data=4"], env={})
    assert "mesh.data" in cfg2.applied  # the flag path still works


def test_dumps_roundtrip(tmp_path):
    cfg, _ = load_config(["--scenario.num_cars=100000"], env={})
    path = str(tmp_path / "out.json")
    open(path, "w").write(cfg.dumps())
    cfg2, _ = load_config([], env={}, path=path)
    assert cfg2.as_dict() == cfg.as_dict()
    assert cfg2.scenario.num_cars == 100_000
