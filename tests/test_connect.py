"""Connect runtime: file source tailing, digital-twin sink, Avro data lake,
offset resume across worker restarts."""

import json
import os

import numpy as np
import pytest

from iotml.connect import (ConnectWorker, DocumentStoreSink, FileStreamSource,
                           HoistFieldKey, ObjectStoreSink)
from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.ops.avro import AvroCodec
from iotml.ops.avro_container import ContainerWriter, read_container
from iotml.stream.broker import Broker


def _write_lines(path, lines, header=None):
    with open(path, "w") as fh:
        if header:
            fh.write(header + "\n")
        for l in lines:
            fh.write(l + "\n")


def test_file_stream_source_replays_and_tails(tmp_path):
    path = str(tmp_path / "data.csv")
    _write_lines(path, ["r1", "r2"], header="h")
    broker = Broker()
    w = ConnectWorker(broker)
    w.add_source("csv", FileStreamSource(path, "car-data-csv",
                                         skip_header=True))
    counts = w.run_once()
    assert counts["csv"] == 2
    # appended lines flow on the next pass (tail semantics)
    with open(path, "a") as fh:
        fh.write("r3\n")
    assert w.run_once()["csv"] == 1
    msgs = broker.fetch("car-data-csv", 0, 0)
    assert [m.value for m in msgs] == [b"r1", b"r2", b"r3"]


def test_document_store_sink_digital_twin(tmp_path):
    """Latest state per car, keyed by the hoisted MQTT-topic-derived key —
    the MongoDB digital-twin contract."""
    store_path = str(tmp_path / "twin.json")
    broker = Broker()
    broker.create_topic("sensor-data", partitions=2)
    for i, (car, speed) in enumerate([("car-1", 10), ("car-2", 20),
                                      ("car-1", 30)]):
        broker.produce("sensor-data", json.dumps({"speed": speed}).encode(),
                       key=car.encode())
    w = ConnectWorker(broker)
    sink = DocumentStoreSink(store_path)
    w.add_sink("mongo", sink, ["sensor-data"], transforms=[HoistFieldKey()])
    w.run_once()
    assert sink.count() == 2
    assert sink.find_one("car-1")["speed"] == 30  # upsert: latest wins
    assert sink.find_one("car-2")["_id"] == "car-2"
    # persisted; a fresh sink reloads the twin
    assert DocumentStoreSink(store_path).find_one("car-1")["speed"] == 30


def test_object_store_sink_avro_lake(tmp_path):
    """Framed Avro topic → .avro container files, readable back with the
    schema intact (GCS sink parity)."""
    broker = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=10))
    gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=30)  # 300 records
    lake = str(tmp_path / "lake")
    w = ConnectWorker(broker)
    sink = ObjectStoreSink(lake, KSQL_CAR_SCHEMA, flush_size=120)
    w.add_sink("gcs", sink, ["SENSOR_DATA_S_AVRO"])
    w.run_once()
    files = sorted(os.listdir(lake))
    assert files and all(f.endswith(".avro") for f in files)
    # object naming: <topic>+<partition>+<startoffset>.avro
    assert files[0] == "SENSOR_DATA_S_AVRO+0+0000000000.avro"
    total = []
    for f in files:
        schema, records = read_container(os.path.join(lake, f))
        assert schema.field_names == KSQL_CAR_SCHEMA.field_names
        total.extend(records)
    assert len(total) == 300
    assert all(isinstance(r["SPEED"], float) for r in total[:5])


def test_container_roundtrip_dicts(tmp_path):
    path = str(tmp_path / "x.avro")
    codec_fields = KSQL_CAR_SCHEMA.fields
    recs = [{f.name: (float(i) if f.avro_type == "double" else
                      i if f.avro_type == "int" else "false")
             for f in codec_fields} for i in range(7)]
    with ContainerWriter(path, KSQL_CAR_SCHEMA) as w:
        w.write_block(recs[:4])
        w.write_block(recs[4:])
    schema, got = read_container(path)
    assert got == recs


def test_sink_resumes_from_committed_offsets():
    broker = Broker()
    broker.create_topic("t")
    broker.produce("t", json.dumps({"a": 1}).encode(), key=b"k1")
    w = ConnectWorker(broker)
    sink = DocumentStoreSink()
    w.add_sink("s", sink, ["t"])
    assert w.run_once()["s"] == 1
    # restart: a new worker+sink resumes after the commit, not from 0
    broker.produce("t", json.dumps({"a": 2}).encode(), key=b"k2")
    w2 = ConnectWorker(broker)
    sink2 = DocumentStoreSink()
    w2.add_sink("s", sink2, ["t"])
    assert w2.run_once()["s"] == 1
    assert sink2.count() == 1 and sink2.find_one("k2")["a"] == 2


def test_csv_fixture_to_training_slice(tmp_path):
    """The reference's offline fixture chain: CSV file → FileStreamSource →
    topic → KSQL-equivalent CSV→Avro → training batches (reference
    test_file_source_and _testdata.sh:41-66)."""
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import write_csv_fixture
    from iotml.stream.consumer import StreamConsumer
    from iotml.streamproc.tasks import DelimitedToAvro

    path = str(tmp_path / "car-sensor-data.csv")
    write_csv_fixture(path, n_rows=50)
    broker = Broker()
    w = ConnectWorker(broker)
    w.add_source("csv", FileStreamSource(path, "car-data-csv",
                                         skip_header=True))
    w.run_once()
    task = DelimitedToAvro(broker, src="car-data-csv",
                           dst="SENSOR_DATA_S_AVRO")
    assert task.process_available() == 50
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"], group="g")
    batches = list(SensorBatches(consumer, batch_size=25))
    assert sum(b.n_valid for b in batches) == 50
    assert batches[0].x.shape == (25, 18)
