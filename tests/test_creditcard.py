"""Creditcard workflow: CSV-line topic parity, scaler math, end-to-end AUC."""

import numpy as np
import pytest

from iotml.cli.creditcard import run as creditcard_run
from iotml.data.creditcard import (COLUMNS, N_FEATURES, CreditcardBatches,
                                   StandardScaler, decode_csv_batch,
                                   produce_csv_lines, synth_creditcard_csv)
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer


def test_synth_csv_shape(tmp_path):
    path = str(tmp_path / "cc.csv")
    n_fraud = synth_creditcard_csv(path, n_rows=200, fraud_rate=0.1, seed=1)
    lines = open(path).read().splitlines()
    assert len(lines) == 201
    assert lines[0].replace('"', "").split(",") == COLUMNS
    assert 0 < n_fraud < 60
    # label column consistent with returned count
    labels = [int(l.rsplit(",", 1)[1]) for l in lines[1:]]
    assert sum(labels) == n_fraud


def test_produce_and_decode_parity(tmp_path):
    path = str(tmp_path / "cc.csv")
    synth_creditcard_csv(path, n_rows=50, seed=2)
    broker = Broker()
    n = produce_csv_lines(broker, "creditcard", path)
    assert n == 50
    msgs = StreamConsumer(broker, ["creditcard:0:0"], group="g").poll(100)
    assert len(msgs) == 50
    # messages are the raw CSV lines (reference producer parity)
    assert msgs[0].value.decode() == open(path).read().splitlines()[1]
    x, y = decode_csv_batch([m.value for m in msgs])
    assert x.shape == (50, N_FEATURES) and y.shape == (50,)
    # manual check of row 0 against the file
    row0 = [float(v) for v in msgs[0].value.decode().split(",")]
    np.testing.assert_allclose(x[0], row0[:30], rtol=1e-6)
    assert y[0] == int(row0[30])


def test_standard_scaler_matches_batch_fit():
    rng = np.random.default_rng(0)
    x = rng.normal(3.0, 2.5, (500, 4))
    full = StandardScaler().fit(x)
    inc = StandardScaler()
    for chunk in np.array_split(x, 7):
        inc.partial_fit(chunk)
    np.testing.assert_allclose(inc.mean, full.mean, rtol=1e-10)
    np.testing.assert_allclose(inc.std, full.std, rtol=1e-10)
    np.testing.assert_allclose(full.mean, x.mean(axis=0), rtol=1e-10)
    t = full.transform(x)
    np.testing.assert_allclose(t.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(t.std(axis=0), 1.0, atol=1e-4)


def test_batches_filter_and_padding(tmp_path):
    path = str(tmp_path / "cc.csv")
    synth_creditcard_csv(path, n_rows=70, fraud_rate=0.2, seed=3)
    broker = Broker()
    produce_csv_lines(broker, "cc", path)
    batches = list(CreditcardBatches(
        StreamConsumer(broker, ["cc:0:0"], group="g"),
        batch_size=32, only_normal=True))
    assert all(b.x.shape == (32, 30) for b in batches)
    assert all((b.labels[: b.n_valid] == 0).all() for b in batches)
    tail = batches[-1]
    assert (tail.x[tail.n_valid:] == 0).all()
    # two iterations give identical epochs (KafkaDataset re-read semantics)
    again = list(CreditcardBatches(
        StreamConsumer(broker, ["cc:0:0"], group="g2"),
        batch_size=32, only_normal=True))
    np.testing.assert_array_equal(batches[0].x, again[0].x)


def test_end_to_end_cli_auc():
    out = creditcard_run(["synth:600", "--epochs", "8"])
    assert out["records"] == 600
    rep = out["report"]
    # synthetic frauds are 3-5σ off-manifold: a trained AE must separate them
    assert rep["roc_auc"] > 0.9
    assert rep["mean_error_anomaly"] > rep["mean_error_normal"]
    assert rep["confusion"]["tp"] + rep["confusion"]["fn"] > 0
