"""Generated Grafana dashboards stay in sync with the metric registry."""

import json

from iotml.obs import metrics as m
from iotml.obs.dashboards import dashboard_configmap, generate_dashboard


def test_dashboard_covers_all_registered_metrics():
    dash = generate_dashboard()
    exprs = " ".join(t["expr"] for p in dash["panels"] for t in p["targets"])
    for name in ("iotml_records_consumed_total", "iotml_records_trained_total",
                 "iotml_records_scored_total", "iotml_train_step_seconds",
                 "iotml_reconstruction_mse"):
        assert name in exprs
    assert len(dash["panels"]) == len(m.default_registry._metrics)
    # counters rate()d, gauges raw, histograms averaged
    assert any("rate(iotml_records_trained_total[1m]" in e
               for e in exprs.split()) or "rate(iotml_records_trained_total[1m])" in exprs
    assert "iotml_reconstruction_mse" in exprs
    assert "rate(iotml_train_step_seconds_sum[1m])" in exprs


def test_new_metric_gets_a_panel():
    reg = m.Registry()
    reg.counter("my_thing_total", "things done")
    reg.gauge("my_level", "current level")
    dash = generate_dashboard("t", registry=reg)
    titles = [p["title"] for p in dash["panels"]]
    assert "things done" in titles and "current level" in titles


def test_configmap_shape():
    doc = json.loads(dashboard_configmap())
    assert doc["kind"] == "ConfigMap"
    assert doc["metadata"]["labels"]["grafana_dashboard"] == "1"
    inner = json.loads(doc["data"]["iotml.json"])
    assert inner["schemaVersion"] == 16 and inner["panels"]


def test_family_dashboards_mirror_reference_split():
    """The reference ships hivemq.json (broker) + devsim.json (agents); the
    generated ConfigMap carries those families plus the ml view."""
    from iotml.mqtt.broker import MqttBroker

    MqttBroker()  # registers the mqtt_* family in the default registry
    doc = json.loads(dashboard_configmap())
    assert "iotml.json" in doc["data"]
    assert "iotml-broker.json" in doc["data"]
    assert "iotml-ml.json" in doc["data"]
    broker_dash = json.loads(doc["data"]["iotml-broker.json"])
    titles = {p["targets"][0]["expr"] for p in broker_dash["panels"]}
    assert any("mqtt_" in t for t in titles)
    assert not any("iotml_records" in t for t in titles)  # families disjoint

    ml = generate_dashboard(family="ml")
    assert all("iotml_" in p["targets"][0]["expr"] for p in ml["panels"])


def test_live_family_charts_the_continuous_loop():
    """The continuous-learning services' metrics (trainer rounds/loss,
    scorer hot-swaps, live quality) and the car-health family get their
    own dashboard — the round-4 gap where the live loop was stdout-only."""
    from iotml.serve.carhealth import CarHealthDetector

    CarHealthDetector()  # registers car_health_* in the default registry
    live = generate_dashboard(family="live")
    exprs = {p["targets"][0]["expr"] for p in live["panels"]}
    for needle in ("live_train_rounds_total", "live_train_loss",
                   "live_model_updates_total", "live_detection_precision",
                   "car_health_alerts_active"):
        assert any(needle in e for e in exprs), (needle, exprs)
    doc = json.loads(dashboard_configmap())
    assert "iotml-live.json" in doc["data"]
