"""Zero-copy columnar data plane (ISSUE 10): raw frame batches, the ONE
frame decoder, byte-parity against the python codec oracle, the v1
runtime guard, replay==live decoder sharing, and the zero-per-record
allocation contract."""

import gc
import tracemalloc

import numpy as np
import pytest

from iotml.core.schema import (CAR_SCHEMA_V2_ID, KSQL_CAR_SCHEMA,
                               KSQL_CAR_SCHEMA_V2)
from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.ops import framing
from iotml.ops.avro import AvroCodec
from iotml.store import segment as seg
from iotml.stream.broker import Broker, SchemaIdMismatchError
from iotml.stream.consumer import StreamConsumer
from iotml.stream import native as native_mod

NATIVE = native_mod.available()
needs_native = pytest.mark.skipif(not NATIVE,
                                  reason="C++ engine not built")

CODEC = AvroCodec(KSQL_CAR_SCHEMA)
V2_CODEC = AvroCodec(KSQL_CAR_SCHEMA_V2)


def _record(rng, label="false", with_nulls=False, nan_field=None):
    rec = {}
    for f in KSQL_CAR_SCHEMA.fields:
        if f.name == "FAILURE_OCCURRED":
            rec[f.name] = label
        elif f.avro_type in ("int", "long"):
            rec[f.name] = int(rng.integers(0, 40))
        else:
            rec[f.name] = float(rng.normal())
    if with_nulls:
        rec["SPEED"] = None
        rec["COOLANT_TEMP"] = None
    if nan_field:
        rec[nan_field] = float("nan")
    return rec


def _v2_record(rng, region="eu-west", label="true"):
    rec = _record(rng, label=label)
    rec["REGION"] = region
    return rec


def _seeded_frames(rng, n=64, base_offset=0, schema_id=1,
                   tombstone_at=(), v2_at=(), keyfn=None):
    """Seeded store frames: v1 payloads with nulls/NaN sprinkled in,
    optional tombstones and v2 (evolved-writer) frames."""
    frames, truth = [], []
    off = base_offset
    for i in range(n):
        key = (keyfn(i) if keyfn else f"car-{i % 7}".encode())
        if i in tombstone_at:
            frames.append(seg.encode_record(off, key, None, 1000 + i,
                                            None))
            truth.append(("tombstone", None))
        elif i in v2_at:
            rec = _v2_record(rng)
            payload = framing.frame(V2_CODEC.encode(rec),
                                    CAR_SCHEMA_V2_ID)
            frames.append(seg.encode_record(off, key, payload, 1000 + i,
                                            None))
            truth.append(("v2", rec))
        else:
            rec = _record(rng, label=("true" if i % 9 == 0 else "false"),
                          with_nulls=(i % 11 == 0),
                          nan_field="THROTTLE_POS" if i % 13 == 0
                          else None)
            payload = framing.frame(CODEC.encode(rec), schema_id)
            frames.append(seg.encode_record(off, key, payload, 1000 + i,
                                            None))
            truth.append(("v1", rec))
        off += 1
    return b"".join(frames), truth


# --------------------------------------------------------- parity oracle
@needs_native
def test_frame_decoder_matches_python_oracle_bit_exact():
    """Native columnar decode == the pure-python oracle, bit for bit —
    values (incl. NaN and nulls), labels, keys, cursor, stop flags and
    tombstone skips, over seeded chunks with a v1/v2 mix."""
    rng = np.random.default_rng(7)
    buf, _ = _seeded_frames(rng, n=96, base_offset=5,
                            tombstone_at={10, 40}, v2_at={77})
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    dec = nc.frame_decoder()
    for start in (5, 9, 30):
        x = np.zeros((256, nc.n_numeric), np.float32)
        lab = np.zeros((256, nc.n_strings), f"S{native_mod.LABEL_STRIDE}")
        keys = np.zeros((256,), f"S{native_mod.KEY_STRIDE}")
        rows, nxt, flags, skipped = dec.decode_into(buf, start, x, lab,
                                                    keys)
        onum, olab, okeys, onext, oflags, oskip = \
            framing.decode_frames_columnar_py(
                buf, start, KSQL_CAR_SCHEMA, with_keys=True,
                label_stride=native_mod.LABEL_STRIDE,
                key_stride=native_mod.KEY_STRIDE)
        assert (rows, nxt, flags, skipped) == \
            (onum.shape[0], onext, oflags, oskip)
        assert flags & framing.FRAMES_STOP_SCHEMA  # parked at the v2 frame
        assert np.array_equal(x[:rows], onum, equal_nan=True)
        assert np.array_equal(lab[:rows], olab)
        assert np.array_equal(keys[:rows], okeys)


@needs_native
def test_frame_decoder_matches_full_python_codec():
    """Ground truth: the columnar float32 output equals the v1 python
    codec's float64 decode cast to float32 (single rounding both ways)."""
    rng = np.random.default_rng(11)
    buf, truth = _seeded_frames(rng, n=50)
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    dec = nc.frame_decoder()
    x = np.zeros((64, nc.n_numeric), np.float32)
    lab = np.zeros((64, nc.n_strings), f"S{native_mod.LABEL_STRIDE}")
    rows, _, _, _ = dec.decode_into(buf, 0, x, lab)
    assert rows == 50
    payloads = []
    for _pos, _end, _off, _key, value, _ts, _h in seg.scan_records(buf):
        payloads.append(framing.strip_frame(value))
    cols = CODEC.decode_batch(payloads)
    want = CODEC.sensor_matrix(cols).astype(np.float32)
    assert np.array_equal(x[:rows], want, equal_nan=True)
    labels = [("" if r["FAILURE_OCCURRED"] is None
               else r["FAILURE_OCCURRED"]) for _k, r in truth]
    col = [f.name for f in KSQL_CAR_SCHEMA.fields
           if f.avro_type == "string"].index("FAILURE_OCCURRED")
    got = [s.decode() for s in lab[:rows, col]]
    assert got == labels


@needs_native
def test_torn_tail_ends_batch_like_recovery():
    rng = np.random.default_rng(3)
    buf, _ = _seeded_frames(rng, n=20)
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    dec = nc.frame_decoder()
    x = np.zeros((32, nc.n_numeric), np.float32)
    lab = np.zeros((32, nc.n_strings), f"S{native_mod.LABEL_STRIDE}")
    cut = buf[: int(len(buf) * 0.6)]
    rows, nxt, flags, _ = dec.decode_into(cut, 0, x, lab)
    o = framing.decode_frames_columnar_py(cut, 0, KSQL_CAR_SCHEMA)
    assert (rows, nxt, flags) == (o[0].shape[0], o[3], o[4])
    assert flags & framing.FRAMES_STOP_TORN
    assert 0 < rows < 20


# ------------------------------------------------ end-to-end batch parity
def _fill(broker, n_ticks=40, num_cars=25, failure_rate=0.08):
    gen = FleetGenerator(FleetScenario(num_cars=num_cars,
                                      failure_rate=failure_rate))
    return gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=n_ticks)


def _batches(broker, force_python=False, **kw):
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                              group=kw.pop("group", "g"))
    sb = SensorBatches(consumer, batch_size=100, keep_labels=True,
                       keep_keys=True, **kw)
    if force_python:
        sb._native = None  # the pure codec is the oracle
        sb._ring = False
    return list(sb), sb


@needs_native
def test_columnar_batches_equal_python_codec_batches(tmp_path):
    """The acceptance oracle: columnar-native over a durable broker ==
    the pure-python codec path over the same records — values, labels,
    keys, batch boundaries."""
    broker = Broker(store_dir=str(tmp_path / "store"))
    _fill(broker)
    cols, sb = _batches(broker, group="columnar")
    assert isinstance(sb._ring, object) and sb._ring not in (None, False)
    pys, _ = _batches(broker, force_python=True, group="python")
    assert len(cols) == len(pys) and len(cols) > 5
    for a, b in zip(cols, pys):
        assert a.n_valid == b.n_valid
        assert np.array_equal(a.x, b.x, equal_nan=True)
        assert list(a.labels) == list(b.labels)
        assert np.array_equal(a.keys, b.keys)
    broker.close()


@needs_native
def test_columnar_skips_tombstones(tmp_path):
    broker = Broker(store_dir=str(tmp_path / "store"))
    rng = np.random.default_rng(5)
    for i in range(30):
        broker.produce("SENSOR_DATA_S_AVRO",
                       framing.frame(CODEC.encode(_record(rng)), 1),
                       key=b"car-1", timestamp_ms=i)
    broker.produce("SENSOR_DATA_S_AVRO", None, key=b"car-1",
                   timestamp_ms=31)  # tombstone mid-stream
    for i in range(10):
        broker.produce("SENSOR_DATA_S_AVRO",
                       framing.frame(CODEC.encode(_record(rng)), 1),
                       key=b"car-2", timestamp_ms=40 + i)
    batches, sb = _batches(broker, pad_tail=True)
    assert sb._ring not in (None, False)
    assert sum(b.n_valid for b in batches) == 40  # tombstone skipped
    broker.close()


# --------------------------------------------------------- the v1 guard
@needs_native
def test_v2_writer_never_misread_on_columnar_path(tmp_path):
    """A v2 (evolved) writer's frames on the topic: the columnar path
    must detour those chunks through name resolution — labels stay
    labels (REGION never read positionally as FAILURE_OCCURRED)."""
    broker = Broker(store_dir=str(tmp_path / "store"))
    rng = np.random.default_rng(9)
    labels = []
    for i in range(260):
        if 100 <= i < 140:  # a rolling-upgrade window of v2 frames
            rec = _v2_record(rng, label="true" if i % 2 else "false")
            payload = framing.frame(V2_CODEC.encode(rec),
                                    CAR_SCHEMA_V2_ID)
            labels.append(rec["FAILURE_OCCURRED"])
        else:
            rec = _record(rng, label="true" if i % 5 == 0 else "false")
            payload = framing.frame(CODEC.encode(rec), 1)
            labels.append(rec["FAILURE_OCCURRED"])
        broker.produce("SENSOR_DATA_S_AVRO", payload, key=b"car",
                       timestamp_ms=i)
    batches, sb = _batches(broker)
    assert sb._ring not in (None, False)
    got = [lab for b in batches for lab in b.labels[: b.n_valid]]
    assert got == labels  # the v1 read would have seen "eu-west" here
    assert sum(b.n_valid for b in batches) == 260
    broker.close()


@needs_native
def test_v2_guard_fused_wire_path(tmp_path):
    """The fused NativeKafkaBroker.fetch_decode path raises
    SchemaIdMismatchError at an evolved frame instead of blind-stripping
    it, and SensorBatches decodes the mixed topic correctly anyway."""
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.native_kafka import NativeKafkaBroker

    broker = Broker()
    rng = np.random.default_rng(13)
    labels = []
    for i in range(60):
        if 20 <= i < 30:
            rec = _v2_record(rng, label="true")
            payload = framing.frame(V2_CODEC.encode(rec),
                                    CAR_SCHEMA_V2_ID)
        else:
            rec = _record(rng, label="false")
            payload = framing.frame(CODEC.encode(rec), 1)
        labels.append(rec["FAILURE_OCCURRED"])
        broker.produce("SENSOR_DATA_S_AVRO", payload, timestamp_ms=i)
    with KafkaWireServer(broker) as srv:
        nb = NativeKafkaBroker(f"127.0.0.1:{srv.port}")
        nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
        with pytest.raises(SchemaIdMismatchError):
            # from offset 20 the first frame is evolved: the guard trips
            nb.fetch_decode("SENSOR_DATA_S_AVRO", 0, 20, nc, strip=5)
        # a fetch below decodes only the verified prefix
        num, _lab, nxt = nb.fetch_decode("SENSOR_DATA_S_AVRO", 0, 0, nc,
                                         strip=5)
        assert len(num) == 20 and nxt == 20
        consumer = StreamConsumer(nb, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group="wire")
        sb = SensorBatches(consumer, batch_size=10, keep_labels=True)
        got = [lab for b in sb for lab in b.labels[: b.n_valid]]
        assert got == labels
        nb.close()


# ------------------------------------------- replay == live, ONE decoder
@needs_native
def test_replay_and_live_share_one_decoder(tmp_path, monkeypatch):
    """Timestamp-replay backfill and live consume produce identical
    batches AND both enter through FrameDecoder.decode_into — the one
    decode entry point (counted via monkeypatch)."""
    broker = Broker(store_dir=str(tmp_path / "store"))
    rng = np.random.default_rng(17)
    for i in range(300):
        broker.produce("SENSOR_DATA_S_AVRO",
                       framing.frame(CODEC.encode(_record(rng)), 1),
                       key=b"car", timestamp_ms=1_000 + i)
    calls = []
    orig = native_mod.FrameDecoder.decode_into

    def counted(self, *a, **kw):
        calls.append(1)
        return orig(self, *a, **kw)

    monkeypatch.setattr(native_mod.FrameDecoder, "decode_into", counted)

    live_consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                                   group="live")
    live = list(SensorBatches(live_consumer, batch_size=50))
    live_calls = len(calls)
    assert live_calls > 0

    replay_consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                                     group="replay")
    replay_consumer.seek_to_timestamp(1_100)  # backfill from mid-stream
    replay = list(SensorBatches(replay_consumer, batch_size=50))
    assert len(calls) > live_calls  # replay used the SAME entry point

    # replay batches == the live batches past the timestamp cut
    live_rows = np.concatenate([b.x[: b.n_valid] for b in live])
    replay_rows = np.concatenate([b.x[: b.n_valid] for b in replay])
    assert np.array_equal(replay_rows, live_rows[100:], equal_nan=True)
    broker.close()


# ------------------------------------------ zero per-record allocations
@needs_native
def test_zero_per_record_python_objects_on_fast_path(tmp_path):
    """Allocation counting: decoding 16x more records through the
    columnar fast path must NOT allocate ~16x more Python objects —
    the per-chunk cost is O(1) buffers, never per-record objects."""
    broker = Broker(store_dir=str(tmp_path / "store"))
    rng = np.random.default_rng(23)
    for i in range(2048):
        broker.produce("SENSOR_DATA_S_AVRO",
                       framing.frame(CODEC.encode(_record(rng)), 1),
                       key=b"car", timestamp_ms=i)
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    dec = nc.frame_decoder()
    x = np.zeros((2048, nc.n_numeric), np.float32)
    lab = np.zeros((2048, nc.n_strings), f"S{native_mod.LABEL_STRIDE}")

    def count_allocs(rows):
        consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group=f"alloc-{rows}")
        consumer.poll_into(dec, x, lab, max_rows=8)  # warm caches
        gc.collect()
        tracemalloc.start()
        got, _ = consumer.poll_into(dec, x, lab, max_rows=rows)
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
        assert got == rows
        return sum(s.count for s in snap.statistics("filename"))

    small = count_allocs(128)
    big = count_allocs(2040)
    # 16x the records must stay within ~2x the allocations (noise), not
    # scale linearly: the fast path holds zero per-record objects
    assert big < small * 2 + 64, (small, big)
    broker.close()


@needs_native
def test_traced_sessions_keep_the_header_path_in_process(tmp_path,
                                                         monkeypatch):
    """Record headers (the trace carrier) only exist on the in-process
    broker and the columnar path never materialises them: with tracing
    ON, a durable in-process consumer must stay on the message path so
    the span-log invariants (chaos/obs) keep their 'consume' spans."""
    from iotml.obs import tracing

    broker = Broker(store_dir=str(tmp_path / "store"))
    _fill(broker, n_ticks=4)
    monkeypatch.setattr(tracing, "ENABLED", True)
    _, sb = _batches(broker, group="traced")
    assert sb._ring in (None, False)  # columnar declined, headers flow
    monkeypatch.setattr(tracing, "ENABLED", False)
    _, sb2 = _batches(broker, group="untraced")
    assert sb2._ring not in (None, False)
    broker.close()


# ------------------------------------------------- raw fetch + the wire
def test_fetch_raw_contract_in_memory_and_durable(tmp_path):
    rng = np.random.default_rng(29)
    for durable in (False, True):
        broker = Broker(store_dir=str(tmp_path / "s2") if durable
                        else None)
        broker.create_topic("T", retention_messages=None)
        for i in range(20):
            broker.produce(
                "T", framing.frame(CODEC.encode(_record(rng)), 1),
                key=b"k", timestamp_ms=i)
        raw = broker.fetch_raw("T", 0, 0)
        assert raw is not None and raw.start_offset == 0
        # the returned bytes are REAL store frames: the one parser
        # (store.segment) walks them
        offs = [off for _p, _e, off, _k, _v, _t, _h
                in seg.scan_records(raw.data)]
        assert offs[0] == 0 and len(offs) == 20
        assert broker.fetch_raw("T", 0, 20) is None  # log end
        broker.close()


def test_fetch_raw_wire_out_of_range():
    from iotml.stream.broker import OffsetOutOfRangeError
    from iotml.stream.kafka_wire import KafkaWireBroker, KafkaWireServer

    broker = Broker()
    broker.create_topic("T", retention_messages=5)
    rng = np.random.default_rng(31)
    for i in range(20):
        broker.produce("T", framing.frame(CODEC.encode(_record(rng)), 1),
                       timestamp_ms=i)
    with KafkaWireServer(broker) as srv:
        wb = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        with pytest.raises(OffsetOutOfRangeError) as ei:
            wb.fetch_raw("T", 0, 0)
        assert ei.value.earliest == 15
        raw = wb.fetch_raw("T", 0, 15)
        assert raw is not None and raw.start_offset == 15
        offs = [off for _p, _e, off, _k, _v, _t, _h
                in seg.scan_records(raw.data)]
        assert offs == list(range(15, 20))
        wb.close()


@needs_native
def test_poll_into_autoresets_after_retention_trim():
    """A columnar cursor stranded below the retained base auto-resets
    to earliest AND still returns data in the same poll (a trim must
    not read as a phantom end-of-stream)."""
    rng = np.random.default_rng(37)
    broker = Broker()
    broker.create_topic("T", retention_messages=8)
    for i in range(30):
        broker.produce("T", framing.frame(CODEC.encode(_record(rng)), 1),
                       timestamp_ms=i)
    assert broker.begin_offset("T", 0) == 22
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    dec = nc.frame_decoder()
    consumer = StreamConsumer(broker, ["T:0:0"], group="trim")
    x = np.zeros((64, nc.n_numeric), np.float32)
    lab = np.zeros((64, nc.n_strings), f"S{native_mod.LABEL_STRIDE}")
    rows, fb = consumer.poll_into(dec, x, lab)
    assert rows == 8 and not fb
    assert consumer.positions() == [("T", 0, 30)]


@needs_native
def test_tombstone_at_cursor_on_fused_wire_path():
    """A tombstone (value=None) at the cursor trips the fused path's
    guard; the message-path fallback must SKIP it (delete markers have
    no payload), never crash on len(None)."""
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.native_kafka import NativeKafkaBroker

    rng = np.random.default_rng(41)
    broker = Broker()
    for i in range(15):
        broker.produce("SENSOR_DATA_S_AVRO",
                       framing.frame(CODEC.encode(_record(rng)), 1),
                       key=b"car-1", timestamp_ms=i)
    broker.produce("SENSOR_DATA_S_AVRO", None, key=b"car-1",
                   timestamp_ms=16)
    for i in range(10):
        broker.produce("SENSOR_DATA_S_AVRO",
                       framing.frame(CODEC.encode(_record(rng)), 1),
                       key=b"car-2", timestamp_ms=20 + i)
    with KafkaWireServer(broker) as srv:
        nb = NativeKafkaBroker(f"127.0.0.1:{srv.port}")
        consumer = StreamConsumer(nb, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group="tomb")
        batches = list(SensorBatches(consumer, batch_size=10,
                                     keep_keys=True))
        assert sum(b.n_valid for b in batches) == 25
        nb.close()


def test_relay_server_without_raw_downgrades_cleanly():
    """A wire server whose backing broker RAISES NotImplementedError
    from fetch_raw (a relay to a pre-extension upstream) must answer
    UNSUPPORTED_VERSION — the client pins back to classic FETCH and the
    pipeline keeps flowing."""
    from iotml.stream.kafka_wire import KafkaWireBroker, KafkaWireServer

    rng = np.random.default_rng(43)
    broker = Broker()
    for i in range(30):
        broker.produce("SENSOR_DATA_S_AVRO",
                       framing.frame(CODEC.encode(_record(rng)), 1),
                       timestamp_ms=i)

    class Relay:
        def __getattr__(self, name):
            return getattr(broker, name)

        def fetch_raw(self, *a, **kw):
            raise NotImplementedError("upstream lacks RAW_FETCH")

    with KafkaWireServer(Relay()) as srv:
        wb = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        with pytest.raises(NotImplementedError):
            wb.fetch_raw("SENSOR_DATA_S_AVRO", 0, 0)
        consumer = StreamConsumer(wb, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group="relay")
        batches = list(SensorBatches(consumer, batch_size=10))
        assert sum(b.n_valid for b in batches) == 30
        # the downgrade is remembered: no further RAW_FETCH round trips
        assert consumer._raw_unsupported is True
        wb.close()


def test_poll_into_none_without_raw_support():
    """A broker without fetch_raw keeps consumers on the legacy paths."""

    class NoRaw:
        pass

    consumer = StreamConsumer.__new__(StreamConsumer)
    consumer.broker = NoRaw()
    consumer._cursors = [["T", 0, 0]]
    consumer._rr = 0
    assert consumer.poll_into(None, None, None) is None


# ------------------------------------------------------- pipeline knobs
def test_pipeline_knobs_never_leak_into_config_tree():
    """IOTML_PREFETCH_DEPTH / IOTML_DECODE_RING_BUFFERS /
    IOTML_RAW_BATCH_BYTES are process toggles in config's non_config
    set: the resolver must neither reject them nor apply them."""
    from iotml.config import load_config

    cfg, _ = load_config(argv=[], env={
        "IOTML_PREFETCH_DEPTH": "3",
        "IOTML_DECODE_RING_BUFFERS": "8",
        "IOTML_RAW_BATCH_BYTES": "65536"})
    clean, _ = load_config(argv=[], env={})
    assert cfg.as_dict() == clean.as_dict()
    assert cfg.applied == set()


def test_pipeline_knob_validation(monkeypatch):
    from iotml.data import pipeline as pl

    monkeypatch.setenv("IOTML_PREFETCH_DEPTH", "4")
    monkeypatch.setenv("IOTML_DECODE_RING_BUFFERS", "2")
    monkeypatch.setenv("IOTML_RAW_BATCH_BYTES", "8192")
    assert pl.prefetch_depth() == 4
    assert pl.decode_ring_buffers() == 2
    assert pl.raw_batch_bytes() == 8192
    monkeypatch.setenv("IOTML_PREFETCH_DEPTH", "0")
    with pytest.raises(ValueError):
        pl.prefetch_depth()
    monkeypatch.setenv("IOTML_DECODE_RING_BUFFERS", "1")
    with pytest.raises(ValueError):
        pl.decode_ring_buffers()
    monkeypatch.setenv("IOTML_RAW_BATCH_BYTES", "nope")
    with pytest.raises(ValueError):
        pl.raw_batch_bytes()


@needs_native
def test_minimal_ring_still_correct(tmp_path, monkeypatch):
    """ring=2 (the minimum) must not corrupt carried tails: batch
    parity against the python path holds at every ring size."""
    monkeypatch.setenv("IOTML_DECODE_RING_BUFFERS", "2")
    monkeypatch.setenv("IOTML_RAW_BATCH_BYTES", "16384")  # small fetches
    broker = Broker(store_dir=str(tmp_path / "store"))
    _fill(broker, n_ticks=30)
    cols, sb = _batches(broker, group="ring2", poll_chunk=37)
    assert sb._ring not in (None, False) and len(sb._ring) == 2
    pys, _ = _batches(broker, force_python=True, group="ring2-py",
                      poll_chunk=37)
    assert len(cols) == len(pys)
    for a, b in zip(cols, pys):
        assert np.array_equal(a.x, b.x, equal_nan=True)
        assert np.array_equal(a.keys, b.keys)
    broker.close()


# ----------------------------------------------------------- lint (R14)
def test_r14_confines_frame_parsing():
    import os

    from iotml.analysis.lint import lint_file

    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "analysis", "bad_frame.py")
    findings = lint_file(fixture, "fixtures/bad_frame.py")
    r14 = [f for f in findings if f.rule == "R14"]
    # head struct + scan_records + encode_record + native-symbol call
    assert len(r14) >= 4
    # and the production tree is clean
    from iotml.analysis.lint import default_root, lint_paths

    tree = [f for f in lint_paths([default_root()], rules={"R14"})
            if f.rule == "R14"]
    assert tree == []
