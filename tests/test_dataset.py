"""Stream → fixed-shape batch pipeline (decode, filter, pad, window)."""

import numpy as np

from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer


def make_stream(num_cars=30, ticks=10, failure_rate=0.0, topic="SENSOR_DATA_S_AVRO"):
    broker = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=num_cars, failure_rate=failure_rate))
    n = gen.publish(broker, topic, n_ticks=ticks)
    consumer = StreamConsumer(broker, [f"{topic}:0:0"], group="test")
    return broker, consumer, n


def test_batches_fixed_shape_and_padding():
    _, consumer, n = make_stream(num_cars=30, ticks=10)  # 300 records
    batches = list(SensorBatches(consumer, batch_size=64))
    assert len(batches) == 5  # 4 full + 1 padded tail
    for b in batches[:-1]:
        assert b.x.shape == (64, 18) and b.n_valid == 64
    tail = batches[-1]
    assert tail.x.shape == (64, 18)
    assert tail.n_valid == 300 - 4 * 64
    assert np.all(tail.x[tail.n_valid:] == 0.0)
    assert tail.mask.sum() == tail.n_valid


def test_take_skip_and_indices():
    _, consumer, _ = make_stream(num_cars=50, ticks=10)  # 500 records
    bs = SensorBatches(consumer, batch_size=50, skip=2, take=3)
    batches = list(bs)
    assert len(batches) == 3
    # indices are post-skip (reference OutputCallback starts at 0 after the
    # skip slice, cardata-v3.py:243-249)
    assert [b.first_index for b in batches] == [0, 50, 100]


def test_skip_applies_once_across_drains():
    """A continuous scorer re-entering the iterator must not re-skip newly
    arrived data (skip targets the stream head only)."""
    broker, consumer, _ = make_stream(num_cars=50, ticks=2)  # 100 records
    bs = SensorBatches(consumer, batch_size=50, skip=1)
    first = list(bs)
    assert len(first) == 1  # one batch skipped, one emitted
    # more data arrives; drain again — nothing further may be skipped
    gen = FleetGenerator(FleetScenario(num_cars=50))
    gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=1)
    second = list(bs)
    assert sum(b.n_valid for b in second) == 50


def test_only_normal_filters_failures():
    _, consumer, _ = make_stream(num_cars=200, ticks=5, failure_rate=0.2)
    bs = SensorBatches(consumer, batch_size=32, only_normal=True, keep_labels=True)
    got = 0
    for b in bs:
        assert all(l == "false" for l in b.labels[: b.n_valid])
        got += b.n_valid
    assert 0 < got < 1000  # some rows filtered


def test_values_normalized_range():
    _, consumer, _ = make_stream(num_cars=20, ticks=5)
    for b in SensorBatches(consumer, batch_size=100):
        assert b.x.dtype == np.float32
        # normalized sensors live in ~[-1, 1]; zeroed cols exactly 0
        assert np.all(b.x[:, 0] == 0.0)
        assert np.all(np.abs(b.x[: b.n_valid, 1]) <= 1.0 + 1e-5)


def test_epoch_reread_is_deterministic():
    _, consumer, _ = make_stream(num_cars=30, ticks=4)
    bs = SensorBatches(consumer, batch_size=40)
    epochs = []
    for it in bs.epochs(2):
        epochs.append(np.concatenate([b.x[: b.n_valid] for b in it]))
    np.testing.assert_array_equal(epochs[0], epochs[1])


def test_windowed_batches_next_step_target():
    _, consumer, _ = make_stream(num_cars=10, ticks=30)  # 300 sequential records
    bs = SensorBatches(consumer, batch_size=8, window=4)
    b = next(iter(bs))
    assert b.x.shape == (8, 4, 18)
    assert b.y.shape == (8, 1, 18)
    # window shift=1: row k's window starts at record k; target = record k+4
    # => x[1,0] == x[0,1] (overlapping windows)
    np.testing.assert_array_equal(b.x[1, 0], b.x[0, 1])
    # => y[0] == x[4,3]? target of window 0 is record 4 == first row of window 4
    np.testing.assert_array_equal(b.y[0, 0], b.x[4, 0])


def test_ksql_schema_is_default():
    _, consumer, _ = make_stream(num_cars=5, ticks=2)
    bs = SensorBatches(consumer)
    assert bs.schema is KSQL_CAR_SCHEMA
