"""devsim CLI: the reference's `kubectl devsim` verbs (run/jobs/show/log/
abort/example, `kube-cli.sh:26-47`) over processes + a state directory."""

import json
import os
import subprocess
import sys
import time

import pytest

from iotml.cli import devsim

SCENARIOS = os.path.join(os.path.dirname(devsim.__file__), "..", "gen",
                         "scenarios")


@pytest.fixture(autouse=True)
def state_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "devsim-state")
    monkeypatch.setenv(devsim.STATE_DIR_ENV, d)
    return d


def test_run_evaluation_scenario_inproc(capsys):
    rc = devsim.main(["run", "-s",
                      os.path.join(SCENARIOS, "scenario_evaluation.xml")])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    # 25 cars × 40 msgs, and the shared-subscription consumer saw them all
    assert out["published"] == 1000
    assert sum(out["consumers"].values()) == 1000


def test_run_full_scenario_with_cap(capsys):
    rc = devsim.main(["run", "--cap", "50", "-s",
                      os.path.join(SCENARIOS, "scenario.xml")])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    # 50 cars × 3000 msgs each, fanned out to six shared consumers
    assert out["published"] == 150_000
    assert sum(out["consumers"].values()) == 150_000
    assert len(out["consumers"]) == 6


def test_example_prints_parseable_scenario(capsys):
    from iotml.mqtt.scenario import parse_scenario

    assert devsim.main(["example"]) == 0
    xml = capsys.readouterr().out
    scenario = parse_scenario(xml)
    assert list(scenario.client_groups.values())[0].count == 25


def test_detach_jobs_show_log_abort(capsys):
    # a detached job that runs long enough to abort: full scenario capped,
    # real-time-ish pacing via time-scale
    rc = devsim.main(["run", "--detach", "--cap", "5", "--time-scale", "0.5",
                      "-s", os.path.join(SCENARIOS, "scenario.xml")])
    assert rc == 0
    job = capsys.readouterr().out.strip()
    assert job.startswith("devsim-")

    assert devsim.main(["jobs"]) == 0
    assert job in capsys.readouterr().out

    assert devsim.main(["show", job]) == 0
    meta = json.loads(capsys.readouterr().out)
    assert meta["state"] in ("Running", "Completed")

    assert devsim.main(["abort", job]) == 0
    capsys.readouterr()
    deadline = time.time() + 5
    state = None
    while time.time() < deadline:
        devsim.main(["show", job])
        state = json.loads(capsys.readouterr().out)["state"]
        if state == "Aborted":
            break
        time.sleep(0.2)
    assert state == "Aborted"

    assert devsim.main(["log", job]) == 0  # log exists (may be empty)

    with pytest.raises(SystemExit):
        devsim.main(["show", "devsim-nope"])


def test_cli_entrypoint_runs_as_module():
    env = dict(os.environ)
    rc = subprocess.run(
        [sys.executable, "-m", "iotml.cli.devsim", "example"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(devsim.__file__)))
        + "/..", env=env, capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0
    assert "<scenario>" in rc.stdout
