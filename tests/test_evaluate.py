"""Anomaly-eval suite: hand-computed cases + sklearn cross-check."""

import jax
import numpy as np
import pytest

from iotml.evaluate import (auc, average_precision, confusion_at_threshold,
                            evaluate_detector, precision_recall_curve,
                            reconstruction_errors, roc_curve)
from iotml.models.autoencoder import CAR_AUTOENCODER


def test_confusion_hand_case():
    scores = np.array([0.1, 0.9, 0.4, 0.8, 0.2])
    labels = np.array([0, 1, 0, 0, 1])
    c = confusion_at_threshold(scores, labels, 0.5)
    # pred anomaly: idx 1 (label 1 → TP), idx 3 (label 0 → FP)
    # pred normal: idx 0, 2 (TN), idx 4 (label 1 → FN)
    assert (c["tp"], c["fp"], c["fn"], c["tn"]) == (1, 1, 1, 2)
    assert c["precision"] == 0.5 and c["recall"] == 0.5
    assert c["accuracy"] == pytest.approx(3 / 5)


def test_roc_perfect_and_random():
    labels = np.array([0, 0, 1, 1])
    fpr, tpr, _ = roc_curve(np.array([0.1, 0.2, 0.8, 0.9]), labels)
    assert auc(fpr, tpr) == pytest.approx(1.0)
    # anti-correlated scores → AUC 0
    fpr, tpr, _ = roc_curve(np.array([0.9, 0.8, 0.2, 0.1]), labels)
    assert auc(fpr, tpr) == pytest.approx(0.0)


def test_curves_match_sklearn():
    sk = pytest.importorskip("sklearn.metrics")
    rng = np.random.default_rng(7)
    labels = rng.integers(0, 2, 500)
    scores = rng.normal(0, 1, 500) + labels * 0.8  # informative but noisy
    scores[10] = scores[11]  # exercise tie handling

    fpr, tpr, _ = roc_curve(scores, labels)
    assert auc(fpr, tpr) == pytest.approx(
        sk.roc_auc_score(labels, scores), abs=1e-12)
    assert average_precision(scores, labels) == pytest.approx(
        sk.average_precision_score(labels, scores), abs=1e-12)

    prec, rec, _ = precision_recall_curve(scores, labels)
    sk_prec, sk_rec, _ = sk.precision_recall_curve(labels, scores)
    assert rec[-1] == 0.0 and prec[-1] == 1.0
    # identical realizable operating points
    ours = set(zip(np.round(prec, 12), np.round(rec, 12)))
    theirs = set(zip(np.round(sk_prec, 12), np.round(sk_rec, 12)))
    assert theirs <= ours


def test_reconstruction_errors_match_manual():
    import jax

    model = CAR_AUTOENCODER
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 18), np.float32))["params"]
    x = np.random.default_rng(0).uniform(-1, 1, (37, 18)).astype(np.float32)
    errs = reconstruction_errors(model, params, x, batch_size=16)
    pred = np.asarray(model.apply({"params": params}, x))
    manual = np.mean((pred - x) ** 2, axis=1)
    np.testing.assert_allclose(errs, manual, rtol=1e-5)


def test_evaluate_detector_report():
    import jax

    model = CAR_AUTOENCODER
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 18), np.float32))["params"]
    rng = np.random.default_rng(1)
    x_normal = rng.uniform(-0.2, 0.2, (64, 18)).astype(np.float32)
    x_anom = rng.uniform(-3, 3, (16, 18)).astype(np.float32)
    x = np.concatenate([x_normal, x_anom])
    labels = np.concatenate([np.zeros(64), np.ones(16)])
    rep = evaluate_detector(model, params, x, labels, threshold=0.5)
    assert rep.n == 80
    # an untrained AE still reconstructs small inputs better than wild ones
    assert rep.mean_error_anomaly > rep.mean_error_normal
    assert 0.0 <= rep.roc_auc <= 1.0
    assert "auc=" in rep.summary()
    assert rep.as_dict()["confusion"]["tp"] + rep.as_dict()["confusion"]["fn"] == 16


def test_write_report_persists_json_and_svg(tmp_path):
    """VERDICT r1: the eval numbers become an artifact an operator can
    open — report.json with curves, report.svg with ROC/PR/histogram —
    and the directory uploads through the ArtifactStore."""
    import json

    from iotml.evaluate.anomaly import evaluate_detector
    from iotml.evaluate.report import write_report
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.train.artifacts import ArtifactStore

    rng = np.random.default_rng(0)
    x = rng.normal(size=(300, 18)).astype(np.float32)
    labels = rng.random(300) < 0.1
    x[labels] *= 6.0  # anomalies reconstruct badly
    params = CAR_AUTOENCODER.init(jax.random.PRNGKey(0),
                                  x[:1])["params"]
    report = evaluate_detector(CAR_AUTOENCODER, params, x, labels,
                               threshold=5.0)
    from iotml.evaluate.anomaly import reconstruction_errors
    scores = np.asarray(reconstruction_errors(CAR_AUTOENCODER, params, x))

    store_root = str(tmp_path / "store")
    paths = write_report(report, scores, labels,
                         str(tmp_path / "report"),
                         store=ArtifactStore(store_root),
                         name="model-eval")
    data = json.loads(open(paths["json"]).read())
    assert data["n"] == 300
    assert 0.0 <= data["roc_auc"] <= 1.0
    assert len(data["curves"]["roc"]["fpr"]) > 2
    svg = open(paths["svg"]).read()
    assert svg.startswith("<?xml") and "svg" in svg[:300]
    # trees ship as zip blobs (ArtifactStore.upload_tree contract)
    assert (tmp_path / "store" / "model-eval.zip").is_file()
    assert paths["uploaded"]
