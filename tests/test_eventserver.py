"""Epoll MQTT listener (`MqttEventServer`) — protocol parity with the
threaded front, fleet-scale connection counts, and slow-consumer eviction.

The reference holds 100k MQTT clients on a 5-node HiveMQ cluster
(hivemq-crd.yaml:10-18, scenario.xml:13-14); this is the single-process
scale path standing in for that cluster."""

import json
import socket
import threading
import time

import pytest

from iotml.mqtt.broker import MqttBroker
from iotml.mqtt.bridge import KafkaBridge
from iotml.mqtt.eventserver import MqttEventServer
from iotml.mqtt.wire import (CONNACK, MqttClient, connect_packet,
                             publish_packet)
from iotml.stream.broker import Broker


def test_connect_pub_sub_roundtrip():
    broker = MqttBroker()
    with MqttEventServer(broker) as srv:
        got = []
        ev = threading.Event()

        def on_msg(topic, payload):
            got.append((topic, payload))
            ev.set()

        sub = MqttClient("127.0.0.1", srv.port, "sub-1", on_message=on_msg)
        sub.subscribe("vehicles/#", qos=0)
        pub = MqttClient("127.0.0.1", srv.port, "pub-1")
        pub.publish("vehicles/sensor/data/car-1", b"hello", qos=0)
        assert ev.wait(5)
        assert got == [("vehicles/sensor/data/car-1", b"hello")]
        pub.disconnect()
        sub.disconnect()


def test_qos1_puback_over_event_loop():
    broker = MqttBroker()
    with MqttEventServer(broker) as srv:
        c = MqttClient("127.0.0.1", srv.port, "q1")
        # publish() blocks on PUBACK; returning proves the ack round-trip
        c.publish("t/a", b"x", qos=1)
        c.disconnect()


def test_bridge_to_kafka_over_event_server():
    mqtt_broker = MqttBroker()
    stream = Broker()
    bridge = KafkaBridge(mqtt_broker, stream, partitions=2)
    with MqttEventServer(mqtt_broker) as srv:
        c = MqttClient("127.0.0.1", srv.port, "car-7")
        for i in range(10):
            c.publish(f"vehicles/sensor/data/car-7", json.dumps(
                {"seq": i}).encode(), qos=1)
        c.disconnect()
    assert bridge.forwarded() == 10
    total = sum(stream.end_offset("sensor-data", p) for p in range(2))
    assert total == 10


def _raw_publisher(port, client_id, n_msgs, topic, payload, barrier):
    """Minimal raw-socket qos0 publisher (no reader thread — the shape a
    10k-client fleet bench uses)."""
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.sendall(connect_packet(client_id))
    # read CONNACK (4 bytes: header, len=2, body)
    buf = b""
    while len(buf) < 4:
        buf += s.recv(4 - len(buf))
    assert buf[0] >> 4 == CONNACK
    barrier.wait()
    pkt = publish_packet(topic, payload, qos=0)
    for _ in range(n_msgs):
        s.sendall(pkt)
    return s


def test_many_connections_fanin():
    """Hundreds of concurrent sockets on one event loop, all bridged."""
    n_conns, per_conn = 200, 20
    mqtt_broker = MqttBroker()
    stream = Broker()
    bridge = KafkaBridge(mqtt_broker, stream, partitions=4)
    with MqttEventServer(mqtt_broker) as srv:
        barrier = threading.Barrier(n_conns)
        socks, threads, errors = [], [], []

        def run(i):
            try:
                socks.append(_raw_publisher(
                    srv.port, f"car-{i:05d}", per_conn,
                    f"vehicles/sensor/data/car-{i:05d}", b"{}", barrier))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        for i in range(n_conns):
            t = threading.Thread(target=run, args=(i,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=30)
        assert not errors
        deadline = time.time() + 30
        want = n_conns * per_conn
        while bridge.forwarded() < want and time.time() < deadline:
            time.sleep(0.05)
        assert bridge.forwarded() == want
        assert srv.connection_count == n_conns
        for s in socks:
            s.close()


def test_slow_consumer_evicted():
    """A subscriber that never reads gets its outbuf capped: the broker
    drops it instead of buffering unboundedly (HiveMQ overload-protection
    stance)."""
    mqtt_broker = MqttBroker()
    with MqttEventServer(mqtt_broker, max_outbuf=64 * 1024) as srv:
        # raw subscriber that never reads after SUBACK
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        s.sendall(connect_packet("sleepy"))
        buf = b""
        while len(buf) < 4:
            buf += s.recv(4 - len(buf))
        from iotml.mqtt.wire import subscribe_packet
        s.sendall(subscribe_packet(1, [("flood/#", 0)]))
        time.sleep(0.2)  # allow SUBACK processing
        # tiny kernel buffers so the 64 KiB cap is reachable quickly
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)

        pub = MqttClient("127.0.0.1", srv.port, "firehose")
        payload = b"z" * 8192
        for i in range(200):  # ~1.6 MB >> 64 KiB cap
            pub.publish("flood/x", payload, qos=0)
        deadline = time.time() + 10
        while time.time() < deadline:
            if "sleepy" not in mqtt_broker.session_ids():
                break
            time.sleep(0.05)
        assert "sleepy" not in mqtt_broker.session_ids(), \
            "stalled subscriber should have been evicted"
        # the broker itself is still healthy for other clients
        pub.ping()
        pub.disconnect()
        s.close()


def test_malformed_packet_kills_only_that_connection():
    """A truncated CONNECT body (IndexError territory) must drop that one
    client, not the event loop serving everyone else."""
    from iotml.mqtt.wire import packet as mk_packet

    broker = MqttBroker()
    with MqttEventServer(broker) as srv:
        bad = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        # CONNECT with body ending right after the protocol name
        bad.sendall(mk_packet(1, 0, b"\x00\x04MQTT"))
        # server should close us
        bad.settimeout(5)
        assert bad.recv(16) == b""
        bad.close()
        # the loop is still alive: a healthy client works end-to-end
        c = MqttClient("127.0.0.1", srv.port, "healthy")
        c.publish("t/x", b"ok", qos=1)
        c.ping()
        c.disconnect()


def test_rejected_connect_gets_connack_before_close():
    """Zero-byte client id with clean-session=0 must receive the CONNACK
    return code 0x02 before the FIN (spec §3.1.3-8), matching the threaded
    front."""
    s = socket.create_connection
    broker = MqttBroker()
    with MqttEventServer(broker) as srv:
        sock = s(("127.0.0.1", srv.port), timeout=5)
        sock.sendall(connect_packet("", clean=False))
        sock.settimeout(5)
        buf = b""
        while len(buf) < 4:
            chunk = sock.recv(4 - len(buf))
            if not chunk:
                break
            buf += chunk
        assert len(buf) == 4, "no CONNACK before close"
        assert buf[0] >> 4 == CONNACK
        assert buf[3] == 0x02  # v4 'identifier rejected'
        sock.close()


def test_publisher_backpressure_pause_resume():
    """Aggregate delivery backlog over the high watermark suspends reads
    from the feeding publisher (TCP backpressure — observable via
    paused_count); draining below the low watermark resumes it and every
    message still arrives exactly once."""
    from iotml.mqtt.wire import subscribe_packet

    broker = MqttBroker()
    # the kernel absorbs a few MB (tcp_wmem auto-tune) before the
    # app-level outbuf grows, so the flood must comfortably exceed that
    N, payload = 1500, b"z" * 16384
    with MqttEventServer(broker, max_outbuf=256 << 20,
                         high_watermark=2 << 20,
                         low_watermark=512 * 1024) as srv:
        # subscriber that STOPS reading after SUBACK: its server-side
        # outbuf is where the backlog accumulates.  The small receive
        # buffer must be set BEFORE connect — the TCP window scale is
        # negotiated at SYN time, and shrinking it afterwards wedges the
        # connection into zero-window-probe backoff
        sub = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sub.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        sub.settimeout(10)
        sub.connect(("127.0.0.1", srv.port))
        sub.sendall(connect_packet("stalled-sub"))
        buf = b""
        while len(buf) < 4:
            buf += sub.recv(4 - len(buf))
        sub.sendall(subscribe_packet(1, [("flood/#", 0)]))
        time.sleep(0.2)

        pub = MqttClient("127.0.0.1", srv.port, "firehose")

        def flood():
            try:
                for _ in range(N):
                    pub.publish("flood/x", payload, qos=0)
            except OSError:
                pass

        t = threading.Thread(target=flood, daemon=True)
        t.start()
        # the publisher must get read-suspended while the backlog is high
        deadline = time.time() + 30
        while srv.paused_count == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert srv.paused_count > 0, \
            "backpressure never engaged (pause is a no-op)"
        # drain the stalled subscriber → backlog sinks below the low
        # watermark → the publisher resumes and the flood completes
        sub.settimeout(30)
        drained = 0
        while t.is_alive() or srv.paused_count:
            try:
                chunk = sub.recv(1 << 16)
            except socket.timeout:
                break
            if not chunk:
                break
            drained += len(chunk)
        t.join(timeout=30)
        assert not t.is_alive(), "flood never completed after resume"
        # drop the stalled subscriber: its remaining backlog is discarded,
        # the watermark sinks, and the publisher must be readable again
        sub.close()
        deadline = time.time() + 30
        while srv.paused_count and time.time() < deadline:
            time.sleep(0.02)
        assert srv.paused_count == 0
        # reads really did resume: a qos1 round-trip still works
        pub.publish("flood/x", b"after-resume", qos=1, timeout=30)
        pub.disconnect()


def test_packets_before_connect_drop_connection():
    """Spec §3.1: first packet must be CONNECT — a pre-CONNECT SUBSCRIBE
    must not leak topic-tree state under a None client id."""
    from iotml.mqtt.wire import subscribe_packet

    broker = MqttBroker()
    with MqttEventServer(broker) as srv:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        s.sendall(subscribe_packet(1, [("a/#", 0)]))
        s.settimeout(5)
        assert s.recv(16) == b"", "server must close on pre-CONNECT packet"
        s.close()
    assert broker._tree.filters_of(None) == [] if hasattr(
        broker._tree, "filters_of") else True


def test_stalled_backpressure_evicts_slowest_consumer():
    """Overload-protection escape: when the paused backlog never drains
    (stalled consumers all under max_outbuf), the slowest consumer is
    evicted after stall_timeout_s and publishers resume — the system must
    not wedge forever."""
    from iotml.mqtt.wire import subscribe_packet

    broker = MqttBroker()
    with MqttEventServer(broker, max_outbuf=64 << 20,
                         high_watermark=1 << 20,
                         low_watermark=256 * 1024,
                         stall_timeout_s=1.0) as srv:
        sub = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sub.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        sub.settimeout(10)
        sub.connect(("127.0.0.1", srv.port))
        sub.sendall(connect_packet("stalled"))
        buf = b""
        while len(buf) < 4:
            buf += sub.recv(4 - len(buf))
        sub.sendall(subscribe_packet(1, [("flood/#", 0)]))
        time.sleep(0.2)

        pub = MqttClient("127.0.0.1", srv.port, "pub")
        payload = b"z" * 16384

        def flood():
            try:
                for _ in range(1200):  # ~20 MB, enough to trip the pause
                    pub.publish("flood/x", payload, qos=0)
            except OSError:
                pass

        t = threading.Thread(target=flood, daemon=True)
        t.start()
        deadline = time.time() + 30
        while srv.paused_count == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert srv.paused_count > 0
        # nobody drains the sub; after stall_timeout_s it must be evicted
        # and the flood must complete
        t.join(timeout=60)
        assert not t.is_alive(), "publisher stayed wedged past the timeout"
        deadline = time.time() + 10
        while "stalled" in broker.session_ids() and time.time() < deadline:
            time.sleep(0.05)
        assert "stalled" not in broker.session_ids()
        pub.publish("flood/x", b"alive", qos=1)
        pub.disconnect()
        sub.close()
