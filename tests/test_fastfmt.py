"""fastfmt must be byte-identical to np.array2string — always.

The payload format is a wire contract (reference OutputCallback payloads,
cardata-v3.py:247); a single divergent byte breaks downstream consumers.
These tests hammer the fast path with the shapes the scorer produces and
with adversarial inputs that must trigger the numpy fallback."""

import numpy as np
import pytest

from iotml.serve.fastfmt import format_rows


def _check(rows):
    got = format_rows(rows)
    want = [np.array2string(r) for r in rows]
    for g, w, r in zip(got, want, rows):
        assert g == w, f"mismatch for {r!r}:\n fast={g!r}\n  np ={w!r}"


def test_typical_prediction_rows():
    rng = np.random.default_rng(0)
    _check(rng.uniform(-1, 1, (500, 18)).astype(np.float32))


def test_relu_outputs_with_exact_zeros():
    rng = np.random.default_rng(1)
    x = np.maximum(rng.normal(size=(200, 18)), 0.0).astype(np.float32)
    _check(x)


def test_wide_and_narrow_rows():
    rng = np.random.default_rng(2)
    for f in (1, 2, 5, 30, 64):
        _check(rng.uniform(-5, 5, (50, f)).astype(np.float32))


def test_exponential_trigger_rows_fall_back():
    rng = np.random.default_rng(3)
    x = rng.uniform(-1, 1, (50, 18)).astype(np.float32)
    x[::7, 3] = 3e-05          # tiny → exp format
    x[::11, 5] = 2.5e9         # huge → exp format
    x[::13, 7] = 1.0           # ratio trigger rows
    x[::13, 8] = 2000.0
    _check(x)


def test_nonfinite_rows_fall_back():
    x = np.ones((8, 6), np.float32)
    x[1, 2] = np.nan
    x[3, 4] = np.inf
    x[5, 0] = -np.inf
    _check(x)


def test_adversarial_magnitudes():
    rng = np.random.default_rng(4)
    vals = np.array([0.0, 1e-4, 9.9e-5, 1e8 - 1, 1e8, -0.5, 123.456,
                     0.1, 1/3, 2/3, 1e3, 999.0, 1001.0], np.float64)
    for _ in range(50):
        row = rng.choice(vals, size=rng.integers(1, 20))
        _check(row[None, :])
    _check(vals[None, :].astype(np.float32))


def test_float64_rows():
    rng = np.random.default_rng(5)
    _check(rng.normal(size=(100, 12)))


def test_non_default_printoptions_fall_back():
    rng = np.random.default_rng(6)
    x = rng.uniform(-1, 1, (5, 8)).astype(np.float32)
    with np.printoptions(precision=3):
        _check(x)


def test_integer_valued_floats():
    _check(np.array([[0.0, 1.0, 2.0, 100.0, -3.0]], np.float32))
    _check(np.zeros((3, 18), np.float32))


def test_fast_path_is_actually_fast():
    import time

    rng = np.random.default_rng(7)
    rows = rng.uniform(-1, 1, (2000, 18)).astype(np.float32)
    t0 = time.perf_counter()
    format_rows(rows)
    fast = time.perf_counter() - t0
    t0 = time.perf_counter()
    [np.array2string(r) for r in rows]
    base = time.perf_counter() - t0
    assert fast < base, f"fast path slower than numpy: {fast} vs {base}"


# ---- native whole-batch formatter (fmt_engine.cc) -----------------------


def _native_active():
    from iotml.stream.native import available
    return available()


@pytest.mark.skipif(not _native_active(), reason="native engine unavailable")
class TestNativeFormatter:
    def test_native_path_engages(self):
        from iotml.serve.fastfmt import _format_rows_native
        rows = np.array([[1.25, 2.5]], np.float32)
        out = _format_rows_native(rows)
        assert out is not None
        assert out[0] == np.array2string(rows[0])

    def test_decimal_tie_rounding(self):
        # dyadic rationals whose decimal expansion terminates with a '5'
        # exactly at fractional digit 9: the 8-digit cutoff is an exact
        # tie, resolved to-even over the exact value (dragon4 semantics)
        ties = np.array([[1 / 512, 3 / 512, 5 / 512, 255 / 512],
                        [7 / 512, 9 / 512, 11 / 512, 201 / 512]])
        _check(ties)                    # float64
        _check(ties.astype(np.float32))

    def test_float32_vs_float64_precision(self):
        # dragon4 runs at array dtype precision: f32 rows must use f32
        # shortest-repr digits (1 + f32-ulp is "1.0000001", not the f64
        # expansion "1.00000012")
        v32 = np.nextafter(np.float32(1.0), np.float32(2.0))
        _check(np.array([[v32, np.float32(0.1)]], np.float32))
        v64 = np.nextafter(1.0, 2.0)
        _check(np.array([[v64, 0.1]]))

    def test_negative_zero_and_integers(self):
        _check(np.array([[-0.0, 0.0, 1.0, -100.0, 25.0, 1e7]]))
        _check(np.array([[-0.0, 0.0, 1.0, -100.0]], np.float32))

    def test_eligibility_boundaries(self):
        # values straddling every exponential-trigger bound, per row
        _check(np.array([[9.9999999e7, 12345.0]]))      # just under 1e8
        _check(np.array([[1.00000001e8, 12345.0]]))     # just over → exp
        _check(np.array([[1.0e-4, 0.002]]))             # at the tiny bound
        _check(np.array([[0.99999e-4, 0.002]]))         # below → exp
        _check(np.array([[1.0, 999.99]]))               # ratio just under
        _check(np.array([[1.0, 1000.01]]))              # ratio over → exp

    def test_wrap_assembly_long_rows(self):
        rng = np.random.default_rng(7)
        for f in (18, 19, 29, 30, 31, 60, 100, 200):
            _check(rng.uniform(-9.99, 9.99, (20, f)).astype(np.float32))
            _check(rng.uniform(-9.99, 9.99, (8, f)))

    def test_random_fuzz_against_numpy(self):
        rng = np.random.default_rng(11)
        for _ in range(20):
            scale = 10.0 ** rng.integers(-3, 7)
            rows = (rng.normal(size=(40, 18)) * scale)
            _check(rows.astype(np.float32))
            _check(rows)

    def test_mixed_fallback_and_native_rows(self):
        rng = np.random.default_rng(13)
        x = rng.uniform(-1, 1, (60, 12)).astype(np.float32)
        x[5, 0] = np.nan
        x[17, 3] = np.inf
        x[23, 7] = 5e9
        x[31, 2] = 1e-6
        _check(x)
