"""Pallas fused-fit kernel vs the scanned reference implementation.

The fused kernel hand-writes forward+backward+Adam for the reference
autoencoder (cardata-v3.py:176-194 semantics: L1 *activity* regularizer,
masked MSE, Keras 'accuracy'); these tests pin it to `make_scanned_fit`
(autodiff + optax) step by step.  On CPU the kernel runs in interpret mode
— same code path the TPU executes, minus Mosaic lowering."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from iotml.models.autoencoder import (CAR_AUTOENCODER,
                                      CREDITCARD_AUTOENCODER)
from iotml.ops.fused_train import fused_fit, supported
from iotml.train.loop import TrainState, Trainer, make_scanned_fit


def _data(S=6, B=32, F=18, seed=0, ragged=True):
    rng = np.random.default_rng(seed)
    xs = rng.uniform(-1, 1, (S, B, F)).astype(np.float32)
    masks = np.ones((S, B), np.float32)
    if ragged:
        masks[-1, B // 2:] = 0.0  # short final batch, like a real stream tail
        xs[-1, B // 2:] = 0.0
    return xs, masks


@pytest.mark.parametrize("model,F", [(CAR_AUTOENCODER, 18),
                                     (CREDITCARD_AUTOENCODER, 30)])
def test_fused_matches_scanned_losses_and_params(model, F):
    xs, masks = _data(F=F)
    s1 = TrainState.create(model, jax.random.PRNGKey(0), xs[0])
    scanned = make_scanned_fit(model, s1.tx)
    ref_state, (ref_losses, ref_accs) = scanned(
        s1, jnp.asarray(xs), jnp.asarray(xs), jnp.asarray(masks), 4)

    s2 = TrainState.create(model, jax.random.PRNGKey(0), xs[0])
    assert supported(s2, supervised=False)
    new_state, losses, accs = fused_fit(s2, xs, masks, epochs=4)

    np.testing.assert_allclose(np.asarray(losses), np.asarray(ref_losses),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(accs), np.asarray(ref_accs),
                               rtol=2e-4, atol=1e-6)
    for layer in ("encoder0", "encoder1", "decoder0", "decoder1"):
        for leaf in ("kernel", "bias"):
            np.testing.assert_allclose(
                np.asarray(new_state.params[layer][leaf]),
                np.asarray(ref_state.params[layer][leaf]),
                rtol=5e-3, atol=2e-5)
    assert int(new_state.step) == int(ref_state.step) == 24
    assert int(new_state.opt_state[0].count) == 24


def test_fused_resumes_with_bias_correction_continuity():
    """Two fused calls of 2 epochs == one call of 4: Adam's t counter (and
    the bias correction) must continue, not restart."""
    xs, masks = _data(ragged=False)
    s1 = TrainState.create(CAR_AUTOENCODER, jax.random.PRNGKey(0), xs[0])
    s_once, losses_once, _ = fused_fit(s1, xs, masks, epochs=4)

    s2 = TrainState.create(CAR_AUTOENCODER, jax.random.PRNGKey(0), xs[0])
    s2, l_a, _ = fused_fit(s2, xs, masks, epochs=2)
    s2, l_b, _ = fused_fit(s2, xs, masks, epochs=2)
    np.testing.assert_allclose(
        np.concatenate([np.asarray(l_a), np.asarray(l_b)]),
        np.asarray(losses_once), rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(s2.params["encoder0"]["kernel"]),
        np.asarray(s_once.params["encoder0"]["kernel"]),
        rtol=1e-3, atol=1e-6)


def test_supported_rejects_other_contracts():
    xs, _ = _data()
    st = TrainState.create(CAR_AUTOENCODER, jax.random.PRNGKey(0), xs[0],
                           tx=optax.sgd(1e-2))
    assert not supported(st, supervised=False)  # no adam state
    st2 = TrainState.create(CAR_AUTOENCODER, jax.random.PRNGKey(0), xs[0])
    assert not supported(st2, supervised=True)


def test_trainer_fit_compiled_auto_uses_fused_path():
    """fit_compiled(fused='auto') must agree with fused='never' on the same
    stream — the integration seam the bench rides."""
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer

    broker = Broker()
    FleetGenerator(FleetScenario(num_cars=50, failure_rate=0.02)).publish(
        broker, "T", n_ticks=20)

    def history(fused):
        consumer = StreamConsumer(broker, ["T:0:0"], group=f"g-{fused}")
        batches = SensorBatches(consumer, batch_size=100, only_normal=True)
        tr = Trainer(CAR_AUTOENCODER)
        return tr.fit_compiled(batches, epochs=3, fused=fused)

    h_auto = history("auto")
    h_scan = history("never")
    np.testing.assert_allclose(h_auto["loss"], h_scan["loss"],
                               rtol=2e-4, atol=1e-6)
    assert h_auto["records"] == h_scan["records"]
    # and loss went down
    assert h_auto["loss"][-1] < h_auto["loss"][0]


def test_fused_always_raises_when_unsupported():
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer

    broker = Broker()
    FleetGenerator(FleetScenario(num_cars=10, failure_rate=0.0)).publish(
        broker, "T", n_ticks=10)
    consumer = StreamConsumer(broker, ["T:0:0"])
    batches = SensorBatches(consumer, batch_size=50)
    tr = Trainer(CAR_AUTOENCODER, tx=optax.sgd(1e-2))
    with pytest.raises(ValueError):
        tr.fit_compiled(batches, epochs=1, fused="always")


def test_fused_respects_custom_activity_l1():
    """Trainer must forward the model's activity_l1 into the fused kernel —
    a model with a non-default regularizer has a visibly different loss."""
    from iotml.models.autoencoder import DenseAutoencoder

    xs, masks = _data(ragged=False)
    strong = DenseAutoencoder(input_dim=18, activity_l1=1e-1)
    s1 = TrainState.create(strong, jax.random.PRNGKey(0), xs[0])
    scanned = make_scanned_fit(strong, s1.tx)
    _, (ref_losses, _) = scanned(s1, jnp.asarray(xs), jnp.asarray(xs),
                                 jnp.asarray(masks), 2)

    from iotml.data.dataset import Batch
    tr = Trainer(strong)
    bs = [Batch(x=xs[i], n_valid=xs.shape[1], first_index=i)
          for i in range(xs.shape[0])]
    h = tr.fit_compiled(bs, epochs=2, fused="always")
    np.testing.assert_allclose(h["loss"], np.asarray(ref_losses),
                               rtol=2e-4, atol=1e-6)


def test_auto_falls_back_to_scan_for_large_slices():
    """The fused kernel is VMEM-resident; auto mode must gate on data size
    and quietly use the scanned fit for big slices."""
    from unittest import mock

    from iotml.data.dataset import Batch
    from iotml.ops import fused_train

    xs, _ = _data(S=4, B=64, ragged=False)
    bs = [Batch(x=xs[i], n_valid=xs.shape[1], first_index=i)
          for i in range(xs.shape[0])]
    tr = Trainer(CAR_AUTOENCODER)
    with mock.patch.object(fused_train, "fused_fit",
                           side_effect=AssertionError("fused used")):
        with mock.patch.object(fused_train, "VMEM_DATA_BUDGET_BYTES", 1):
            h = tr.fit_compiled(bs, epochs=1)  # falls back, no AssertionError
    assert len(h["loss"]) == 1
    with pytest.raises(ValueError):
        with mock.patch.object(fused_train, "VMEM_DATA_BUDGET_BYTES", 1):
            tr.fit_compiled(bs, epochs=1, fused="always")


def test_fused_matches_autodiff_with_fractional_masks():
    """ADVICE r1: the hand-derived backward carries the mask factor, so the
    fused fit stays exact for fractional sample weights, not just 0/1."""
    xs, masks = _data(S=4)
    rng = np.random.default_rng(7)
    masks = (masks * rng.uniform(0.25, 1.0, masks.shape)).astype(np.float32)

    s1 = TrainState.create(CAR_AUTOENCODER, jax.random.PRNGKey(0), xs[0])
    scanned = make_scanned_fit(CAR_AUTOENCODER, s1.tx)
    ref_state, (ref_losses, _) = scanned(
        s1, jnp.asarray(xs), jnp.asarray(xs), jnp.asarray(masks), 3)

    s2 = TrainState.create(CAR_AUTOENCODER, jax.random.PRNGKey(0), xs[0])
    new_state, losses, _ = fused_fit(s2, xs, masks, epochs=3)
    np.testing.assert_allclose(np.asarray(losses), np.asarray(ref_losses),
                               rtol=2e-4, atol=1e-6)
    for layer in ("encoder0", "encoder1", "decoder0", "decoder1"):
        for leaf in ("kernel", "bias"):
            np.testing.assert_allclose(
                np.asarray(new_state.params[layer][leaf]),
                np.asarray(ref_state.params[layer][leaf]),
                rtol=5e-3, atol=2e-5)
