"""iotml.gateway — sharded scatter-gather twin serving (ISSUE 20):
key→partition→shard policy, shard ownership + 421 fencing, the smart
client (point / batch / fan-out / feature-join), the dumb-client
router REST surface, standby byte-equality across compaction and
failover, the REST serving disciplines (per-request metrics, bounded
concurrency, named handler threads, crash-shaped kill), connect /twin
pagination, and the federated multi-front fleet."""

import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.gateway import (FrontProcess, GatewayClient, GatewayCluster,
                           GatewayError, GatewayRouter, front_for,
                           partition_for_key, run_federated_fleet,
                           shard_for_key)
from iotml.store import StorePolicy
from iotml.stream.broker import Broker
from iotml.twin import CHANGELOG_TOPIC, TwinFeatureStore, TwinService
from iotml.utils.rest import (RestServer, rest_request_seconds,
                              rest_requests)

IN = "SENSOR_DATA_S_AVRO"
F = len(KSQL_CAR_SCHEMA.sensor_fields)


def _publish(broker, n_ticks=6, cars=8, seed=5, partitions=4):
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    gen = FleetGenerator(FleetScenario(num_cars=cars, seed=seed,
                                       failure_rate=0.2))
    return gen.publish(broker, IN, n_ticks=n_ticks, partitions=partitions)


def _await(cond, timeout_s=20.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while not cond():
        if time.monotonic() >= deadline:
            raise TimeoutError(f"{what} not reached in {timeout_s}s")
        time.sleep(0.02)


# --------------------------------------------------------- pure policy
def test_partition_policy_matches_broker_keyed_produce():
    """partition_for_key IS the broker's keyed partitioner: a record
    produced by key lands exactly where the gateway computes it will."""
    b = Broker()
    b.create_topic("t", partitions=4)
    keys = [f"car_{i}" for i in range(32)]
    for k in keys:
        b.produce("t", b"v", key=k.encode())
    for k in keys:
        p = partition_for_key(k, 4)
        assert any(m.key == k.encode()
                   for m in b.fetch("t", p, 0, 1 << 20))
    # shard policy composes: partition % n_shards, stable for str/bytes
    for k in keys:
        assert shard_for_key(k, 4, 2) == partition_for_key(k, 4) % 2
        assert partition_for_key(k.encode(), 4) == partition_for_key(k, 4)


def test_front_for_is_consistent_and_total():
    ids = [f"car_{i}" for i in range(100)]
    assign = [front_for(c, 3) for c in ids]
    assert assign == [front_for(c, 3) for c in ids]  # pure
    assert set(assign) == {0, 1, 2}  # every front gets cars
    assert all(0 <= a < 3 for a in assign)


# --------------------------------------------------- shards + ownership
def test_shard_ownership_info_and_421_fencing():
    b = Broker()
    b.create_topic(IN, partitions=4)
    _publish(b)
    cluster = GatewayCluster(b, n_shards=2, standbys=False).start()
    try:
        client = GatewayClient(cluster)
        _await(lambda: client.count() == 8, what="shards drained")
        infos = [json.loads(urllib.request.urlopen(
            f"{s.url}/shard/info", timeout=5).read())
            for s in cluster.shards]
        assert infos[0]["partitions"] == [0, 2]
        assert infos[1]["partitions"] == [1, 3]
        assert sum(i["count"] for i in infos) == 8
        # a direct hit on the WRONG shard is fenced with 421, never an
        # answer — the smart client's refresh-and-retry cue
        car = next(c for c in client.cars() if client.shard_of(c) == 0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{cluster.shards[1].url}/shard/twin/{car}", timeout=5)
        assert ei.value.code == 421
        client.close()
    finally:
        cluster.stop()


def test_gateway_client_point_batch_and_fanout_queries():
    b = Broker()
    b.create_topic(IN, partitions=4)
    published = _publish(b)
    # reference answers come from a single unsharded read-only tap
    ref = TwinService(b, group="gw-test-ref", changelog=False)
    while ref.pump_once():
        pass
    cluster = GatewayCluster(b, n_shards=2, standbys=False).start()
    client = GatewayClient(cluster)
    try:
        _await(lambda: client.aggregate()["records"] == published,
               what="shards drained")
        cars = client.cars()
        assert cars == ref.cars() and len(cars) == 8
        # point lookups route by key hash and agree with the tap
        for car in cars:
            doc = client.get(car)
            assert doc == ref.get(car)
        assert client.get("no-such-car") is None
        # batched lookups: slim docs in request order, None = unknown
        got = client.mget(cars + ["ghost"])
        assert got[-1] is None
        for car, slim in zip(cars, got):
            full = ref.get(car)
            assert slim["car"] == car
            assert slim["offset"] == full["offset"]
            assert slim["ts"] == full["timestamp_ms"]
            assert slim["count"] > 0
            assert slim["partition"] == partition_for_key(car, 4)
        # fan-out merges equal the unsharded fold
        assert client.count() == ref.count()
        agg = client.aggregate()
        assert agg["records"] == published
        assert agg["cars"] == 8
        # pagination through the client fan-out
        assert client.cars(limit=3) == cars[:3]
        assert client.cars(limit=3, offset=6) == cars[6:]
        # retire travels to the owning shard; the car is gone fleet-wide
        assert client.retire(cars[0]) and client.get(cars[0]) is None
        assert not client.retire(cars[0])
    finally:
        client.close()
        cluster.stop()


def test_gateway_client_duck_types_feature_store():
    """StreamScorer(feature_store=client): matrix/vector/dim through
    the sharded plane match the local TwinFeatureStore join."""
    b = Broker()
    b.create_topic(IN, partitions=4)
    _publish(b)
    # same group label as the other test's tap: consumer group is a
    # watermark-series dimension, and the suite-wide registry pins a
    # cardinality bound — taps with identical topic/partition coverage
    # share one frontier name instead of minting new series
    ref = TwinService(b, group="gw-test-ref", changelog=False)
    while ref.pump_once():
        pass
    fs = TwinFeatureStore(ref)
    cluster = GatewayCluster(b, n_shards=2, standbys=False).start()
    client = GatewayClient(cluster)
    try:
        _await(lambda: client.count() == 8, what="shards drained")
        assert client.dim == fs.dim
        keys = [c.encode() for c in ref.cars()] + [None, b"ghost"]
        n = len(keys) + 2  # padding rows
        local = fs.matrix(keys, n)
        remote = client.matrix(keys, n)
        assert remote.shape == (n, fs.dim)
        assert np.allclose(remote, local, atol=1e-6)
        assert remote[:8].any() and not remote[8:].any()
        v = client.vector(keys[0])
        assert np.allclose(v, fs.vector(keys[0]), atol=1e-6)
    finally:
        client.close()
        cluster.stop()


# ------------------------------------------------------------- router
def test_gateway_router_rest_surface():
    b = Broker()
    b.create_topic(IN, partitions=4)
    _publish(b)
    cluster = GatewayCluster(b, n_shards=2, standbys=False).start()
    client = GatewayClient(cluster)
    rest = RestServer(name="iotml-gw-router-test")
    GatewayRouter(cluster, client).mount(rest)
    rest.start()
    try:
        _await(lambda: client.count() == 8, what="shards drained")
        # the routing map smart clients bootstrap from
        mp = json.loads(urllib.request.urlopen(
            f"{rest.url}/gateway/map", timeout=5).read())
        assert mp["n_shards"] == 2 and mp["n_partitions"] == 4
        assert [s["shard"] for s in mp["shards"]] == [0, 1]
        assert all(s["url"].startswith("http://") for s in mp["shards"])
        # a second smart client bootstraps from the URL, not the object
        remote = GatewayClient(rest.url)
        cars = remote.cars()
        assert len(cars) == 8
        remote.close()
        # GET /twin pagination fans out and merges
        page = json.loads(urllib.request.urlopen(
            f"{rest.url}/twin?limit=3", timeout=5).read())
        assert page["count"] == 8 and page["cars"] == cars[:3]
        assert page["next_offset"] == 3
        last = json.loads(urllib.request.urlopen(
            f"{rest.url}/twin?limit=5&offset=3", timeout=5).read())
        assert last["cars"] == cars[3:] and last["next_offset"] is None
        fast = json.loads(urllib.request.urlopen(
            f"{rest.url}/twin?count_only=1", timeout=5).read())
        assert fast == {"count": 8}
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{rest.url}/twin?limit=x", timeout=5)
        assert ei.value.code == 400
        # proxied point lookup + batched dumb-client mget
        doc = json.loads(urllib.request.urlopen(
            f"{rest.url}/twin/{cars[0]}", timeout=5).read())
        assert doc["car"] == cars[0] and "aggregates" in doc
        req = urllib.request.Request(
            f"{rest.url}/gateway/mget",
            data=json.dumps({"keys": [cars[0], "ghost"]}).encode(),
            headers={"Content-Type": "application/json"})
        got = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert got["docs"][0]["car"] == cars[0]
        assert got["docs"][1] is None
        agg = json.loads(urllib.request.urlopen(
            f"{rest.url}/gateway/aggregate", timeout=5).read())
        assert agg["cars"] == 8
        # proxied retire
        req = urllib.request.Request(f"{rest.url}/twin/{cars[0]}",
                                     method="DELETE")
        assert urllib.request.urlopen(req, timeout=5).status == 204
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{rest.url}/twin/{cars[0]}",
                                   timeout=5)
    finally:
        rest.stop()
        client.close()
        cluster.stop()


# --------------------------------- standbys: rebalance + failover (S3)
def test_standby_byte_identical_across_compaction_and_failover(tmp_path):
    """TwinService(partitions=...) under live rebalance: each shard's
    warm standby rebuilds byte-for-byte equal to its primary across a
    compaction pass, and a killed shard's standby promotes into a
    primary serving the exact pre-kill state."""
    b = Broker(store_dir=str(tmp_path),
               store_policy=StorePolicy(fsync="never",
                                        segment_bytes=8 * 1024,
                                        compact_grace_ms=10 ** 9))
    b.create_topic(IN, partitions=4)
    cluster = GatewayCluster(b, n_shards=2).start()
    client = GatewayClient(cluster)
    published = 0
    try:
        # tick-by-tick with drain barriers: every tick re-emits each
        # car's changelog record, so compaction has versions to fold
        for _ in range(4):
            published += _publish(b, n_ticks=1)
            _await(lambda: client.aggregate()["records"] == published,
                   what="shards drained")
        _await(lambda: all(s.lag() == 0
                           for s in cluster.standbys.values()),
               what="standby catch-up")
        # force a compaction pass over the changelog, then more traffic:
        # the standby replays the COMPACTED form + the live tail and
        # must still land on identical bytes
        for p in range(4):
            b.store.log_for(CHANGELOG_TOPIC, p).roll()
        stats = b.run_compaction(force=True)
        assert sum(s.records_removed for s in stats.values()) > 0
        published += _publish(b, n_ticks=2)
        _await(lambda: client.aggregate()["records"] == published,
               what="post-compaction drain")
        _await(lambda: all(s.lag() == 0
                           for s in cluster.standbys.values()),
               what="post-compaction standby catch-up")
        for shard in cluster.shards:
            assert (cluster.standbys[shard.shard_id].table.snapshot()
                    == shard.service.table.snapshot())
        # failover: kill shard 0, promote its standby, exact state
        pre_kill = cluster.shards[0].service.table.snapshot()
        pre_cars = [c for c in client.cars() if client.shard_of(c) == 0]
        cluster.kill_shard(0)
        promote_s = cluster.promote(0)
        assert promote_s < GatewayCluster.PROMOTE_SLO_S
        assert cluster.shards[0].service.table.snapshot() == pre_kill
        client.refresh()
        for car in pre_cars:
            assert client.get(car)["car"] == car
        assert client.aggregate()["records"] == published
        # the promoted primary is shadowed by a FRESH standby
        _await(lambda: cluster.standbys[0].lag() == 0,
               what="fresh standby catch-up")
        assert (cluster.standbys[0].table.snapshot()
                == cluster.shards[0].service.table.snapshot())
    finally:
        client.close()
        cluster.stop()
        b.close()


def test_client_survives_shard_kill_mid_queries():
    """A client holding persistent connections observes the kill as a
    connection error (never a zombie answer) and retries onto the
    promoted shard within its deadline."""
    b = Broker()
    b.create_topic(IN, partitions=4)
    _publish(b)
    cluster = GatewayCluster(b, n_shards=2).start()
    client = GatewayClient(cluster, retry_deadline_s=10.0)
    try:
        _await(lambda: client.count() == 8, what="shards drained")
        cars0 = [c for c in client.cars() if client.shard_of(c) == 0]
        assert client.get(cars0[0])["car"] == cars0[0]  # conn warm
        _await(lambda: cluster.standbys[0].lag() == 0,
               what="standby catch-up")
        cluster.kill_shard(0)
        cluster.promote(0)
        # same client object, same keys: answered by the new primary
        for car in cars0:
            assert client.get(car)["car"] == car
        assert client.refreshes >= 2  # the retry path actually ran
    finally:
        client.close()
        cluster.stop()


# ------------------------------------------- REST serving disciplines
def test_rest_per_request_metrics():
    srv = RestServer(name="iotml-rest-mtest")
    srv.route("GET", r"/ping", lambda m, body: (200, {"pong": True}))
    srv.start()
    try:
        base_ok = rest_requests.value(route=r"/ping", code=200)
        base_404 = rest_requests.value(route="(unmatched)", code=404)
        for _ in range(3):
            urllib.request.urlopen(f"{srv.url}/ping", timeout=5).read()
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{srv.url}/nope", timeout=5)
        # the counters land in a `finally` AFTER the response bytes are
        # written — the client can observe the reply before the handler
        # thread is rescheduled, so await rather than assert instantly
        _await(lambda: rest_requests.value(route=r"/ping", code=200)
               == base_ok + 3, timeout_s=5.0, what="ping counter")
        _await(lambda: rest_requests.value(route="(unmatched)", code=404)
               == base_404 + 1, timeout_s=5.0, what="404 counter")
        # the latency series is keyed by the registered PATTERN (a
        # closed set), never by the concrete path
        assert 'route="/ping"' in rest_request_seconds.render()
    finally:
        srv.stop()


def test_rest_concurrency_guard_sheds_with_503():
    srv = RestServer(name="iotml-rest-gtest", max_concurrency=2)
    srv.route("GET", r"/ping", lambda m, body: (200, {"pong": True}))
    srv.start()
    held = []
    try:
        base = rest_requests.value(route="(guard)", code=503)
        # two keep-alive connections occupy both slots (the guard
        # bounds CONNECTIONS — each holds its handler thread)
        for _ in range(2):
            c = http.client.HTTPConnection(srv.host, srv.port, timeout=5)
            c.request("GET", "/ping")
            assert c.getresponse().read() == b'{"pong": true}'
            held.append(c)
        _await(lambda: srv.active_connections() == 2,
               what="both slots held")
        # handler threads are daemon, named and discoverable (R8)
        names = [t.name for t in threading.enumerate()
                 if t.name.startswith("iotml-rest-gtest-h")]
        assert len(names) == 2
        # the third connection is shed with a raw 503 BEFORE a handler
        # thread exists, and told not to retry on this socket
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{srv.url}/ping", timeout=5)
        assert ei.value.code == 503
        assert ei.value.headers["Connection"] == "close"
        assert rest_requests.value(route="(guard)", code=503) == base + 1
        # freeing a slot readmits new connections
        held.pop().close()
        _await(lambda: srv.active_connections() == 1,
               what="slot released")
        doc = json.loads(urllib.request.urlopen(
            f"{srv.url}/ping", timeout=5).read())
        assert doc == {"pong": True}
    finally:
        for c in held:
            c.close()
        srv.stop()


def test_rest_max_concurrency_env(monkeypatch):
    monkeypatch.setenv("IOTML_REST_MAX_CONCURRENCY", "7")
    srv = RestServer(name="iotml-rest-env")
    assert srv.max_concurrency == 7
    srv.httpd.server_close()
    monkeypatch.setenv("IOTML_REST_MAX_CONCURRENCY", "zero")
    with pytest.raises(ValueError, match="not an integer"):
        RestServer(name="iotml-rest-env2")
    monkeypatch.setenv("IOTML_REST_MAX_CONCURRENCY", "0")
    with pytest.raises(ValueError, match=">= 1"):
        RestServer(name="iotml-rest-env3")


def test_rest_kill_severs_established_keepalive():
    """kill() must look like a crash to clients on persistent
    connections: shutdown() alone leaves handler threads answering on
    old sockets — a zombie serving stale state is a WRONG answer."""
    srv = RestServer(name="iotml-rest-ktest")
    srv.route("GET", r"/ping", lambda m, body: (200, {"pong": True}))
    srv.start()
    conn = http.client.HTTPConnection(srv.host, srv.port, timeout=5)
    try:
        conn.request("GET", "/ping")
        assert conn.getresponse().read() == b'{"pong": true}'
        srv.kill()
        with pytest.raises((OSError, http.client.HTTPException)):
            conn.request("GET", "/ping")
            conn.getresponse()
    finally:
        conn.close()


# --------------------------------------- connect /twin pagination (S1)
def test_connect_twin_listing_paginates():
    from iotml.connect import ConnectServer, ConnectWorker

    b = Broker()
    b.create_topic(IN, partitions=2)
    _publish(b, partitions=2)
    svc = TwinService(b)
    while svc.pump_once():
        pass
    srv = ConnectServer(ConnectWorker(b)).start()
    try:
        srv.attach_twin(svc)
        cars = svc.cars()
        # count_only fast path materialises no id list
        fast = json.loads(urllib.request.urlopen(
            f"{srv.url}/twin?count_only=true", timeout=5).read())
        assert fast["count"] == 8 and "cars" not in fast
        # page walk via next_offset reconstructs the full listing
        walked, offset = [], 0
        while offset is not None:
            page = json.loads(urllib.request.urlopen(
                f"{srv.url}/twin?limit=3&offset={offset}",
                timeout=5).read())
            assert len(page["cars"]) <= 3
            walked += page["cars"]
            offset = page["next_offset"]
        assert walked == cars
        # limit is clamped to the ceiling, never a megabyte id dump
        page = json.loads(urllib.request.urlopen(
            f"{srv.url}/twin?limit=999999", timeout=5).read())
        assert page["limit"] <= 10_000
        for bad in ("limit=x", "offset=-1"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{srv.url}/twin?{bad}", timeout=5)
            assert ei.value.code == 400
    finally:
        srv.stop()


# ---------------------------------------------------- federation (S0)
def test_topic_mapping_stream_key_validation():
    from iotml.mqtt.bridge import TopicMapping

    keyed = TopicMapping.sensor_data_keyed()
    assert keyed.stream_key == "car" and keyed.stream_topic == IN
    assert TopicMapping.sensor_data().stream_key == "topic"
    with pytest.raises(ValueError, match="stream_key"):
        TopicMapping(("a/#",), "t", stream_key="payload")


def test_publish_many_is_qos0_only():
    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.wire import MqttClient, MqttServer

    core = MqttBroker(name="iotml-test-front")
    srv = MqttServer(core, port=0)
    srv.start()
    try:
        cli = MqttClient("127.0.0.1", srv.port, "qos-test", keepalive=0)
        try:
            assert cli.publish_many([("t/a", b"x"), ("t/b", b"y")]) == 2
            with pytest.raises(ValueError, match="QoS 0"):
                cli.publish_many([("t/a", b"x")], qos=1)
        finally:
            cli.disconnect()
    finally:
        srv.shutdown()
        srv.server_close()


def test_federated_fleet_small_end_to_end():
    """Scaled-down ISSUE-20 acceptance: two real front PROCESSES over
    the wire protocol, one keyed stream, a sharded gateway answering
    for cars that entered through every front."""
    report = run_federated_fleet(cars=40, fronts=2, ticks=1, shards=2,
                                 partitions=4, probe_per_front=2,
                                 timeout_s=120.0)
    assert report["ok"], report
    assert report["published"] == 40
    assert report["folded"] == 40
    assert report["fleet_cars_served"] == 40
    assert report["per_front_lookups_ok"] == [True, True]


# ------------------------------------------------------------ the drill
def test_gateway_drill_smoke():
    from iotml.gateway.drill import run_gateway_drill

    report = run_gateway_drill(seed=11, records=600, cars=20)
    assert report.ok, [i.detail for i in report.invariants if not i.ok]
    assert report.storm_wrong == 0
    assert report.slos["promote_s"] < GatewayCluster.PROMOTE_SLO_S
