"""Consumer-group rebalance + elastic recovery: the scalable-Deployment
story the reference delegates to Kafka's coordinator (SURVEY §2.7, §5),
reproduced against the in-process broker."""

import pytest

from iotml.stream.broker import Broker
from iotml.stream.group import (GroupConsumer, GroupCoordinator,
                                range_assign, roundrobin_assign)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture
def broker():
    b = Broker()
    b.create_topic("sensor-data", partitions=10)
    for i in range(200):
        b.produce("sensor-data", f"r{i}".encode(), partition=i % 10)
    return b


def test_range_assignor_contiguous_and_balanced():
    a = range_assign(["m1", "m2", "m3"], {"t": 10})
    sizes = sorted(len(v) for v in a.values())
    assert sizes == [3, 3, 4]
    got = sorted(tp for v in a.values() for tp in v)
    assert got == [("t", p) for p in range(10)]
    # contiguity per member
    for parts in a.values():
        ps = [p for _, p in parts]
        assert ps == list(range(ps[0], ps[0] + len(ps)))


def test_roundrobin_assignor_interleaves_topics():
    a = roundrobin_assign(["m1", "m2"], {"t1": 3, "t2": 3})
    assert sorted(len(v) for v in a.values()) == [3, 3]
    got = sorted(tp for v in a.values() for tp in v)
    assert got == [("t1", 0), ("t1", 1), ("t1", 2),
                   ("t2", 0), ("t2", 1), ("t2", 2)]


def test_join_splits_partitions_and_generation_bumps(broker):
    coord = GroupCoordinator(broker, "g")
    c1 = GroupConsumer(coord, ["sensor-data"])
    assert len(c1.assignment) == 10
    g1 = coord.generation

    c2 = GroupConsumer(coord, ["sensor-data"])
    assert coord.generation > g1
    # c1 heals itself on next poll and the split covers all partitions
    c1.poll()
    assert len(c1.assignment) == 5 and len(c2.assignment) == 5
    assert sorted(c1.assignment + c2.assignment) == \
        [("sensor-data", p) for p in range(10)]


def test_all_records_consumed_across_members(broker):
    coord = GroupCoordinator(broker, "g")
    c1 = GroupConsumer(coord, ["sensor-data"])
    c2 = GroupConsumer(coord, ["sensor-data"])
    seen = set()
    for c in (c1, c2):
        while True:
            msgs = c.poll()
            if not msgs:
                break
            seen.update(m.value for m in msgs)
    assert len(seen) == 200


def test_graceful_leave_hands_partitions_to_survivor(broker):
    coord = GroupCoordinator(broker, "g")
    c1 = GroupConsumer(coord, ["sensor-data"])
    c2 = GroupConsumer(coord, ["sensor-data"])
    c1.poll()

    # c2 consumes some of its share, commits, leaves
    got = c2.poll(30)
    c2.commit()
    c2.close()

    # c1 inherits everything and resumes c2's partitions at the commit
    msgs = []
    while True:
        chunk = c1.poll()
        if not chunk:
            break
        msgs.extend(chunk)
    assert len(c1.assignment) == 10
    values = set(m.value for m in msgs) | set(m.value for m in got)
    assert len(values) == 200  # no gaps, no redelivery after clean handoff


def test_crash_triggers_session_timeout_and_redelivery(broker):
    clock = FakeClock()
    coord = GroupCoordinator(broker, "g", session_timeout_s=5.0, clock=clock)
    c1 = GroupConsumer(coord, ["sensor-data"])
    c2 = GroupConsumer(coord, ["sensor-data"])
    c1.poll()

    # c2 consumes 40 records but only commits after the first 20
    first = c2.poll(20)
    c2.commit()
    uncommitted = c2.poll(20)
    # ...and crashes: no leave(), no more heartbeats
    clock.t += 6.0

    # survivor's next poll expires the corpse and adopts its partitions
    msgs = list(c1.poll())
    assert c1.rebalances >= 1
    assert len(c1.assignment) == 10
    while True:
        chunk = c1.poll()
        if not chunk:
            break
        msgs.extend(chunk)
    survivor_values = set(m.value for m in msgs)
    # at-least-once: the 20 uncommitted records ARE redelivered
    assert set(m.value for m in uncommitted) <= survivor_values
    # nothing is lost: committed ∪ survivor = everything
    assert set(m.value for m in first) | survivor_values == \
        {f"r{i}".encode() for i in range(200)}


def test_scale_out_mid_stream_no_duplicates_with_commits(broker):
    coord = GroupCoordinator(broker, "g")
    c1 = GroupConsumer(coord, ["sensor-data"])
    part1 = c1.poll(50)
    c1.commit()

    c2 = GroupConsumer(coord, ["sensor-data"])  # scale-out
    rest = []
    for c in (c1, c2):
        while True:
            chunk = c.poll()
            if not chunk:
                break
            rest.extend(chunk)
    # with a commit before the rebalance, handoff introduces no duplicates
    all_msgs = part1 + rest
    assert len(all_msgs) == 200
    assert len(set(m.value for m in all_msgs)) == 200


def test_heartbeat_rejects_stale_generation(broker):
    coord = GroupCoordinator(broker, "g")
    m1, gen1, _ = coord.join(["sensor-data"])
    coord.join(["sensor-data"])  # second member bumps generation
    assert coord.heartbeat(m1, gen1) is False
    m1b, gen2, assigned = coord.join(["sensor-data"], m1)
    assert m1b == m1 and gen2 == coord.generation
    assert coord.heartbeat(m1, gen2) is True


def test_group_elastic_sensorbatches_pipeline():
    """End-to-end elasticity: two group members run SensorBatches over a
    partitioned framed-Avro sensor stream; one crashes mid-consume; the
    survivor adopts its partitions and the fleet's records all get through
    (at-least-once)."""
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    b = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=50, failure_rate=0.0))
    total = gen.publish(b, "SENSOR_DATA_S_AVRO", n_ticks=20, partitions=10)
    assert total == 1000

    clock = FakeClock()
    coord = GroupCoordinator(b, "scorers", session_timeout_s=5.0, clock=clock)
    c1 = GroupConsumer(coord, ["SENSOR_DATA_S_AVRO"])
    c2 = GroupConsumer(coord, ["SENSOR_DATA_S_AVRO"])
    c1.poll(1)  # heal after c2's join; drops the fetched record (redelivered)

    b1 = SensorBatches(c1, batch_size=100)
    b2 = SensorBatches(c2, batch_size=100)

    # c2 consumes one drain pass of its share, commits nothing, crashes
    crashed_rows = sum(batch.n_valid for batch in b2)
    assert crashed_rows > 0
    clock.t += 6.0  # session timeout expires the corpse

    survivor_rows = sum(batch.n_valid for batch in b1)
    c1.commit()
    # survivor saw everything c2 never committed
    assert survivor_rows == 1000
    assert len(c1.assignment) == 10
